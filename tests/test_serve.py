"""ctt-serve: persistent serving daemon tests.

Covers the submission/execution split end to end:

  * ExecutionContext: process singleton, explicit contexts through
    ``build()``, install() for long-lived hosts;
  * the durable job queue: priority claim order, first-writer-wins
    results, stale-lease requeue at gen+1 (daemon death recovery);
  * admission: queue-depth and per-tenant quota rejections (429 on the
    wire, ``serve.quota_rejections`` counter);
  * byte-identity: a daemon-submitted watershed produces chunk-for-chunk
    identical output to ``build()`` in a fresh process;
  * liveness: mid-job client disconnect survives, /metrics parses as
    OpenMetrics, ``obs watch`` renders the serve health line;
  * SIGTERM drain (subprocess): the in-flight job finishes, queued jobs
    stay durable, the heartbeat carries ``draining``, and a restarted
    daemon over the same state dir completes the leftovers.
"""

import hashlib
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from cluster_tools_tpu.obs import metrics as obs_metrics
from cluster_tools_tpu.obs import trace as obs_trace
from cluster_tools_tpu.runtime import ExecutionContext, build
from cluster_tools_tpu.serve import (
    JobQueue, QuotaRejected, ServeClient, ServeDaemon,
)
from cluster_tools_tpu.serve.client import read_endpoint
from cluster_tools_tpu.serve.admission import AdmissionController
from cluster_tools_tpu.serve.protocol import (
    ProtocolError, job_signature, resolve_workflow, validate_submission,
)
from cluster_tools_tpu.utils import file_reader

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WS_CONFIG = {
    "threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10,
    "halo": [2, 4, 4],
}


def _digest(root):
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def _ws_volume(seed=0, shape=(16, 32, 32)):
    from scipy import ndimage

    rng = np.random.default_rng(seed)
    raw = ndimage.gaussian_filter(rng.random(shape), (1.0, 2.0, 2.0))
    return (
        (raw - raw.min()) / (raw.max() - raw.min())
    ).astype("float32")


def _sleep_vol_job(td, tag, sleep_s, tenant="default", priority=0):
    """A submission payload for a calibrated-cost job (the ctt-steal
    skewed-cost fixture task, resolved by dotted path): one block, every
    block costs ``sleep_s``."""
    path = os.path.join(td, f"{tag}.n5")
    if not os.path.exists(path):
        file_reader(path).create_dataset(
            "x", data=np.ones((2, 8, 8), dtype="float32"), chunks=(2, 8, 8)
        )
    return {
        "workflow": "bench_e2e_lib:SkewedCostTask",
        "kwargs": {
            "tmp_folder": os.path.join(td, f"tmp_{tag}"),
            "config_dir": os.path.join(td, f"configs_{tag}"),
            "input_path": path, "input_key": "x",
            "output_path": path, "output_key": "y",
        },
        "configs": {
            "global": {"block_shape": [2, 8, 8]},
            "skewed_cost": {
                "hot_z_end": 0, "base_s": float(sleep_s), "hot_s": 99.0,
            },
        },
        "tenant": tenant,
        "priority": priority,
    }


@pytest.fixture
def daemon_factory(tmp_path):
    """In-process daemons with tracing scoped to this test (the daemon
    would otherwise flip the process-global trace switch on for the rest
    of the session)."""
    was_on = obs_trace.enabled()
    if not was_on:
        obs_trace.enable(str(tmp_path / "trace"), "serve_test",
                         export_env=False)
    daemons = []

    def make(state_dir, **conf):
        d = ServeDaemon(str(state_dir), config=conf)
        d.start()
        daemons.append(d)
        return d

    yield make
    for d in daemons:
        d.request_drain()
        if d._httpd is not None:
            d._httpd.shutdown()
            d._httpd.server_close()
        for t in d._threads:
            if t.name.startswith("ctt-serve-exec"):
                t.join(timeout=30)
    if not was_on:
        obs_trace.disable()


# --------------------------------------------------------------------------
# ExecutionContext


class TestExecutionContext:
    def test_process_context_singleton_idempotent(self):
        a = ExecutionContext.process_context()
        b = ExecutionContext.process_context()
        assert a is b
        assert a.activate() is a
        desc = a.describe()
        assert desc["activated"] and desc["pid"] == os.getpid()
        assert a.local_device_count() >= 1
        assert desc["chunk_cache_budget_bytes"] >= 0

    def test_install_makes_context_process_wide(self):
        prev = ExecutionContext.process_context()
        ctx = ExecutionContext(role="serve")
        try:
            assert ctx.install() is ctx
            assert ExecutionContext.process_context() is ctx
            assert ctx.describe()["role"] == "serve"
        finally:
            prev.install()

    def test_build_threads_explicit_context(self, tmp_path):
        from cluster_tools_tpu.runtime import config as cfg
        from cluster_tools_tpu.workflows import UniqueWorkflow

        path = str(tmp_path / "d.n5")
        rng = np.random.default_rng(0)
        file_reader(path).create_dataset(
            "seg", data=rng.integers(0, 9, (8, 16, 16)).astype(np.uint64),
            chunks=(4, 8, 8),
        )
        config_dir = str(tmp_path / "configs")
        cfg.write_global_config(config_dir, {"block_shape": [4, 8, 8]})
        ctx = ExecutionContext().activate()
        n0 = ctx.builds_executed
        wf = UniqueWorkflow(
            str(tmp_path / "tmp"), config_dir,
            input_path=path, input_key="seg",
            output_path=path, output_key="u",
        )
        assert build([wf], context=ctx)
        assert ctx.builds_executed == n0 + 1
        with file_reader(path, "r") as f:
            assert f["u"][:].size > 0


# --------------------------------------------------------------------------
# protocol


class TestProtocol:
    def test_validate_submission_normalizes_and_rejects(self):
        rec = validate_submission({
            "workflow": " WatershedWorkflow ",
            "kwargs": {"tmp_folder": "/t"},
        })
        assert rec["workflow"] == "WatershedWorkflow"
        assert rec["tenant"] == "default" and rec["priority"] == 0
        for bad in (
            [],                                        # not an object
            {},                                        # no workflow
            {"workflow": "X"},                         # no tmp_folder
            {"workflow": "X", "kwargs": {"tmp_folder": "/t"},
             "priority": "high"},                      # bad priority
            {"workflow": "X", "kwargs": {"tmp_folder": "/t"},
             "configs": {"global": {}}},               # configs, no dir
        ):
            with pytest.raises(ProtocolError):
                validate_submission(bad)

    def test_resolve_workflow_catalog_and_dotted(self):
        from cluster_tools_tpu.workflows import WatershedWorkflow

        assert resolve_workflow("WatershedWorkflow") is WatershedWorkflow
        cls = resolve_workflow("bench_e2e_lib:SkewedCostTask")
        assert cls.task_name == "skewed_cost"
        for bad in ("NoSuchWorkflow", "nope.nope:Missing",
                    "json:JSONDecoder"):
            with pytest.raises(ProtocolError):
                resolve_workflow(bad)

    def test_job_signature_keys_on_workflow_and_block_shape(self):
        a = job_signature({"workflow": "W",
                           "configs": {"global": {"block_shape": [4, 8, 8]}}})
        b = job_signature({"workflow": "W",
                           "configs": {"global": {"block_shape": [4, 8, 8]}}})
        c = job_signature({"workflow": "W",
                           "configs": {"global": {"block_shape": [8, 8, 8]}}})
        assert a == b and a != c


# --------------------------------------------------------------------------
# durable job queue


class TestJobQueue:
    def test_submit_claim_priority_order_and_states(self, tmp_path):
        q = JobQueue(str(tmp_path / "jobs"), lease_s=5.0)
        j1 = q.submit({"workflow": "A", "tenant": "t", "priority": 0})
        j2 = q.submit({"workflow": "B", "tenant": "t", "priority": 5})
        j3 = q.submit({"workflow": "C", "tenant": "t", "priority": 5})
        assert [j1, j2, j3] == ["j000001", "j000002", "j000003"]
        assert q.get(j1)["state"] == "queued"
        # claim order: priority desc, then submission sequence
        c = q.claim_next()
        assert c.job_id == j2 and c.gen == 0
        assert q.get(j2)["state"] == "running"
        assert q.claim_next().job_id == j3
        assert q.claim_next().job_id == j1
        assert q.claim_next() is None
        assert q.complete(c, {"ok": True, "seconds": 0.1})
        # first writer wins: a duplicate completion is a no-op
        assert not q.complete(c, {"ok": False, "seconds": 9.9})
        st = q.get(j2)
        assert st["state"] == "done" and st["result"]["ok"]
        stats = q.stats()
        assert stats["in_flight"] == 2 and stats["per_tenant"] == {"t": 2}

    def test_stale_lease_requeues_at_next_generation(self, tmp_path):
        was_on = obs_trace.enabled()
        if not was_on:
            obs_trace.enable(str(tmp_path / "trace"), "serve_unit",
                             export_env=False)
        try:
            q = JobQueue(str(tmp_path / "jobs"), lease_s=0.2)
            jid = q.submit({"workflow": "A", "tenant": "t", "priority": 0})
            claim = q.claim_next()
            assert claim.gen == 0
            # a second daemon sees a live lease: nothing claimable
            q2 = JobQueue(str(tmp_path / "jobs"), lease_s=0.2)
            assert q2.claim_next() is None
            # the owner dies: its lease stamp ages past 3 x lease_s
            lease = json.load(open(claim.lease_path))
            lease["wall"] -= 3600.0
            with open(claim.lease_path, "w") as f:
                json.dump(lease, f)
            before = obs_metrics.snapshot()["counters"]
            takeover = q2.claim_next()
            assert takeover is not None and takeover.job_id == jid
            assert takeover.gen == 1
            after = obs_metrics.snapshot()["counters"]
            assert after.get("serve.leases_requeued", 0) > before.get(
                "serve.leases_requeued", 0
            )
            assert q2.complete(takeover, {"ok": True, "seconds": 0.1})
            assert q2.get(jid)["state"] == "done"
        finally:
            if not was_on:
                obs_trace.disable()

    def test_renew_restamps_wall(self, tmp_path):
        q = JobQueue(str(tmp_path / "jobs"), lease_s=1.0)
        q.submit({"workflow": "A", "tenant": "t", "priority": 0})
        claim = q.claim_next()
        before = json.load(open(claim.lease_path))
        time.sleep(0.05)
        q.renew(claim)
        after = json.load(open(claim.lease_path))
        assert after["wall"] > before["wall"]
        assert after["claim_wall"] == pytest.approx(before["claim_wall"])


# --------------------------------------------------------------------------
# admission


class TestAdmission:
    def test_queue_depth_and_tenant_quota(self):
        adm = AdmissionController(
            max_queue_depth=3, tenant_quota=2, tenant_quotas={"big": 3}
        )
        ok, _ = adm.admit("a", {"in_flight": 0, "per_tenant": {}})
        assert ok
        ok, reason = adm.admit("a", {"in_flight": 3, "per_tenant": {}})
        assert not ok and "queue full" in reason
        ok, reason = adm.admit(
            "a", {"in_flight": 2, "per_tenant": {"a": 2}}
        )
        assert not ok and "quota" in reason
        # per-tenant override: "big" rides its own ceiling
        ok, _ = adm.admit("big", {"in_flight": 2, "per_tenant": {"big": 2}})
        assert ok
        # disabled gates admit everything
        open_adm = AdmissionController(None, None)
        ok, _ = open_adm.admit("a", {"in_flight": 999,
                                     "per_tenant": {"a": 999}})
        assert ok

    def test_zero_limits_mean_admit_nothing(self):
        """0 is a real ceiling, not a truthy-falsy 'unlimited': only
        None disables a gate."""
        adm = AdmissionController(max_queue_depth=0, tenant_quota=None)
        ok, reason = adm.admit("a", {"in_flight": 0, "per_tenant": {}})
        assert not ok and "queue full" in reason
        adm = AdmissionController(max_queue_depth=None, tenant_quota=0)
        ok, reason = adm.admit("a", {"in_flight": 0, "per_tenant": {}})
        assert not ok and "quota" in reason


# --------------------------------------------------------------------------
# daemon end-to-end (in process)


class TestServeDaemon:
    def test_byte_identical_to_fresh_process_build(
        self, tmp_path, daemon_factory
    ):
        """The acceptance contract: daemon-submitted execution is
        byte-identical (incl. chunk digests) to build() in a fresh
        process — only the setup cost differs."""
        raw = _ws_volume()
        paths = {}
        for tag in ("cold", "serve"):
            p = str(tmp_path / f"{tag}.n5")
            file_reader(p).create_dataset(
                "bnd", data=raw, chunks=(8, 16, 16)
            )
            paths[tag] = p

        # fresh process: the cold path every workflow run paid before
        driver = tmp_path / "cold_driver.py"
        driver.write_text(
            "import os, sys\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "from cluster_tools_tpu.runtime import build, config as cfg\n"
            "from cluster_tools_tpu.workflows import WatershedWorkflow\n"
            f"td = {str(tmp_path)!r}\n"
            "config_dir = os.path.join(td, 'configs_cold')\n"
            "cfg.write_global_config(config_dir,"
            " {'block_shape': [8, 16, 16]})\n"
            f"cfg.write_config(config_dir, 'watershed', {WS_CONFIG!r})\n"
            "wf = WatershedWorkflow(\n"
            "    os.path.join(td, 'tmp_cold'), config_dir,\n"
            f"    input_path={paths['cold']!r}, input_key='bnd',\n"
            f"    output_path={paths['cold']!r}, output_key='ws')\n"
            "assert build([wf])\n"
        )
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PALLAS_AXON_POOL_IPS": ""}
        env.pop("CTT_TRACE_DIR", None)
        proc = subprocess.run(
            [sys.executable, str(driver)], capture_output=True, text=True,
            env=env, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]

        daemon = daemon_factory(tmp_path / "serve_state")
        client = ServeClient(state_dir=str(tmp_path / "serve_state"))
        state = client.submit_and_wait(
            "WatershedWorkflow",
            {
                "tmp_folder": str(tmp_path / "tmp_serve"),
                "config_dir": str(tmp_path / "configs_serve"),
                "input_path": paths["serve"], "input_key": "bnd",
                "output_path": paths["serve"], "output_key": "ws",
            },
            configs={"global": {"block_shape": [8, 16, 16]},
                     "watershed": dict(WS_CONFIG)},
            timeout_s=300,
        )
        assert state["state"] == "done" and state["result"]["ok"]

        with file_reader(paths["cold"], "r") as fc, \
                file_reader(paths["serve"], "r") as fs:
            np.testing.assert_array_equal(fs["ws"][:], fc["ws"][:])
        assert _digest(os.path.join(paths["serve"], "ws")) == _digest(
            os.path.join(paths["cold"], "ws")
        ), "daemon output chunks not byte-identical to the fresh process"
        assert daemon.healthz()["context"]["builds_executed"] >= 1

    def test_warm_cold_accounting_and_metrics(
        self, tmp_path, daemon_factory
    ):
        daemon = daemon_factory(tmp_path / "state")
        client = ServeClient(state_dir=str(tmp_path / "state"))
        td = str(tmp_path)
        s1 = client.submit_and_wait(**_submit_kw(
            _sleep_vol_job(td, "w1", 0.01)), timeout_s=120)
        s2 = client.submit_and_wait(**_submit_kw(
            _sleep_vol_job(td, "w2", 0.01)), timeout_s=120)
        assert not s1["result"]["warm"], "first signature must be cold"
        assert s2["result"]["warm"], "repeat signature must be warm"
        text = client.metrics_text()
        assert text.rstrip().endswith("# EOF")
        lines = {
            l.split(" ")[0]: float(l.split(" ")[1])
            for l in text.splitlines()
            if l and not l.startswith("#") and " " in l
        }
        assert lines.get("ctt_serve_jobs_done_total", 0) >= 2
        assert lines.get("ctt_serve_warm_compile_jobs_total", 0) >= 1
        assert lines.get("ctt_serve_cold_compile_jobs_total", 0) >= 1
        try:
            from prometheus_client.openmetrics.parser import (
                text_string_to_metric_families,
            )
            assert list(text_string_to_metric_families(text))
        except ImportError:
            pass

    def test_quota_rejection_and_requeue_after_finish(
        self, tmp_path, daemon_factory
    ):
        daemon_factory(
            tmp_path / "state", tenant_quota=1, max_queue_depth=2
        )
        client = ServeClient(state_dir=str(tmp_path / "state"))
        td = str(tmp_path)
        blocker = client.submit(**_submit_kw(
            _sleep_vol_job(td, "q1", 1.5, tenant="t1")))
        _wait_state(client, blocker, "running")
        # tenant t1 is at quota while its job runs
        with pytest.raises(QuotaRejected) as exc:
            client.submit(**_submit_kw(
                _sleep_vol_job(td, "q2", 0.01, tenant="t1")))
        assert "quota" in str(exc.value)
        # another tenant still fits (queue depth 2: 1 running + 1 queued)
        other = client.submit(**_submit_kw(
            _sleep_vol_job(td, "q3", 0.01, tenant="t2")))
        # ... and now the queue itself is full for everyone
        with pytest.raises(QuotaRejected) as exc:
            client.submit(**_submit_kw(
                _sleep_vol_job(td, "q4", 0.01, tenant="t3")))
        assert "queue full" in str(exc.value)
        client.wait(blocker, timeout_s=120)
        client.wait(other, timeout_s=120)
        # capacity freed: the rejected tenant resubmits successfully
        done = client.submit_and_wait(**_submit_kw(
            _sleep_vol_job(td, "q5", 0.01, tenant="t1")), timeout_s=120)
        assert done["result"]["ok"]

    def test_priority_orders_claims(self, tmp_path, daemon_factory):
        daemon_factory(tmp_path / "state")  # concurrency 1 (default)
        client = ServeClient(state_dir=str(tmp_path / "state"))
        td = str(tmp_path)
        blocker = client.submit(**_submit_kw(
            _sleep_vol_job(td, "p0", 1.5)))
        _wait_state(client, blocker, "running")
        low = client.submit(**_submit_kw(
            _sleep_vol_job(td, "p_low", 0.01, priority=0)))
        high = client.submit(**_submit_kw(
            _sleep_vol_job(td, "p_high", 0.01, priority=10)))
        client.wait(blocker, timeout_s=120)
        s_low = client.wait(low, timeout_s=120)
        s_high = client.wait(high, timeout_s=120)
        assert (
            s_high["result"]["finished_wall"]
            < s_low["result"]["finished_wall"]
        ), "higher priority must claim (and finish) first"

    def test_mid_job_client_disconnect_survives(
        self, tmp_path, daemon_factory
    ):
        daemon = daemon_factory(tmp_path / "state")
        client = ServeClient(state_dir=str(tmp_path / "state"))
        td = str(tmp_path)
        job = client.submit(**_submit_kw(_sleep_vol_job(td, "d1", 1.0)))
        _wait_state(client, job, "running")
        # a client tears its connection mid-request while the job runs
        for payload in (b"", b"POST /api/v1/jobs HTTP/1.1\r\nContent-"):
            s = socket.create_connection(("127.0.0.1", daemon.port), 5)
            if payload:
                s.sendall(payload)
            s.close()
        # the daemon neither died nor lost the job
        assert client.healthz()["ok"]
        state = client.wait(job, timeout_s=120)
        assert state["result"]["ok"]

    def test_requests_require_daemon_token(self, tmp_path, daemon_factory):
        """The auth gate: serve.json is 0600 and carries the token; a
        tokenless caller gets 401 everywhere but /healthz — never
        reaching workflow resolution (arbitrary imports) in particular."""
        daemon = daemon_factory(tmp_path / "state")
        state_dir = str(tmp_path / "state")
        ep = read_endpoint(state_dir)
        assert ep["token"] == daemon.token
        mode = os.stat(os.path.join(state_dir, "serve.json")).st_mode
        assert mode & 0o777 == 0o600
        base = f"http://{ep['host']}:{ep['port']}"
        # tokenless liveness probe stays open
        with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
            assert json.loads(resp.read())["ok"]
        # everything else answers 401 without the token
        for method, path, data in (
            ("GET", "/api/v1/jobs", None),
            ("GET", "/metrics", None),
            ("POST", "/api/v1/jobs",
             json.dumps(_sleep_vol_job(str(tmp_path), "auth", 0.01))
             .encode()),
        ):
            req = urllib.request.Request(
                base + path, data=data, method=method,
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=10)
            assert exc.value.code == 401, (method, path)
        # the file-discovered client carries the token on every call
        client = ServeClient(state_dir=state_dir)
        assert client.token == daemon.token
        assert client.list_jobs() == []
        assert client.metrics_text().rstrip().endswith("# EOF")
        # Bearer form works too (prometheus-style authorization)
        req = urllib.request.Request(
            base + "/api/v1/jobs",
            headers={"Authorization": f"Bearer {daemon.token}"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read()) == {"jobs": []}

    def test_lease_renewer_threads_stop_with_jobs(
        self, tmp_path, daemon_factory
    ):
        """Each job's lease renewer must die with the job — a persistent
        daemon otherwise accumulates one immortal thread per job."""
        daemon_factory(tmp_path / "state")
        client = ServeClient(state_dir=str(tmp_path / "state"))
        td = str(tmp_path)
        for i in range(3):
            state = client.submit_and_wait(**_submit_kw(
                _sleep_vol_job(td, f"lr{i}", 0.01)), timeout_s=120)
            assert state["result"]["ok"]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            alive = [t for t in threading.enumerate()
                     if t.name == "ctt-serve-lease" and t.is_alive()]
            if not alive:
                break
            time.sleep(0.05)
        assert not alive, f"leaked lease renewers: {alive}"

    def test_watch_renders_serve_line(self, tmp_path, daemon_factory):
        from cluster_tools_tpu.obs.live import LiveRun, format_watch

        daemon_factory(tmp_path / "state")
        client = ServeClient(state_dir=str(tmp_path / "state"))
        client.submit_and_wait(**_submit_kw(
            _sleep_vol_job(str(tmp_path), "w", 0.01)), timeout_s=120)
        obs_metrics.flush()
        snap = LiveRun(obs_trace.run_dir()).poll()
        text = format_watch(snap)
        assert "serve:" in text and "done 1" in text


def _submit_kw(payload):
    return {
        "workflow": payload["workflow"],
        "kwargs": payload["kwargs"],
        "configs": payload["configs"],
        "tenant": payload["tenant"],
        "priority": payload["priority"],
    }


def _wait_state(client, job_id, state, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if client.status(job_id)["state"] == state:
            return
        time.sleep(0.05)
    raise AssertionError(
        f"job {job_id} never reached {state!r}: "
        f"{client.status(job_id)['state']}"
    )


# --------------------------------------------------------------------------
# SIGTERM drain (real daemon process)


@pytest.mark.timeout(300)
class TestSigtermDrain:
    def _spawn(self, state_dir, extra_env=None):
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PALLAS_AXON_POOL_IPS": "", "CTT_HEARTBEAT_S": "0.2"}
        env.pop("CTT_TRACE_DIR", None)
        env.pop("CTT_RUN_ID", None)
        if extra_env:
            env.update(extra_env)
        proc = subprocess.Popen(
            [sys.executable, "-m", "cluster_tools_tpu.serve",
             "--state-dir", str(state_dir), "--lease-s", "0.5"],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        deadline = time.monotonic() + 60
        ep_path = os.path.join(str(state_dir), "serve.json")
        while time.monotonic() < deadline:
            if os.path.exists(ep_path):
                try:
                    client = ServeClient(state_dir=str(state_dir))
                    client.healthz()
                    return proc, client
                except Exception:
                    pass
            if proc.poll() is not None:
                raise AssertionError(
                    f"daemon died at startup:\n{proc.stderr.read()}"
                )
            time.sleep(0.1)
        proc.kill()
        raise AssertionError("daemon never became healthy")

    def test_drain_finishes_running_keeps_queued_then_resumes(
        self, tmp_path
    ):
        state_dir = tmp_path / "state"
        td = str(tmp_path)
        proc, client = self._spawn(state_dir)
        try:
            running = client.submit(**_submit_kw(
                _sleep_vol_job(td, "r1", 2.0)))
            _wait_state(client, running, "running")
            queued = [
                client.submit(**_submit_kw(
                    _sleep_vol_job(td, f"g{i}", 0.01)))
                for i in range(2)
            ]
            proc.send_signal(signal.SIGTERM)
            # heartbeats keep landing DURING the drain: the SIGTERM
            # flush stops the beat thread, request_drain restarts it —
            # readers must see live draining beats (not `exiting`, not
            # staleness) while the in-flight job finishes
            run_dir = os.path.join(
                str(state_dir), "trace",
                json.load(open(state_dir / "serve.json"))["run_id"],
            )

            def read_hb():
                names = [n for n in os.listdir(run_dir)
                         if n.startswith("hb.p")]
                assert names, os.listdir(run_dir)
                return json.load(open(os.path.join(run_dir, names[0])))

            draining_beats = []
            deadline = time.monotonic() + 1.5
            while time.monotonic() < deadline:
                try:
                    hb = read_hb()
                except (OSError, json.JSONDecodeError):
                    hb = None
                if (
                    hb
                    and hb.get("draining")
                    and not hb.get("exiting")
                    and hb["seq"] not in [b["seq"] for b in draining_beats]
                ):
                    draining_beats.append(hb)
                    if len(draining_beats) >= 2:
                        break
                time.sleep(0.05)
            assert len(draining_beats) >= 2, (
                "heartbeat went silent during the drain: "
                f"{draining_beats}"
            )
            rc = proc.wait(timeout=120)
            assert rc == 0, (proc.stdout.read(), proc.stderr.read())
            # the in-flight job drained to a real result ...
            q = JobQueue(str(state_dir / "jobs"), lease_s=0.5)
            st = q.get(running)
            assert st["state"] == "done" and st["result"]["ok"], st
            # ... the queued jobs were not run and not lost ...
            for jid in queued:
                assert q.get(jid)["state"] == "queued"
            # ... and the heartbeat flagged the drain before exit
            hb = read_hb()
            assert hb["draining"] is True and hb["exiting"] is True
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

        # a successor daemon over the same state dir completes the
        # leftovers — the disk is the queue
        proc2, client2 = self._spawn(state_dir)
        try:
            for jid in queued:
                state = client2.wait(jid, timeout_s=120)
                assert state["result"]["ok"]
        finally:
            proc2.send_signal(signal.SIGTERM)
            try:
                proc2.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc2.kill()
                proc2.wait(timeout=30)
