"""Oracle tests for the round-2 component additions: region_centers,
merge_uniques (UniqueWorkflow), seed NMS, and the ilastik seam."""

import os
import stat

import numpy as np
import pytest

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


class TestRegionCenters:
    def test_centers_are_interior_maxima(self, tmp_path, rng):
        from scipy.ndimage import distance_transform_edt

        from cluster_tools_tpu.workflows import RegionCentersWorkflow

        shape = (16, 24, 24)
        seg = np.zeros(shape, dtype=np.uint64)
        seg[2:8, 2:10, 2:10] = 1
        seg[2:8, 14:22, 2:10] = 2
        seg[10:14, 4:20, 12:20] = 5  # sparse ids allowed
        path = str(tmp_path / "d.n5")
        file_reader(path).create_dataset("seg", data=seg, chunks=(8, 12, 12))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 12, 12]})
        wf = RegionCentersWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            output_path=path, output_key="centers",
        )
        assert build([wf])
        centers = file_reader(path, "r")["centers"][:]
        assert centers.shape == (6, 3)  # max id 5 → table over 0..5
        for sid in (1, 2, 5):
            c = centers[sid].astype(int)
            # the center lies inside its object...
            assert seg[tuple(c)] == sid
            # ...at the EDT-argmax depth (oracle recompute)
            sel = seg == sid
            bb = tuple(
                slice(a.min(), a.max() + 1) for a in np.nonzero(sel)
            )
            dist = distance_transform_edt(sel[bb])
            local = tuple(cc - b.start for cc, b in zip(c, bb))
            assert dist[local] == dist.max()
        # ids with no voxels stay zero
        np.testing.assert_array_equal(centers[3], 0)


class TestUniqueWorkflow:
    def test_merged_uniques_match_numpy(self, tmp_path, rng):
        from cluster_tools_tpu.workflows import UniqueWorkflow

        labels = rng.integers(0, 1000, (20, 30, 30)).astype(np.uint64) * 7
        path = str(tmp_path / "d.n5")
        file_reader(path).create_dataset("seg", data=labels, chunks=(8, 12, 12))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 12, 12]})
        cfg.write_config(config_dir, "merge_uniques", {"threads_per_job": 4})
        wf = UniqueWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            output_path=path, output_key="uniques",
        )
        assert build([wf])
        got = file_reader(path, "r")["uniques"][:]
        np.testing.assert_array_equal(got, np.unique(labels))


class TestSeedNms:
    def test_suppresses_dominated_maxima_keeps_strong(self):
        import jax.numpy as jnp

        from cluster_tools_tpu.ops.watershed import suppress_seeds

        dt = np.zeros((1, 16, 16), dtype=np.float32)
        maxima = np.zeros((1, 16, 16), dtype=bool)
        # strong maximum at (8,8) with radius 6; weak one at (8,10) inside
        # its parabola (6² − 2² = 32 > 1²); far one at (8,1) survives
        dt[0, 8, 8] = 6.0
        dt[0, 8, 10] = 1.0
        dt[0, 1, 1] = 2.0
        maxima[0, 8, 8] = maxima[0, 8, 10] = maxima[0, 1, 1] = True
        kept = np.asarray(
            suppress_seeds(jnp.asarray(maxima), jnp.asarray(dt))
        )
        assert kept[0, 8, 8]
        assert not kept[0, 8, 10]
        assert kept[0, 1, 1]

    def test_plateaus_survive(self):
        import jax.numpy as jnp

        from cluster_tools_tpu.ops.watershed import suppress_seeds

        dt = np.full((8, 8), 3.0, dtype=np.float32)
        maxima = np.zeros((8, 8), dtype=bool)
        maxima[4, 3:6] = True  # equal-height plateau: nobody dominates
        kept = np.asarray(suppress_seeds(jnp.asarray(maxima), jnp.asarray(dt)))
        np.testing.assert_array_equal(kept, maxima)

    def test_dt_watershed_nms_reduces_seeds(self, rng):
        import jax.numpy as jnp
        from scipy import ndimage

        from cluster_tools_tpu.ops.watershed import dt_watershed

        raw = ndimage.gaussian_filter(rng.random((8, 48, 48)), (1, 3, 3))
        raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype(np.float32)
        x = jnp.asarray(raw)
        _, n_plain = dt_watershed(x, threshold=0.6)
        labels, n_nms = dt_watershed(
            x, threshold=0.6, non_maximum_suppression=True
        )
        assert int(n_nms) <= int(n_plain)
        assert int(np.asarray(labels).max()) > 0


def _write_fake_ilastik(folder, mode="ok"):
    """A stand-in honoring the headless CLI contract
    (reference prediction.py:137-146): parses --cutout_subregion and
    --output_filename_format, writes deterministic predictions."""
    os.makedirs(folder, exist_ok=True)
    exe = os.path.join(folder, "run_ilastik.sh")
    script = os.path.join(folder, "fake_ilastik.py")
    with open(script, "w") as f:
        f.write(
            """
import ast, sys
import numpy as np
import h5py

args = dict(a.split("=", 1) for a in sys.argv[1:] if "=" in a)
sub = args["--cutout_subregion"].replace("None", "0")
start, stop = ast.literal_eval(sub)
shape = tuple(b - a for a, b in zip(start[:3], stop[:3]))
z, y, x = np.meshgrid(*[np.arange(a, b) for a, b in zip(start[:3], stop[:3])],
                      indexing="ij")
data = ((z + y + x) % 7).astype("float32") / 7.0
with h5py.File(args["--output_filename_format"], "w") as f:
    f.create_dataset("exported_data", data=data[..., None])
"""
        )
    with open(exe, "w") as f:
        f.write(f"#!/bin/sh\nexec python3 {script} \"$@\"\n")
    os.chmod(exe, os.stat(exe).st_mode | stat.S_IEXEC)
    return exe


class TestIlastikSeam:
    def test_prediction_workflow_with_fake_ilastik(self, tmp_path, rng):
        from cluster_tools_tpu.workflows import IlastikPredictionWorkflow

        shape = (16, 24, 24)
        raw = rng.random(shape).astype(np.float32)
        path = str(tmp_path / "d.n5")
        file_reader(path).create_dataset("raw", data=raw, chunks=(8, 12, 12))
        ilastik_folder = str(tmp_path / "ilastik")
        _write_fake_ilastik(ilastik_folder)
        project = str(tmp_path / "proj.ilp")
        open(project, "w").close()
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 12, 12]})
        wf = IlastikPredictionWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="raw",
            output_path=path, output_key="pred",
            ilastik_folder=ilastik_folder, ilastik_project=project,
            halo=[2, 2, 2], n_channels=1,
        )
        assert build([wf])
        pred = file_reader(path, "r")["pred"][:]
        # oracle: the fake emits ((z+y+x) % 7)/7 in global coordinates, so the
        # merged volume must match it exactly — proving halo'd subregions were
        # cut and cropped back correctly
        z, y, x = np.meshgrid(*[np.arange(s) for s in shape], indexing="ij")
        want = ((z + y + x) % 7).astype("float32") / 7.0
        np.testing.assert_allclose(pred, want)
        # block h5 files cleaned up
        assert not [p for p in os.listdir(tmp_folder) if p.endswith(".h5")]

    def test_missing_ilastik_fails_clearly(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.ilastik import IlastikPredictionTask

        path = str(tmp_path / "d.n5")
        file_reader(path).create_dataset(
            "raw", data=rng.random((8, 8, 8)).astype(np.float32)
        )
        config_dir = str(tmp_path / "configs")
        cfg.write_global_config(config_dir, {"block_shape": [8, 8, 8]})
        task = IlastikPredictionTask(
            str(tmp_path / "tmp"), config_dir,
            input_path=path, input_key="raw",
            ilastik_folder=str(tmp_path / "nope"),
            ilastik_project=str(tmp_path / "nope.ilp"),
        )
        with pytest.raises(Exception, match="ilastik"):
            if build([task]):
                pytest.fail("build must fail when the executable is absent")

    def test_stack_predictions(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.ilastik import StackPredictionsTask

        shape = (8, 16, 16)
        raw = rng.random(shape).astype(np.float32)
        pred = rng.random((2,) + shape).astype(np.float32)
        path = str(tmp_path / "d.n5")
        f = file_reader(path)
        f.create_dataset("raw", data=raw, chunks=(8, 8, 8))
        f.create_dataset("pred", data=pred, chunks=(1, 8, 8, 8))
        config_dir = str(tmp_path / "configs")
        cfg.write_global_config(config_dir, {"block_shape": [8, 8, 8]})
        task = StackPredictionsTask(
            str(tmp_path / "tmp"), config_dir,
            input_path=path, input_key="raw",
            pred_path=path, pred_key="pred",
            output_path=path, output_key="stacked",
        )
        assert build([task])
        got = file_reader(path, "r")["stacked"][:]
        np.testing.assert_allclose(got[0], raw)
        np.testing.assert_allclose(got[1:], pred)

    def test_carving_project_serialization(self, tmp_path, rng):
        import h5py

        from cluster_tools_tpu.workflows import IlastikCarvingWorkflow

        shape = (8, 16, 16)
        seg = np.zeros(shape, dtype=np.uint64)
        seg[:, :8, :] = 1
        seg[:, 8:, :8] = 2
        seg[:, 8:, 8:] = 3
        bnd = rng.random(shape).astype(np.float32)
        path = str(tmp_path / "d.n5")
        f = file_reader(path)
        f.create_dataset("seg", data=seg, chunks=(8, 8, 8))
        f.create_dataset("bnd", data=bnd, chunks=(8, 8, 8))
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 8, 8]})
        out = str(tmp_path / "carving.ilp")
        wf = IlastikCarvingWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            watershed_path=path, watershed_key="seg",
            output_path=out,
        )
        assert build([wf])
        with h5py.File(out, "r") as f:
            ser = f["preprocessing/graph/graph"][:]
            weights = f["preprocessing/graph/edgeWeights"][:]
            assert f["workflowName"][()] == b"Carving"
            n_nodes, n_edges, max_node, _ = ser[:4]
            # RAG of the three-partition volume: edges (1,2), (1,3), (2,3)
            assert (n_nodes, n_edges, max_node) == (4, 3, 3)
            uv = ser[4 : 4 + 2 * n_edges].reshape(n_edges, 2)
            assert {tuple(e) for e in uv} == {(1, 2), (1, 3), (2, 3)}
            assert weights.shape == (n_edges,)
            # neighborhoods: [deg, (nbr, edge)...] per node 0..max_node
            nbh = ser[4 + 2 * n_edges :]
            pos = 0
            degs = []
            for node in range(n_nodes):
                deg = nbh[pos]
                degs.append(deg)
                pos += 1 + 2 * deg
            assert pos == len(nbh)
            assert degs == [0, 2, 2, 2]
