"""Postprocess completion (orphans, block filters, filling filter),
simple/multicut stitching workflows, two-pass MWS."""

import os

import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


def _blockwise_labels(shape=(16, 32, 32)):
    """A partition split into per-block labels (as a block task would emit):
    two true segments, each fragmented at x=16."""
    gt = np.zeros(shape, dtype="uint64")
    gt[:, :16, :] = 1
    gt[:, 16:, :] = 2
    frag = (gt * 2 + (np.arange(shape[2]) >= 16)[None, None, :] - 1).astype(
        "uint64"
    )
    return gt, frag + 1  # labels 1..4


class TestPostprocessCompletion:
    def test_filter_blocks(self, tmp_path, rng):
        from cluster_tools_tpu.tasks.postprocess import FilterBlocksTask

        labels = rng.integers(1, 10, (16, 32, 32)).astype("uint64")
        path = str(tmp_path / "fb.n5")
        file_reader(path).create_dataset("seg", data=labels, chunks=(8, 16, 16))
        discard = np.asarray([3, 5], dtype="uint64")
        res_path = str(tmp_path / "discard.npy")
        np.save(res_path, discard)
        config_dir = str(tmp_path / "configs")
        tmp_folder = str(tmp_path / "tmp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        task = FilterBlocksTask(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            output_path=path, output_key="filtered",
            filter_path=res_path,
        )
        assert build([task])
        got = file_reader(path, "r")["filtered"][:]
        want = np.where(np.isin(labels, discard), 0, labels)
        np.testing.assert_array_equal(got, want)

    def test_filling_size_filter(self, tmp_path):
        from cluster_tools_tpu.tasks.postprocess import FillingSizeFilterTask

        shape = (8, 16, 16)
        labels = np.ones(shape, dtype="uint64")
        labels[:, :, 8:] = 2
        labels[2:4, 6:10, 6:10] = 3  # tiny segment to be filled
        hmap = np.zeros(shape, dtype="float32")
        hmap[:, :, 7:9] = 1.0  # ridge between 1 and 2
        path = str(tmp_path / "fs.n5")
        f = file_reader(path)
        f.create_dataset("seg", data=labels, chunks=(8, 16, 16))
        f.create_dataset("hmap", data=hmap, chunks=(8, 16, 16))
        res_path = str(tmp_path / "discard.npy")
        np.save(res_path, np.asarray([3], dtype="uint64"))
        config_dir = str(tmp_path / "configs_fs")
        tmp_folder = str(tmp_path / "tmp_fs")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        task = FillingSizeFilterTask(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            output_path=path, output_key="filled",
            hmap_path=path, hmap_key="hmap",
            res_path=res_path,
        )
        assert build([task])
        got = file_reader(path, "r")["filled"][:]
        assert 3 not in np.unique(got)
        assert (got > 0).all()  # every voxel re-flooded from survivors
        # untouched regions keep their labels
        assert (got[:, :, :4] == 1).all() and (got[:, :, 12:] == 2).all()

    def test_orphan_assignments(self, tmp_path):
        from cluster_tools_tpu.tasks.graph import InitialSubGraphsTask
        from cluster_tools_tpu.tasks.postprocess import (
            ORPHANS_NAME,
            OrphanAssignmentsTask,
        )
        from cluster_tools_tpu.workflows import GraphWorkflow

        # chain of segments 1-2-3; assignment merges nothing; 1 and 3 are
        # orphans (degree one) and must adopt their only neighbor 2
        labels = np.zeros((8, 8, 24), dtype="uint64")
        labels[:, :, :8] = 1
        labels[:, :, 8:16] = 2
        labels[:, :, 16:] = 3
        path = str(tmp_path / "orph.n5")
        file_reader(path).create_dataset("seg", data=labels, chunks=(8, 8, 8))
        config_dir = str(tmp_path / "configs_o")
        tmp_folder = str(tmp_path / "tmp_o")
        cfg.write_global_config(config_dir, {"block_shape": [8, 8, 24]})
        graph = GraphWorkflow(
            tmp_folder, config_dir, input_path=path, input_key="seg"
        )
        assert build([graph])
        assignment_path = str(tmp_path / "assign.npy")
        np.save(assignment_path, np.asarray([1, 2, 3], dtype="uint64"))
        task = OrphanAssignmentsTask(
            tmp_folder, config_dir,
            assignment_path=assignment_path,
        )
        assert build([task])
        table = np.load(os.path.join(tmp_folder, ORPHANS_NAME))
        got = dict(zip(table[:, 0].tolist(), table[:, 1].tolist()))
        assert got[1] == 2 and got[3] == 2 and got[2] == 2


class TestStitchingWorkflows:
    def test_simple_stitching(self, tmp_path):
        from cluster_tools_tpu.workflows import SimpleStitchingWorkflow

        gt, frag = _blockwise_labels()
        path = str(tmp_path / "ss.n5")
        file_reader(path).create_dataset("frag", data=frag, chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs_ss")
        tmp_folder = str(tmp_path / "tmp_ss")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        wf = SimpleStitchingWorkflow(
            tmp_folder, config_dir,
            labels_path=path, labels_key="frag",
            output_path=path, output_key="stitched",
        )
        assert build([wf])
        got = file_reader(path, "r")["stitched"][:]
        # every boundary-crossing pair merges → the whole foreground becomes
        # one segment (1|2 touch at y=16 boundary? they touch INSIDE blocks
        # too) — simple stitching merges any pair touching a block face
        n_got = len(np.unique(got[got > 0]))
        assert n_got < len(np.unique(frag))

    @pytest.mark.parametrize("target", ["local", "tpu"])
    def test_multicut_stitching_recovers_gt(self, tmp_path, rng, target):
        from cluster_tools_tpu.workflows import MulticutStitchingWorkflow

        gt, frag = _blockwise_labels()
        bnd = np.zeros(gt.shape, dtype=bool)
        bnd[:, 15:17, :] = True  # only the true boundary has evidence
        bnd = ndimage.gaussian_filter(
            bnd.astype("float32"), 1.0
        ) + 0.02 * rng.random(gt.shape).astype("float32")
        path = str(tmp_path / "ms.n5")
        f = file_reader(path)
        f.create_dataset("frag", data=frag, chunks=(8, 16, 16))
        f.create_dataset("bnd", data=bnd.astype("float32"), chunks=(8, 16, 16))
        config_dir = str(tmp_path / "configs_ms")
        tmp_folder = str(tmp_path / "tmp_ms")
        cfg.write_global_config(
            config_dir, {"block_shape": [8, 16, 16], "target": target}
        )
        wf = MulticutStitchingWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="bnd",
            labels_path=path, labels_key="frag",
            output_path=path, output_key="stitched",
        )
        assert build([wf])
        got = file_reader(path, "r")["stitched"][:]
        # fragments of the same gt segment merge, the gt boundary survives
        assert len(np.unique(got)) == 2
        assert (got[:, :14, :] == got[0, 0, 0]).all()
        assert (got[:, 18:, :] == got[0, -1, 0]).all()
        assert got[0, 0, 0] != got[0, -1, 0]


class TestTwoPassMws:
    def test_two_pass_consistency(self, tmp_path, rng):
        from cluster_tools_tpu.ops.affinities import compute_affinities
        from cluster_tools_tpu.workflows import TwoPassMwsWorkflow

        # ground truth: 4 quadrant segments; affinities derived from gt
        shape = (8, 32, 32)
        gt = np.broadcast_to(
            1
            + (np.arange(shape[1]) >= 16)[:, None] * 2
            + (np.arange(shape[2]) >= 16)[None, :],
            shape,
        ).astype("uint64")
        offsets = [[-1, 0, 0], [0, -1, 0], [0, 0, -1],
                   [0, -4, 0], [0, 0, -4]]
        affs, mask = compute_affinities(gt, offsets)
        affs = np.clip(
            affs + 0.05 * rng.standard_normal(affs.shape), 0, 1
        ).astype("float32")
        path = str(tmp_path / "tp.n5")
        file_reader(path).create_dataset(
            "affs", data=affs, chunks=(1, 8, 16, 16)
        )
        config_dir = str(tmp_path / "configs_tp")
        tmp_folder = str(tmp_path / "tmp_tp")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        cfg.write_config(
            config_dir, "two_pass_mws",
            {"offsets": offsets, "strides": [1, 2, 2], "halo": [0, 4, 4]},
        )
        wf = TwoPassMwsWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="affs",
            output_path=path, output_key="mws",
        )
        assert build([wf])
        seg = file_reader(path, "r")["mws"][:]
        assert seg.shape == shape
        # segmentation quality: each gt quadrant is dominated by one segment
        for q in range(1, 5):
            sel = gt == q
            vals, counts = np.unique(seg[sel], return_counts=True)
            assert counts.max() / sel.sum() > 0.9
        # consistency across the pass-0/pass-1 block boundary: the dominant
        # segment of a quadrant is the SAME on both sides of x=16 within a
        # block row — i.e. few distinct labels overall
        assert len(np.unique(seg)) < 30
