"""The bench timing primitives: host-fetch completion barrier + variants.

`timeit` must end every timed call in a real device→host fetch
(bench._host_sync) — on the tunneled TPU backend `block_until_ready` acks
before execution, so block-only timing reads ~0 ms (BENCH r4 first
session).  These tests pin the contract on the CPU backend where both
paths are observable.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _host_sync, fetch_floor_s, timeit  # noqa: E402


def test_host_sync_passes_through_numpy_and_scalars():
    for r in (np.arange(4), 3.5, None, [np.zeros(2), "x"]):
        assert _host_sync(r) is r


def test_host_sync_fetches_device_arrays():
    import jax.numpy as jnp

    r = (jnp.arange(8), jnp.zeros((2, 2)))
    assert _host_sync(r) is r  # completes without error on tuples


def test_timeit_counts_real_work():
    import jax

    @jax.jit
    def f(x):
        for _ in range(20):
            x = jnp_sin(x)
        return x

    import jax.numpy as jnp

    def jnp_sin(x):
        return jnp.sin(x) + 1e-3

    x = jnp.zeros((256, 256))
    t = timeit(lambda: f(x), 3)
    assert t > 0  # a real, positive wall measurement

    # variant scheme: each timed round consumes one distinct input
    calls = []
    variants = [
        (lambda i: lambda: calls.append(i) or f(x + i))(i) for i in range(4)
    ]
    timeit(None, 3, variants=variants)
    assert calls == [0, 1, 2, 3]


def test_fetch_floor_is_small_and_nonnegative():
    floor = fetch_floor_s(repeats=3)
    assert 0.0 <= floor < 1.0  # CPU: microseconds; tunnel: a few ms
