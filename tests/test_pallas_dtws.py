"""Fused per-slice DT-watershed kernel vs the XLA pipeline.

Interpreter-mode (Mosaic lowering is hardware-only — tools/tpu_validate.py).
The contract is BITWISE equality with
``dt_watershed(apply_dt_2d=True, apply_ws_2d=True)``: same EDT arithmetic,
same gaussian taps, same maxima rule, same CC numbering (minimal-flat-index
order), same flood tie-breaks, same size-filter epilogue."""

import numpy as np
import pytest
from scipy import ndimage

import jax.numpy as jnp

from cluster_tools_tpu.ops.pallas_dtws import (
    pallas_dt_watershed,
    pallas_dtws_available,
)
from cluster_tools_tpu.ops.watershed import dt_watershed


def _volume(seed, shape=(3, 16, 128), sigma=1.0):
    rng = np.random.default_rng(seed)
    raw = ndimage.gaussian_filter(rng.random(shape), sigma)
    return ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")


class TestPallasDtws:
    @pytest.mark.parametrize(
        "seed,kw",
        [
            (0, dict(threshold=0.6, size_filter=5)),
            (1, dict(threshold=0.45, sigma_seeds=1.0, sigma_weights=0.0,
                     alpha=0.9, size_filter=0)),
            (2, dict(threshold=0.55, sigma_seeds=0.0, size_filter=10,
                     invert_input=True)),
        ],
    )
    def test_bitwise_equal_to_xla(self, seed, kw):
        raw = _volume(seed)
        want, nw = dt_watershed(jnp.asarray(raw), **kw)
        got, ng = pallas_dt_watershed(raw, interpret=True, **kw)
        assert int(ng) == int(nw)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_mask_and_valid(self, rng):
        raw = _volume(7, (2, 8, 128))
        mask = rng.random(raw.shape) < 0.9
        valid = np.ones(raw.shape, bool)
        valid[:, -2:, :] = False  # padded batch-edge extent
        want, nw = dt_watershed(
            jnp.asarray(raw), mask=jnp.asarray(mask), threshold=0.6,
            size_filter=4, valid=jnp.asarray(valid),
        )
        got, ng = pallas_dt_watershed(
            raw, mask=mask, valid=valid, threshold=0.6, size_filter=4,
            interpret=True,
        )
        assert int(ng) == int(nw)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert (np.asarray(got)[~valid] == 0).all()

    def test_availability_gating(self):
        from cluster_tools_tpu.ops import _backend

        shape = (4, 16, 128)
        assert not pallas_dtws_available(shape, True, True, None, False)
        with _backend.force_dtws_mode("pallas"):
            import jax

            on_tpu = jax.default_backend() == "tpu"
            assert pallas_dtws_available(
                shape, True, True, None, False
            ) == on_tpu
            assert not pallas_dtws_available(shape, False, True, None, False)
            assert not pallas_dtws_available(shape, True, False, None, False)
            assert not pallas_dtws_available(
                shape, True, True, (2.0, 1.0, 1.0), False
            )
            assert not pallas_dtws_available(shape, True, True, None, True)
            assert not pallas_dtws_available((4, 16, 100), True, True, None, False)
            # VMEM budget (ADVICE r3): 1024x1024 slices overflow the ~16 MB
            # VMEM working set and must take the XLA path
            assert not pallas_dtws_available(
                (4, 1024, 1024), True, True, None, False
            )

    def test_large_sigma_gated_off(self):
        """Gaussian radius reaching across a full axis uses clamped reflect
        padding (vs symmetric-cyclic in the XLA path) — such configs must
        not route to the kernel."""
        from cluster_tools_tpu.ops import _backend

        with _backend.force_dtws_mode("pallas"):
            import jax

            on_tpu = jax.default_backend() == "tpu"
            # radius int(4*2.5+0.5)=10 >= H=8 → gated off regardless
            assert not pallas_dtws_available(
                (4, 8, 128), True, True, None, False, sigma_seeds=2.5
            )
            assert not pallas_dtws_available(
                (4, 8, 128), True, True, None, False, sigma_weights=2.5
            )
            # comfortably inside: gate is backend-decided
            assert pallas_dtws_available(
                (4, 32, 128), True, True, None, False,
                sigma_seeds=2.0, sigma_weights=2.0,
            ) == on_tpu
