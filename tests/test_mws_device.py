"""Device mutex watershed vs the host solvers (VERDICT r3 item 3).

The device kernel is the mutually-best-edge parallel greedy
(ops/mws_device.py docstring); with a shared strict total order (weight desc,
ties by input index) the device partition must EQUAL the host
Kruskal-with-mutexes partition — exactly when weights are representable in
both f32 (device) and f64 (host), i.e. quantized affinities; Rand/VoI-close
on continuous affinities (f32 rounding can swap near-equal priorities).
"""

import numpy as np
import pytest

from cluster_tools_tpu.ops import _backend
from cluster_tools_tpu.ops.evaluation import evaluate_segmentation, same_partition
from cluster_tools_tpu.ops.mws import (
    _mws_python,
    compute_mws_segmentation,
    compute_mws_segmentation_with_seeds,
    mutex_watershed_graph,
)
from cluster_tools_tpu.ops.mws_device import mutex_watershed_device

OFFSETS = [
    [-1, 0, 0], [0, -1, 0], [0, 0, -1],
    [-2, 0, 0], [0, -3, 0], [0, 0, -3],
    [-1, -3, 0], [0, 3, 3],
]


def _quantized_affs(rng, shape):
    """Affinities on a 1/256 grid: aff and 1-aff are exact in f32 AND f64,
    so host and device share the identical edge priority order."""
    return (rng.integers(0, 257, (len(OFFSETS),) + shape) / 256.0).astype(
        np.float32
    )


class TestGraphDomain:
    def _random_graph(self, rng, n=220, m=2500):
        uv = rng.integers(0, n, (m, 2)).astype(np.int64)
        uv = uv[uv[:, 0] != uv[:, 1]]
        # quantized weights with deliberate tie mass
        w = rng.integers(0, 64, uv.shape[0]) / 64.0
        attr = rng.random(uv.shape[0]) < 0.6
        return n, uv, w, attr.astype(np.uint8)

    def test_matches_python_oracle(self, rng):
        n, uv, w, attr = self._random_graph(rng)
        want = _mws_python(n, uv, w, attr)
        got = mutex_watershed_device(n, uv, w, attr)
        assert same_partition(want + 1, got + 1)

    def test_matches_native(self, rng):
        from cluster_tools_tpu import native

        if not native.available():
            pytest.skip("native solvers unavailable")
        n, uv, w, attr = self._random_graph(rng)
        want = mutex_watershed_graph(n, uv, w, attr, use_native=True)
        got = mutex_watershed_device(n, uv, w, attr)
        assert same_partition(want + 1, got + 1)

    def test_all_attractive_is_msf_components(self, rng):
        """No repulsive edges → plain maximum-spanning-forest components =
        one cluster per connected component."""
        n = 50
        uv = np.array([[i, i + 1] for i in range(24)]
                      + [[i, i + 1] for i in range(30, 40)])
        w = rng.random(uv.shape[0])
        roots = mutex_watershed_device(n, uv, w, np.ones(uv.shape[0], np.uint8))
        # chain 0..24 one cluster, 30..40 another, rest singletons
        assert len(np.unique(roots[:25])) == 1
        assert len(np.unique(roots[30:41])) == 1
        assert len(np.unique(roots)) == n - 24 - 10

    def test_strong_mutex_blocks_merge(self):
        """Classic 3-node case: strong repulsion between 0-2 must survive a
        weaker attractive chain closing the triangle."""
        uv = np.array([[0, 1], [1, 2], [0, 2]])
        w = np.array([0.9, 0.8, 0.95])
        attr = np.array([1, 1, 0], np.uint8)  # 0-2 repulsive, strongest
        roots = mutex_watershed_device(3, uv, w, attr)
        assert roots[0] == roots[1]          # strongest attractive merges
        assert roots[2] != roots[0]          # mutex blocks the chain
        want = _mws_python(3, uv, w, attr)
        assert same_partition(want + 1, roots + 1)

    def test_msf_shortcut_would_be_wrong(self):
        """Minimal instance (found by fuzzing) where 'maximum spanning forest
        over all edges, then cut repulsive edges' DIFFERS from the true MWS:
        the forest connects clusters through chains of repulsive edges,
        wrongly blocking the 17-22 merge — mutexes are pairwise, not
        transitive.  The device kernel must follow the true semantics."""
        uv = np.array([
            [24, 21], [11, 8], [23, 11], [24, 8], [33, 3], [31, 23],
            [31, 6], [22, 3], [17, 22], [6, 17], [21, 33],
        ])
        w = np.array([0.875, 0.625, 0.125, 0.75, 0.5, 0.625,
                      0.25, 0.75, 0.125, 0.25, 0.5])
        attr = np.array([0, 0, 1, 0, 1, 0, 1, 1, 1, 1, 0], np.uint8)
        want = _mws_python(35, uv, w, attr)
        got = mutex_watershed_device(35, uv, w, attr)
        assert same_partition(want + 1, got + 1)
        # the defining property: 17 and 22 end up together
        assert got[17] == got[22]

    def test_empty_and_single_edge(self):
        roots = mutex_watershed_device(
            4, np.zeros((0, 2), np.int64), np.zeros(0), np.zeros(0, np.uint8)
        )
        assert len(np.unique(roots)) == 4
        roots = mutex_watershed_device(
            4, np.array([[1, 3]]), np.array([0.5]), np.array([1], np.uint8)
        )
        assert roots[1] == roots[3] and len(np.unique(roots)) == 3


class TestVolumeDomain:
    def test_exact_parity_quantized(self, rng):
        affs = _quantized_affs(rng, (6, 16, 16))
        host = compute_mws_segmentation(affs, OFFSETS, use_native=False)
        with _backend.force_mws_mode("device"):
            dev = compute_mws_segmentation(affs, OFFSETS, use_native=False)
        assert same_partition(host.ravel(), dev.ravel())

    def test_exact_parity_with_strides_and_mask(self, rng):
        affs = _quantized_affs(rng, (4, 16, 16))
        mask = np.ones((4, 16, 16), bool)
        mask[:, :3] = False
        kw = dict(strides=[1, 2, 2], mask=mask, seed=3)
        host = compute_mws_segmentation(affs, OFFSETS, use_native=False, **kw)
        with _backend.force_mws_mode("device"):
            dev = compute_mws_segmentation(affs, OFFSETS, use_native=False, **kw)
        assert (dev[~mask] == 0).all()
        fg = mask
        assert same_partition(host[fg].ravel(), dev[fg].ravel())

    def test_rand_voi_parity_continuous(self, rng):
        """Continuous f32 affinities: f64 host vs f32 device priorities can
        swap near-ties — demand Rand/VoI-near-identical partitions
        (BASELINE.md parity metric)."""
        affs = rng.random((len(OFFSETS), 6, 16, 16)).astype(np.float32)
        host = compute_mws_segmentation(affs, OFFSETS, use_native=False)
        with _backend.force_mws_mode("device"):
            dev = compute_mws_segmentation(affs, OFFSETS, use_native=False)
        scores = evaluate_segmentation(host.ravel(), dev.ravel())
        assert scores["rand_index"] > 0.99
        assert scores["vi_split"] + scores["vi_merge"] < 0.1

    def test_seeded_variant_device(self, rng):
        affs = _quantized_affs(rng, (4, 16, 16))
        seeds = np.zeros((4, 16, 16), np.uint64)
        seeds[0, :4, :4] = 7
        seeds[3, 10:, 10:] = 9
        host = compute_mws_segmentation_with_seeds(
            affs, OFFSETS, seeds, use_native=False
        )
        with _backend.force_mws_mode("device"):
            dev = compute_mws_segmentation_with_seeds(
                affs, OFFSETS, seeds, use_native=False
            )
        assert same_partition(host.ravel(), dev.ravel())
        # seed labels must survive verbatim
        assert (dev[seeds == 7] == 7).all() and (dev[seeds == 9] == 9).all()


class TestChainContraction:
    """The chain rule (mws_device docstring): a cluster whose best edge is
    attractive and mutex-immune merges without mutuality, so monotone
    attractive chains contract in O(log) rounds instead of one per round."""

    def test_monotone_chain_single_round(self):
        from cluster_tools_tpu.ops.mws_device import (
            mutex_watershed_device_rounds,
        )

        n = 512
        uv = np.stack([np.arange(n - 1), np.arange(1, n)], axis=1)
        w = np.linspace(1.0, 0.5, n - 1).astype(np.float32)
        att = np.ones(n - 1, bool)
        rounds = mutex_watershed_device_rounds(n, uv, w, att)
        # whole chain is immune (no repulsive edges): one contraction round
        assert rounds <= 2, rounds
        # the mutual-only algorithm serializes the same chain one merge per
        # round — the A/B that keeps the contraction win reproducible
        legacy = mutex_watershed_device_rounds(
            n, uv, w, att, enable_chain=False
        )
        assert legacy >= n - 2, legacy
        lab = mutex_watershed_device(n, uv, w, att)
        want = _mws_python(n, uv, w, att)
        assert same_partition(lab + 1, want + 1)

    def test_chain_with_weak_repulsive_exact(self, rng):
        """Chains + weak long-range repulsive: still few rounds, exact."""
        from cluster_tools_tpu.ops.mws_device import (
            mutex_watershed_device_rounds,
        )

        n = 256
        uv_c = np.stack([np.arange(n - 1), np.arange(1, n)], axis=1)
        w_c = (rng.integers(128, 257, n - 1) / 256.0).astype(np.float32)
        rep = rng.integers(0, n, (300, 2))
        rep = rep[rep[:, 0] != rep[:, 1]]
        w_r = (rng.integers(0, 128, len(rep)) / 256.0).astype(np.float32)
        uv = np.concatenate([uv_c, rep])
        w = np.concatenate([w_c, w_r])
        att = np.concatenate([np.ones(n - 1, bool), np.zeros(len(rep), bool)])
        rounds = mutex_watershed_device_rounds(n, uv, w, att)
        assert rounds <= 16, rounds
        lab = mutex_watershed_device(n, uv, w, att)
        want = _mws_python(n, uv, w, att)
        assert same_partition(lab + 1, want + 1)

    def test_tie_heavy_random_graphs_exact(self):
        """Heavy duplicate-weight mass across many seeds: the chain rule
        must preserve exact parity with the sequential oracle."""
        for seed in range(8):
            tr = np.random.default_rng(100 + seed)
            nn, m = 200, 800
            uv = tr.integers(0, nn, (m, 2)).astype(np.int32)
            uv = uv[uv[:, 0] != uv[:, 1]]
            w = (tr.integers(0, 32, len(uv)) / 32.0).astype(np.float32)
            att = tr.random(len(uv)) < 0.6
            want = _mws_python(nn, uv, w, att)
            got = mutex_watershed_device(nn, uv, w, att)
            assert same_partition(want + 1, got + 1), seed


class TestDoomedPairDiscard:
    """The round-collapse rule (mws_device docstring): every active edge of
    an already-mutexed cluster pair is discarded per round.  Without it the
    near-boundary regime drained one mutexed mutual pair per round."""

    def _bimodal_affinity_problem(self, shape):
        from scipy import ndimage

        from cluster_tools_tpu.ops.mws import _affinity_edge_lists

        offsets = [
            [-1, 0, 0], [0, -1, 0], [0, 0, -1],
            [-2, 0, 0], [0, -4, 0], [0, 0, -4],
        ]
        tr = np.random.default_rng(1)
        affs = ndimage.gaussian_filter(
            tr.random((len(offsets),) + shape).astype(np.float32),
            (0, 1, 2, 2),
        )
        us, vs, ws, att = _affinity_edge_lists(
            affs, offsets, [1, 2, 2], False, 0.0,
            np.random.default_rng(0), 3,
        )
        uv = np.stack([np.concatenate(us), np.concatenate(vs)], axis=1)
        return (
            int(np.prod(shape)), uv,
            np.concatenate(ws).astype(np.float32),
            np.concatenate(att).astype(bool),
        )

    def test_bimodal_round_collapse_exact(self):
        """The bench's realistic regime: 1164 rounds without the rule;
        the bound here leaves ~3x headroom over the measured 33."""
        from cluster_tools_tpu.ops.mws_device import (
            mutex_watershed_device_rounds,
        )

        n, uv, w, att = self._bimodal_affinity_problem((8, 16, 16))
        rounds = mutex_watershed_device_rounds(n, uv, w, att)
        assert rounds <= 100, rounds
        got = mutex_watershed_device(n, uv, w, att)
        want = _mws_python(n, uv, w.astype(np.float64), att.astype(np.uint8))
        assert same_partition(want + 1, got + 1)

    def test_doomed_rows_drain_in_one_round(self):
        """Once a mutex is recorded between two clusters, ALL remaining
        edges of that pair — both signs — must be discarded together.
        Construction: (0,1) merges at 0.9; the mutual repulsive (0,2) at
        0.8 records the mutex; then k parallel weaker edges between the
        two clusters are doomed.  Without the discard rule each drains as
        a mutual pair one round at a time (rounds >= k); with it the whole
        pile goes in one round."""
        from cluster_tools_tpu.ops.mws_device import (
            mutex_watershed_device_rounds,
        )

        k = 24
        uv = [[0, 1], [0, 2]]
        w = [0.9, 0.8]
        att = [True, False]
        for i in range(k):
            # alternate signs, strictly descending weights below the mutex
            uv.append([1, 2] if i % 2 else [0, 2])
            w.append(0.7 - 0.02 * i)
            att.append(bool(i % 2))
        uv = np.asarray(uv)
        w = np.asarray(w, np.float32)
        att = np.asarray(att)
        n = 3
        rounds = mutex_watershed_device_rounds(n, uv, w, att)
        assert rounds <= 4, rounds  # k=24 doomed rows would need >= 12
        got = mutex_watershed_device(n, uv, w, att)
        want = _mws_python(n, uv, w, att)
        assert same_partition(want + 1, got + 1)
        assert len(np.unique(got)) == 2  # {0,1} | {2}
