import numpy as np
import pytest

from cluster_tools_tpu.utils.blocking import (
    Blocking,
    blocks_in_volume,
    make_checkerboard_block_lists,
)


def test_grid_shape_and_ids():
    b = Blocking((100, 100, 100), (50, 30, 100))
    assert b.grid_shape == (2, 4, 1)
    assert b.n_blocks == 8
    for bid in range(b.n_blocks):
        assert b.block_id_from_grid_position(b.block_grid_position(bid)) == bid


def test_blocks_cover_volume_disjointly():
    shape = (53, 41, 17)
    b = Blocking(shape, (16, 16, 16))
    cover = np.zeros(shape, dtype=np.int32)
    for bid in range(b.n_blocks):
        cover[b.block(bid).slicing] += 1
    assert (cover == 1).all()


def test_halo_geometry():
    b = Blocking((100, 100), (50, 50))
    bh = b.block_with_halo(3, (10, 10))  # last block, clipped at upper border
    assert bh.inner.begin == (50, 50)
    assert bh.outer.begin == (40, 40)
    assert bh.outer.end == (100, 100)
    assert bh.inner_local.begin == (10, 10)
    assert bh.inner_local.end == (60, 60)
    # interior block of a 3x3 grid has symmetric halo
    b2 = Blocking((150, 150), (50, 50))
    bh2 = b2.block_with_halo(4, (5, 5))
    assert bh2.outer.begin == (45, 45) and bh2.outer.end == (105, 105)
    assert bh2.inner_local.begin == (5, 5) and bh2.inner_local.end == (55, 55)


def test_neighbors_and_faces():
    b = Blocking((100, 100), (50, 50))
    assert b.neighbor_id(0, 0, lower=True) is None
    assert b.neighbor_id(0, 0, lower=False) == 2
    assert b.neighbor_id(0, 1, lower=False) == 1
    faces = list(b.iterate_faces(0))
    assert len(faces) == 2
    axis, ngb, bb = faces[0]
    assert axis == 0 and ngb == 2
    assert bb.begin == (49, 0) and bb.end == (51, 50)
    # upper-right block has no upper faces
    assert list(b.iterate_faces(3)) == []


def test_roi_restriction():
    shape = (100, 100, 100)
    ids = blocks_in_volume(shape, (50, 50, 50), (0, 0, 0), (50, 100, 100))
    assert ids == [0, 1, 2, 3]
    ids = blocks_in_volume(shape, (50, 50, 50), (25, 25, 25), (75, 75, 75))
    assert ids == list(range(8))


def test_checkerboard_no_adjacent_same_color():
    b = Blocking((90, 90, 90), (30, 30, 30))
    white, black = make_checkerboard_block_lists(b)
    assert len(white) + len(black) == b.n_blocks
    wset = set(white)
    for bid in white:
        for axis in range(3):
            for lower in (True, False):
                ngb = b.neighbor_id(bid, axis, lower)
                assert ngb is None or ngb not in wset
