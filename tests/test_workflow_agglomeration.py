"""Watershed-family completion tests: watershed_from_seeds (via
ThresholdAndWatershedWorkflow), per-block agglomerate, and the global
agglomerative-clustering workflow.

Idioms from the reference suite (SURVEY.md §4): invariant checks + segment
count sanity (test/workflows/multicut_workflow.py:19-28)."""

import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import (
    AgglomerativeClusteringWorkflow,
    ThresholdAndWatershedWorkflow,
    WatershedWorkflow,
)


@pytest.fixture
def boundary_volume(tmp_path, rng):
    raw = ndimage.gaussian_filter(rng.random((24, 48, 48)), (1.0, 2.0, 2.0))
    raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
    path = str(tmp_path / "d.n5")
    file_reader(path).create_dataset("bnd", data=raw, chunks=(12, 24, 24))
    return path, raw


def test_threshold_and_watershed(tmp_path, boundary_volume):
    path, raw = boundary_volume
    config_dir = str(tmp_path / "configs")
    cfg.write_global_config(config_dir, {"block_shape": [12, 24, 24]})
    cfg.write_config(config_dir, "threshold", {})
    # seed cores = low-boundary basins (raw < 0.4)
    cfg.write_config(
        config_dir, "block_components",
        {"threshold": 0.4, "threshold_mode": "less"},
    )
    cfg.write_config(
        config_dir, "watershed_from_seeds",
        {"sigma_weights": 1.0, "halo": [2, 6, 6], "apply_ws_2d": False},
    )
    wf = ThresholdAndWatershedWorkflow(
        str(tmp_path / "tmp"), config_dir,
        input_path=path, input_key="bnd",
        output_path=path, output_key="seg",
    )
    assert build([wf])
    f = file_reader(path, "r")
    seeds = f["seg_seeds"][:]
    seg = f["seg"][:]
    # several global seed components, grown without inventing or losing ids
    # (the unmasked flood covers the full volume, so 0 disappears from seg)
    seed_ids = set(np.unique(seeds[seeds > 0]))
    assert len(seed_ids) > 3
    assert set(np.unique(seg[seg > 0])) == seed_ids
    assert (seg[seeds > 0] == seeds[seeds > 0]).all()
    assert (seg > 0).sum() > (seeds > 0).sum()
    # seed ids are globally merged ⇒ labels are boundary-consistent: a segment
    # crossing the z=12 block face keeps one id on both sides
    a, b = seg[11], seg[12]
    sel = (a > 0) & (b > 0)
    assert sel.sum() > 0
    assert (a[sel] == b[sel]).mean() > 0.8


def _run_ws(tmp_path, path, key, agglomeration, agglo_threshold=0.9):
    config_dir = str(tmp_path / f"configs_{key}")
    cfg.write_global_config(config_dir, {"block_shape": [12, 24, 24]})
    cfg.write_config(
        config_dir, "watershed",
        {"threshold": 0.5, "sigma_seeds": 1.6, "size_filter": 10,
         "halo": [2, 6, 6], "apply_dt_2d": False, "apply_ws_2d": False},
    )
    cfg.write_config(config_dir, "agglomerate", {"threshold": agglo_threshold})
    wf = WatershedWorkflow(
        str(tmp_path / f"tmp_{key}"), config_dir,
        input_path=path, input_key="bnd",
        output_path=path, output_key=key,
        agglomeration=agglomeration,
    )
    assert build([wf])
    return file_reader(path, "r")[key][:]


def test_watershed_agglomeration_merges_fragments(tmp_path, boundary_volume):
    path, raw = boundary_volume
    ws = _run_ws(tmp_path, path, "ws_plain", agglomeration=False)
    merged = _run_ws(tmp_path, path, "ws_agglo", agglomeration=True)
    n_plain = np.unique(ws).size
    n_merged = np.unique(merged).size
    assert 1 < n_merged < n_plain
    # agglomeration only merges: same-id voxels in ws stay same-id in merged
    fg = (ws > 0) & (merged > 0)
    pairs = np.unique(np.stack([ws[fg], merged[fg]]), axis=1)
    assert np.unique(pairs[0]).size == pairs.shape[1]  # ws id → one merged id
    # coverage unchanged
    assert ((merged > 0) == (ws > 0)).all()


def test_agglomerative_clustering_workflow(tmp_path, boundary_volume):
    path, raw = boundary_volume
    ws = _run_ws(tmp_path, path, "ws_for_ac", agglomeration=False)
    config_dir = str(tmp_path / "configs_ac")
    cfg.write_global_config(config_dir, {"block_shape": [12, 24, 24]})
    cfg.write_config(config_dir, "agglomerative_clustering", {"threshold": 0.6})
    wf = AgglomerativeClusteringWorkflow(
        str(tmp_path / "tmp_ac"), config_dir,
        input_path=path, input_key="bnd",
        ws_path=path, ws_key="ws_for_ac",
        output_path=path, output_key="seg_ac",
    )
    assert build([wf])
    seg = file_reader(path, "r")["seg_ac"][:]
    n_ws = np.unique(ws).size
    n_seg = np.unique(seg).size
    assert 1 < n_seg < n_ws
    # clustering is a merge of watershed fragments: coverage identical
    assert ((seg > 0) == (ws > 0)).all()
