"""The example/ scripts must stay runnable (reference example/ parity)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize(
    "script",
    ["multicut.py", "sharded_volume.py", "downscale.py",
     "postprocessing.py", "skeletons.py"],
)
def test_example_demo_runs(tmp_path, script):
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "example", script), "--demo"],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=600,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert out.stdout.strip()
