"""Skeletons, meshes, distances: ops oracles + workflow runs."""

import os

import numpy as np
import pytest

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


def _two_rod_volume():
    """Two straight rods along x at known distance (gap 6 voxels in y)."""
    shape = (12, 24, 40)
    seg = np.zeros(shape, dtype="uint64")
    seg[4:8, 4:8, 4:36] = 1
    seg[4:8, 14:18, 4:36] = 2
    return shape, seg


class TestSkeletonOps:
    def test_rod_skeleton_spans(self):
        from cluster_tools_tpu.ops.skeleton import skeletonize

        obj = np.zeros((7, 7, 40), dtype=bool)
        obj[2:5, 2:5, 2:38] = True
        nodes, edges = skeletonize(obj)
        assert nodes.shape[0] >= 2
        assert nodes[:, 2].max() - nodes[:, 2].min() > 25
        assert edges.shape[0] >= nodes.shape[0] - 1
        # nodes stay inside the object
        vox = np.round(nodes).astype(int)
        assert obj[tuple(vox.T)].all()

    def test_resolution_scaling(self):
        from cluster_tools_tpu.ops.skeleton import skeletonize

        obj = np.zeros((5, 5, 20), dtype=bool)
        obj[1:4, 1:4, 1:19] = True
        nodes_v, _ = skeletonize(obj)
        nodes_p, _ = skeletonize(obj, resolution=[10.0, 4.0, 4.0])
        np.testing.assert_allclose(nodes_p, nodes_v * [10.0, 4.0, 4.0])


class TestMeshOps:
    def test_ball_mesh_properties(self):
        from cluster_tools_tpu.ops.mesh import marching_cubes

        zz, yy, xx = np.mgrid[:16, :16, :16]
        ball = ((zz - 8) ** 2 + (yy - 8) ** 2 + (xx - 8) ** 2) <= 36
        verts, faces, normals = marching_cubes(ball, smoothing_iterations=2)
        # watertight: V - E + F == 2
        uedges = np.unique(
            np.sort(
                np.concatenate(
                    [faces[:, [0, 1]], faces[:, [1, 2]], faces[:, [0, 2]]]
                ),
                axis=1,
            ),
            axis=0,
        )
        assert len(verts) - len(uedges) + len(faces) == 2
        # outward normals
        center = verts.mean(0)
        d = ((verts - center) * normals).sum(1)
        assert (d > 0).mean() == 1.0
        # area close to the analytic sphere
        v0, v1, v2 = verts[faces[:, 0]], verts[faces[:, 1]], verts[faces[:, 2]]
        area = 0.5 * np.linalg.norm(np.cross(v1 - v0, v2 - v0), axis=1).sum()
        assert abs(area - 4 * np.pi * 36) / (4 * np.pi * 36) < 0.1

    def test_obj_ply_roundtrip(self, tmp_path):
        from cluster_tools_tpu.ops.mesh import (
            marching_cubes,
            read_obj,
            write_obj,
            write_ply,
        )

        cube = np.zeros((6, 6, 6), dtype=bool)
        cube[1:5, 1:5, 1:5] = True
        verts, faces, normals = marching_cubes(cube)
        p = str(tmp_path / "cube.obj")
        write_obj(p, verts, faces, normals)
        v2, f2, n2 = read_obj(p)
        np.testing.assert_allclose(v2, verts, atol=1e-6)
        np.testing.assert_array_equal(f2, faces)
        write_ply(str(tmp_path / "cube.ply"), verts, faces, normals)
        assert "end_header" in open(str(tmp_path / "cube.ply")).read()


class TestWorkflows:
    def _setup(self, tmp_path, seg, name):
        path = str(tmp_path / f"{name}.n5")
        file_reader(path).create_dataset("seg", data=seg, chunks=(8, 16, 16))
        config_dir = str(tmp_path / f"configs_{name}")
        tmp_folder = str(tmp_path / f"tmp_{name}")
        cfg.write_global_config(config_dir, {"block_shape": [8, 16, 16]})
        return path, tmp_folder, config_dir

    def test_skeleton_workflow_and_eval(self, tmp_path):
        from cluster_tools_tpu.tasks.skeletons import (
            load_skeleton_evaluation,
            load_skeletons,
        )
        from cluster_tools_tpu.workflows.skeletons import (
            SkeletonEvaluationWorkflow,
        )

        shape, seg = _two_rod_volume()
        path, tmp_folder, config_dir = self._setup(tmp_path, seg, "skel")
        wf = SkeletonEvaluationWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            seg_path=path, seg_key="seg",
        )
        assert build([wf])
        skels = load_skeletons(tmp_folder)
        assert set(skels) == {1, 2}
        for sid, (nodes, edges) in skels.items():
            assert nodes.shape[0] >= 2
            assert nodes[:, 2].max() - nodes[:, 2].min() > 20
        # evaluating against the segmentation itself: perfect correctness
        ev = load_skeleton_evaluation(tmp_folder)
        np.testing.assert_allclose(ev["correctness"], 1.0)
        assert int(ev["n_merges"]) == 0

    def test_upsample_skeletons(self, tmp_path):
        from cluster_tools_tpu.tasks.skeletons import UpsampleSkeletonsTask
        from cluster_tools_tpu.workflows.skeletons import SkeletonWorkflow

        shape, seg = _two_rod_volume()
        path, tmp_folder, config_dir = self._setup(tmp_path, seg, "ups")
        assert build([
            SkeletonWorkflow(
                tmp_folder, config_dir, input_path=path, input_key="seg"
            )
        ])
        task = UpsampleSkeletonsTask(
            tmp_folder, config_dir,
            input_path=path, input_key="seg",
            output_path=path, output_key="skel_vol",
        )
        assert build([task])
        vol = file_reader(path, "r")["skel_vol"][:]
        assert vol.shape == shape
        # painted voxels carry their skeleton id and lie inside the object
        for sid in (1, 2):
            sel = vol == sid
            assert sel.sum() >= 2
            assert (seg[sel] == sid).all()

    def test_distance_workflow(self, tmp_path):
        from cluster_tools_tpu.tasks.distances import load_object_distances
        from cluster_tools_tpu.workflows.skeletons import DistanceWorkflow

        shape, seg = _two_rod_volume()
        path, tmp_folder, config_dir = self._setup(tmp_path, seg, "dist")
        cfg.write_config(
            config_dir, "object_distances", {"max_distance": 50.0}
        )
        wf = DistanceWorkflow(
            tmp_folder, config_dir, input_path=path, input_key="seg"
        )
        assert build([wf])
        dists = load_object_distances(tmp_folder)
        assert (1, 2) in dists
        # rods are separated by a 6-voxel gap in y (8 -> 14)
        assert abs(dists[(1, 2)] - 6.0) <= 1.0

    def test_mesh_workflow(self, tmp_path):
        from cluster_tools_tpu.ops.mesh import read_obj
        from cluster_tools_tpu.workflows.skeletons import MeshWorkflow

        shape, seg = _two_rod_volume()
        path, tmp_folder, config_dir = self._setup(tmp_path, seg, "mesh")
        out_dir = str(tmp_path / "meshes")
        wf = MeshWorkflow(
            tmp_folder, config_dir,
            input_path=path, input_key="seg", output_dir=out_dir,
        )
        assert build([wf])
        for sid in (1, 2):
            verts, faces, normals = read_obj(
                os.path.join(out_dir, f"{sid}.obj")
            )
            assert len(verts) > 10 and len(faces) > 10
            # mesh sits inside the object's physical bounds
            sel = np.argwhere(seg == sid)
            assert verts[:, 2].min() >= sel[:, 2].min() - 1.5
            assert verts[:, 2].max() <= sel[:, 2].max() + 1.5
