"""Slurm/LSF executor tests against a stub scheduler.

The reference has no scheduler mocks ("multi-node is tested by the same code
path with the target switched", SURVEY.md §4); this is the fake-scheduler
seam it lacked: a stand-in ``sbatch``/``bsub`` runs each job script
synchronously, the stand-in queue reports empty, and the whole
submit → poll → per-job status → aggregate path is exercised for real.
"""

import os
import stat

import numpy as np
import pytest

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


def _write_stub_scheduler(folder):
    """sbatch/bsub stand-in: strips scheduler flags, runs the job script
    synchronously.  squeue/bjobs stand-in: reports no queued jobs."""
    os.makedirs(folder, exist_ok=True)
    submit = os.path.join(folder, "stub_submit")
    with open(submit, "w") as f:
        f.write(
            "#!/bin/bash\n"
            "# last argument is the job script\n"
            'script="${@: -1}"\n'
            'bash "$script" > /dev/null 2>&1\n'
            'echo "Submitted batch job 1"\n'
        )
    queue = os.path.join(folder, "stub_queue")
    with open(queue, "w") as f:
        f.write("#!/bin/bash\nexit 0\n")
    for p in (submit, queue):
        os.chmod(p, os.stat(p).st_mode | stat.S_IEXEC)
    return submit, queue


WORKER_ENV = {
    # keep the worker off the accelerator tunnel: unset the axon pool so the
    # sitecustomize platform plugin stays unregistered, force the cpu backend
    "PALLAS_AXON_POOL_IPS": "",
    "JAX_PLATFORMS": "cpu",
}


@pytest.mark.parametrize("target", ["slurm", "lsf"])
def test_cluster_target_runs_workflow(tmp_path, rng, target):
    from cluster_tools_tpu.workflows import UniqueWorkflow

    submit, queue = _write_stub_scheduler(str(tmp_path / "sched"))
    labels = rng.integers(0, 100, (16, 24, 24)).astype(np.uint64)
    path = str(tmp_path / "d.n5")
    file_reader(path).create_dataset("seg", data=labels, chunks=(8, 12, 12))
    config_dir = str(tmp_path / "configs")
    tmp_folder = str(tmp_path / "tmp")
    cfg.write_global_config(
        config_dir,
        {
            "block_shape": [8, 12, 12],
            "target": target,
            "max_jobs": 3,
            "poll_interval_s": 0.05,
            "sbatch_cmd": submit,
            "squeue_cmd": queue,
            "bsub_cmd": submit,
            "bjobs_cmd": queue,
            "worker_env": WORKER_ENV,
        },
    )
    wf = UniqueWorkflow(
        tmp_folder, config_dir, max_jobs=3,
        input_path=path, input_key="seg",
        output_path=path, output_key="uniques",
    )
    assert build([wf])
    got = file_reader(path, "r")["uniques"][:]
    np.testing.assert_array_equal(got, np.unique(labels))
    # the per-block task really went through scheduler jobs
    job_dir = os.path.join(tmp_folder, "cluster_jobs", "find_uniques")
    statuses = [f for f in os.listdir(job_dir) if f.endswith(".status.json")]
    assert 1 <= len(statuses) <= 3


def test_cluster_failure_surfaces_failed_blocks(tmp_path, rng):
    """A worker whose task raises reports its blocks failed; the task layer
    then raises FailedBlocksError (no silent success)."""
    from cluster_tools_tpu.runtime.task import FailedBlocksError
    from cluster_tools_tpu.tasks.ilastik import IlastikPredictionTask

    submit, queue = _write_stub_scheduler(str(tmp_path / "sched"))
    path = str(tmp_path / "d.n5")
    file_reader(path).create_dataset(
        "raw", data=rng.random((8, 8, 8)).astype(np.float32)
    )
    config_dir = str(tmp_path / "configs")
    cfg.write_global_config(
        config_dir,
        {
            "block_shape": [8, 8, 8],
            "target": "slurm",
            "poll_interval_s": 0.05,
            "sbatch_cmd": submit,
            "squeue_cmd": queue,
            "worker_env": WORKER_ENV,
        },
    )
    # project exists so DAG-build passes; the executable is missing, so every
    # worker block fails at run time
    ilastik_folder = str(tmp_path / "noilastik")
    os.makedirs(ilastik_folder)
    task = IlastikPredictionTask(
        str(tmp_path / "tmp"), config_dir,
        input_path=path, input_key="raw",
        ilastik_folder=ilastik_folder,
        ilastik_project=path,
    )
    with pytest.raises((FailedBlocksError, RuntimeError)):
        task.run()


def test_multihost_topology_two_processes(tmp_path, rng):
    """Multi-host scale-out (SURVEY.md §2.9): the SAME driver script runs as
    two real OS processes sharing tmp/config dirs; blocks shard round-robin,
    per-process status files barrier the merge, the merge runs on process 0
    while process 1 waits — combined output identical to a numpy oracle."""
    import subprocess
    import sys

    labels = rng.integers(0, 500, (16, 24, 24)).astype(np.uint64) * 3
    path = str(tmp_path / "d.n5")
    file_reader(path).create_dataset("seg", data=labels, chunks=(4, 12, 12))
    config_dir = str(tmp_path / "configs")
    tmp_folder = str(tmp_path / "tmp")
    cfg.write_global_config(
        config_dir,
        {"block_shape": [4, 12, 12], "num_processes": 2,
         "peer_wait_timeout_s": 120.0},
    )
    script = str(tmp_path / "driver.py")
    with open(script, "w") as f:
        f.write(
            "import sys\n"
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "from cluster_tools_tpu.runtime import build\n"
            "from cluster_tools_tpu.workflows import UniqueWorkflow\n"
            f"wf = UniqueWorkflow({tmp_folder!r}, {config_dir!r},\n"
            f"    input_path={path!r}, input_key='seg',\n"
            f"    output_path={path!r}, output_key='uniques')\n"
            "assert build([wf])\n"
        )
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # keep workers off the accelerator tunnel
    env["JAX_PLATFORMS"] = "cpu"
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.abspath(cfg.__file__))
    )
    env["PYTHONPATH"] = (
        os.path.dirname(pkg_root) + os.pathsep + env.get("PYTHONPATH", "")
    )

    procs = []
    for pid in range(2):
        penv = dict(env)
        penv["CTT_PROCESS_ID"] = str(pid)
        procs.append(
            subprocess.Popen(
                [sys.executable, script], env=penv,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            )
        )
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err.decode()[-2000:]
    got = file_reader(path, "r")["uniques"][:]
    np.testing.assert_array_equal(got, np.unique(labels))
    # both processes really did disjoint shares
    statuses = os.listdir(os.path.join(tmp_folder, "status"))
    assert "find_uniques.p0.status.json" in statuses
    assert "find_uniques.p1.status.json" in statuses
    import json as _json

    s0 = _json.load(open(os.path.join(tmp_folder, "status",
                                      "find_uniques.p0.status.json")))
    s1 = _json.load(open(os.path.join(tmp_folder, "status",
                                      "find_uniques.p1.status.json")))
    assert s0["done"] and s1["done"]
    assert not set(s0["done"]) & set(s1["done"])


def test_peer_abort_fails_waiters_fast(tmp_path):
    """A peer that recorded an abort fails the barrier immediately (not after
    the full peer_wait_timeout_s)."""
    import time

    from cluster_tools_tpu.runtime.task import FailedBlocksError, Target, Task

    cfg.write_global_config(str(tmp_path / "configs"), {"num_processes": 2})
    t = Task(str(tmp_path / "tmp"), str(tmp_path / "configs"))
    aborted = Target(str(tmp_path / "tmp/status/task.p1.status.json"))
    aborted.write({"complete": False, "aborted": True, "error": "boom"})
    t0 = time.time()
    with pytest.raises(FailedBlocksError, match="peer process aborted"):
        t._peer_wait([aborted], timeout_s=60.0, what="peers")
    assert time.time() - t0 < 5.0  # fail-fast, not the 60s timeout
