"""Slurm/LSF executor tests against a stub scheduler.

The reference has no scheduler mocks ("multi-node is tested by the same code
path with the target switched", SURVEY.md §4); this is the fake-scheduler
seam it lacked: a stand-in ``sbatch``/``bsub`` runs each job script
synchronously, the stand-in queue reports empty, and the whole
submit → poll → per-job status → aggregate path is exercised for real.
"""

import os
import stat

import numpy as np
import pytest

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader


def _write_stub_scheduler(folder):
    """sbatch/bsub stand-in: strips scheduler flags, runs the job script
    synchronously.  squeue/bjobs stand-in: reports no queued jobs."""
    os.makedirs(folder, exist_ok=True)
    submit = os.path.join(folder, "stub_submit")
    with open(submit, "w") as f:
        f.write(
            "#!/bin/bash\n"
            "# last argument is the job script\n"
            'script="${@: -1}"\n'
            'bash "$script" > /dev/null 2>&1\n'
            'echo "Submitted batch job 1"\n'
        )
    queue = os.path.join(folder, "stub_queue")
    with open(queue, "w") as f:
        f.write("#!/bin/bash\nexit 0\n")
    for p in (submit, queue):
        os.chmod(p, os.stat(p).st_mode | stat.S_IEXEC)
    return submit, queue


WORKER_ENV = {
    # keep the worker off the accelerator tunnel: unset the axon pool so the
    # sitecustomize platform plugin stays unregistered, force the cpu backend
    "PALLAS_AXON_POOL_IPS": "",
    "JAX_PLATFORMS": "cpu",
}


@pytest.mark.parametrize("target", ["slurm", "lsf"])
def test_cluster_target_runs_workflow(tmp_path, rng, target):
    from cluster_tools_tpu.workflows import UniqueWorkflow

    submit, queue = _write_stub_scheduler(str(tmp_path / "sched"))
    labels = rng.integers(0, 100, (16, 24, 24)).astype(np.uint64)
    path = str(tmp_path / "d.n5")
    file_reader(path).create_dataset("seg", data=labels, chunks=(8, 12, 12))
    config_dir = str(tmp_path / "configs")
    tmp_folder = str(tmp_path / "tmp")
    cfg.write_global_config(
        config_dir,
        {
            "block_shape": [8, 12, 12],
            "target": target,
            "max_jobs": 3,
            "poll_interval_s": 0.05,
            "sbatch_cmd": submit,
            "squeue_cmd": queue,
            "bsub_cmd": submit,
            "bjobs_cmd": queue,
            "worker_env": WORKER_ENV,
        },
    )
    wf = UniqueWorkflow(
        tmp_folder, config_dir, max_jobs=3,
        input_path=path, input_key="seg",
        output_path=path, output_key="uniques",
    )
    assert build([wf])
    got = file_reader(path, "r")["uniques"][:]
    np.testing.assert_array_equal(got, np.unique(labels))
    # the per-block task really went through scheduler jobs
    job_dir = os.path.join(tmp_folder, "cluster_jobs", "find_uniques")
    statuses = [f for f in os.listdir(job_dir) if f.endswith(".status.json")]
    assert 1 <= len(statuses) <= 3


def test_cluster_failure_surfaces_failed_blocks(tmp_path, rng):
    """A worker whose task raises reports its blocks failed; the task layer
    then raises FailedBlocksError (no silent success)."""
    from cluster_tools_tpu.runtime.task import FailedBlocksError
    from cluster_tools_tpu.tasks.ilastik import IlastikPredictionTask

    submit, queue = _write_stub_scheduler(str(tmp_path / "sched"))
    path = str(tmp_path / "d.n5")
    file_reader(path).create_dataset(
        "raw", data=rng.random((8, 8, 8)).astype(np.float32)
    )
    config_dir = str(tmp_path / "configs")
    cfg.write_global_config(
        config_dir,
        {
            "block_shape": [8, 8, 8],
            "target": "slurm",
            "poll_interval_s": 0.05,
            "sbatch_cmd": submit,
            "squeue_cmd": queue,
            "worker_env": WORKER_ENV,
        },
    )
    # project exists so DAG-build passes; the executable is missing, so every
    # worker block fails at run time
    ilastik_folder = str(tmp_path / "noilastik")
    os.makedirs(ilastik_folder)
    task = IlastikPredictionTask(
        str(tmp_path / "tmp"), config_dir,
        input_path=path, input_key="raw",
        ilastik_folder=ilastik_folder,
        ilastik_project=path,
    )
    with pytest.raises((FailedBlocksError, RuntimeError)):
        task.run()
