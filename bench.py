#!/usr/bin/env python
"""Benchmark: DT-watershed voxels/sec/chip (the BASELINE.md headline metric).

Runs the fused per-block DT-watershed XLA program (threshold → EDT → seeds →
height map → seeded flood → size filter) on the default device (the TPU chip
under the driver) over a CREMI-like synthetic boundary volume, and compares
against a single-core host implementation of the same pipeline (scipy EDT +
gaussian + maxima + heapq priority-flood — the moral equivalent of the
reference's vigra path, which is not installable here; reference
cluster_tools/watershed/watershed.py:286-344).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import argparse
import heapq
import json
import sys
import time

import numpy as np
from scipy import ndimage


def make_volume(shape, seed=0):
    rng = np.random.default_rng(seed)
    raw = ndimage.gaussian_filter(rng.random(shape), (1.0, 4.0, 4.0))
    raw = (raw - raw.min()) / (raw.max() - raw.min())
    return raw.astype(np.float32)


# ---------------------------------------------------------------------------
# host baseline: the reference's per-block pipeline with scipy + heapq flood
# ---------------------------------------------------------------------------


def cpu_watershed_flood(hmap, seeds, mask):
    """Sequential priority-flood (vigra watershedsNew equivalent)."""
    labels = seeds.copy()
    visited = seeds > 0
    heap = []
    coords = np.argwhere(seeds > 0)
    for z, y, x in coords:
        heapq.heappush(heap, (hmap[z, y, x], z, y, x))
    shape = hmap.shape
    offs = [(-1, 0, 0), (1, 0, 0), (0, -1, 0), (0, 1, 0), (0, 0, -1), (0, 0, 1)]
    while heap:
        h, z, y, x = heapq.heappop(heap)
        lab = labels[z, y, x]
        for dz, dy, dx in offs:
            nz, ny, nx = z + dz, y + dy, x + dx
            if not (0 <= nz < shape[0] and 0 <= ny < shape[1] and 0 <= nx < shape[2]):
                continue
            if visited[nz, ny, nx] or not mask[nz, ny, nx]:
                continue
            visited[nz, ny, nx] = True
            labels[nz, ny, nx] = lab
            heapq.heappush(heap, (hmap[nz, ny, nx], nz, ny, nx))
    return labels


def cpu_dt_watershed(x, threshold=0.5, sigma_seeds=2.0, sigma_weights=2.0, alpha=0.8):
    fg = x < threshold
    dt = ndimage.distance_transform_edt(fg).astype(np.float32)
    smoothed = ndimage.gaussian_filter(dt, sigma_seeds)
    maxima = (ndimage.maximum_filter(smoothed, 3) == smoothed) & (dt > 0)
    seeds, _ = ndimage.label(maxima, structure=np.ones((3, 3, 3)))
    dtn = (dt - dt.min()) / max(dt.max() - dt.min(), 1e-6)
    hmap = ndimage.gaussian_filter(alpha * x + (1 - alpha) * (1 - dtn), sigma_weights)
    return cpu_watershed_flood(hmap, seeds.astype(np.int32), fg)


# ---------------------------------------------------------------------------


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true", help="small shapes")
    parser.add_argument("--repeats", type=int, default=5)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from cluster_tools_tpu.ops.watershed import dt_watershed

    # block geometry: reference test block shape is [32, 256, 256]
    # (test/base.py:28); quick mode shrinks it
    shape = (16, 64, 64) if args.quick else (32, 256, 256)
    vol = make_volume(shape)
    vox = float(np.prod(shape))

    params = dict(
        threshold=0.5,
        apply_dt_2d=False,
        apply_ws_2d=False,
        sigma_seeds=2.0,
        sigma_weights=2.0,
        alpha=0.8,
        size_filter=25,
    )

    x = jnp.asarray(vol)
    labels, _ = dt_watershed(x, **params)  # compile
    labels.block_until_ready()
    t0 = time.time()
    for _ in range(args.repeats):
        labels, _ = dt_watershed(x, **params)
        labels.block_until_ready()
    t_device = (time.time() - t0) / args.repeats
    device_voxps = vox / t_device

    # host baseline on a smaller crop, scaled by voxel count (the flood is
    # O(n log n); slight optimism in the baseline's favor)
    base_shape = (16, 64, 64) if not args.quick else (8, 32, 32)
    base_vol = vol[tuple(slice(0, s) for s in base_shape)]
    t0 = time.time()
    cpu_dt_watershed(base_vol, **{k: params[k] for k in
                                  ("threshold", "sigma_seeds", "sigma_weights", "alpha")})
    t_host = time.time() - t0
    host_voxps = float(np.prod(base_shape)) / t_host

    result = {
        "metric": "dt_watershed_throughput",
        "value": round(device_voxps / 1e6, 3),
        "unit": "Mvox/s/chip",
        "vs_baseline": round(device_voxps / host_voxps, 2),
        "detail": {
            "block_shape": list(shape),
            "device": str(jax.devices()[0]),
            "device_ms_per_block": round(t_device * 1e3, 1),
            "host_baseline_Mvox_s": round(host_voxps / 1e6, 3),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
