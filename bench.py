#!/usr/bin/env python
"""Benchmark: the five BASELINE.md configs against honest host baselines.

Headline metric (the JSON line's ``value``): DT-watershed voxels/sec/chip for
the fused per-block XLA program (threshold → EDT → seeds → height map → seeded
flood → size filter), measured on the default jax device.  ``vs_baseline`` is
the ratio against a **single-core C++** implementation of the same pipeline
(Felzenszwalb EDT + separable gaussian + 3x3 maxima + priority-flood —
``native.dt_watershed_cpu``, the moral equivalent of the reference's vigra
path, reference cluster_tools/watershed/watershed.py:286-344).

The ``extra`` field carries the remaining BASELINE.md configs:
  * ``dtws_batched``  — the same program vmapped over a block batch
    (``device_batch_size`` pipelining, one dispatch for the whole batch)
  * ``cc``            — thresholded connected components (XLA pointer-jumping
    CC) vs single-core scipy.ndimage.label (C)
  * ``mws``           — **kernel-only**: per-block mutex watershed (the
    framework's native C++ kernel, reference affogato equivalent) vs the same
    kernel whole-volume single-core.  Cross-block stitching is *excluded* on
    the blocked side, so this measures kernel throughput under block
    decomposition, not the full consistent-labeling pipeline (which the
    ``e2e`` config covers for multicut)
  * ``rag``           — RAG extraction + 10-feature edge accumulation vs the
    single-core vectorized numpy path (reference
    ndist.extractBlockFeaturesFromBoundaryMaps)
  * ``infer``         — 3D U-Net forward throughput (the MXU workload:
    bf16 convs), jax/flax predictor vs the identical model on the host
    XLA-CPU backend
  * ``ws_e2e``        — the WatershedWorkflow alone, tpu vs cpu-local
    (cold + jit-cache-warm) — the literal BASELINE.md north-star workload
  * ``e2e_multicut``  — full MulticutSegmentationWorkflow wall-clock,
    ``target='tpu'`` on the default device vs the identical workflow with
    ``target='local'`` forced onto the host XLA-CPU backend in a subprocess
    (the reference's deployment model: all-cores local execution,
    cluster_tasks.py:514-555); plus the same pipeline with
    ``sharded_problem=True, sharded_ws=True`` (since round 5: the
    device-resident collective front — fused watershed+RAG session, one
    volume upload — plus global solve) as ``e2e_sharded_problem_wall_s``

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
"""

import argparse
import json
from functools import partial
import os
import subprocess
import sys
import tempfile
import time

import numpy as np
from scipy import ndimage


def log(msg):
    print(msg, file=sys.stderr, flush=True)


DEFAULT_BENCH_DEADLINE_S = 2400.0


def parse_deadline_env(env=None):
    """CTT_BENCH_DEADLINE_S as a positive finite float, else the default.

    The deadline guards the unlosable-contract machinery; a malformed value
    from a driver/CI template must degrade to the default with a warning,
    never crash the bench before the first JSON line."""
    raw = (os.environ if env is None else env).get("CTT_BENCH_DEADLINE_S")
    if raw is None:
        return DEFAULT_BENCH_DEADLINE_S
    try:
        value = float(raw)
    except (TypeError, ValueError):
        log(f"[bench] invalid CTT_BENCH_DEADLINE_S={raw!r} (not a number); "
            f"using default {DEFAULT_BENCH_DEADLINE_S:.0f}s")
        return DEFAULT_BENCH_DEADLINE_S
    if not (value > 0.0) or value != value or value == float("inf"):
        log(f"[bench] invalid CTT_BENCH_DEADLINE_S={raw!r} (must be a "
            f"positive finite number); using default "
            f"{DEFAULT_BENCH_DEADLINE_S:.0f}s")
        return DEFAULT_BENCH_DEADLINE_S
    return value


def make_volume(shape, seed=0, boundary_frac=0.12):
    """CREMI-like smooth boundary-probability volume.

    BASELINE.md defines the north-star metric on CREMI sample-A boundary
    maps; no CREMI data exists in this environment, so the fixture is
    anisotropic gaussian-filtered noise *calibrated to CREMI statistics*:
    the percentile remap pins the above-threshold (membrane) fraction to
    ``boundary_frac`` (CREMI-A membrane maps: thin sheets, ~10-15% of
    voxels above 0.5; uncalibrated blurred noise sat at 27.6%).  Measured
    on the 32x256x256 bench block after calibration: 12.0% boundary,
    ~60-95 DT-WS fragments per 256^2 slice (mean fragment 909 vox, median
    621), ~9.9k RAG edges — inside the plausible range of the reference's
    CREMI-A oversegmentation at its own [32, 256, 256] test block
    (reference test/base.py:28).  The measured values ride the contract as
    ``fixture_*`` fields so any future fixture drift is visible."""
    rng = np.random.default_rng(seed)
    raw = ndimage.gaussian_filter(rng.random(shape), (1.0, 4.0, 4.0))
    raw = (raw - raw.min()) / (raw.max() - raw.min())
    q = np.quantile(raw, 1.0 - boundary_frac)
    raw = np.clip(raw * (0.5 / q), 0.0, 1.0)
    return raw.astype(np.float32)


def _host_sync(r):
    """Force completion by READING a result element back to host.

    ``block_until_ready`` through the axon tunnel acknowledges the dispatch
    without waiting for remote execution (observed: "0.0 ms" floods of
    2 Mvox), so any timing that ends at block_until_ready measures dispatch
    latency, not the kernel.  A device→host fetch of even one element cannot
    complete until the producing program has actually run.  All outputs of a
    jitted call come from one executable, so fetching from the first array
    leaf suffices.  Host-side results (numpy) pass through at no cost."""
    import jax

    for leaf in jax.tree_util.tree_leaves(r):
        if hasattr(leaf, "ravel"):
            arr = leaf.ravel()
            np.asarray(arr[:1] if arr.shape else arr)
            return r
    return r


def fetch_floor_s(repeats: int = 5) -> float:
    """Median round-trip of a tiny ready-array host fetch — the additive
    floor `_host_sync` puts under every timed call on a tunneled backend
    (~0 on a local device).  Report it next to sub-10ms kernel timings."""
    import jax.numpy as jnp

    x = jnp.arange(8, dtype=jnp.int32)
    warm = x + jnp.int32(100)  # same shape/dtype, DIFFERENT buffer
    np.asarray(x[:1])  # materialize x itself
    # Pre-compile every distinct slice start (each start is its own sliced
    # executable; timing a first-time compile would overstate the floor) —
    # but warm on a DIFFERENT input array: executables are shared per
    # (program, shape) while any remote execution-result cache is keyed on
    # the input, so each timed call below is a first execution of
    # (program_i, x) and cannot be served from cache.
    for i in range(min(repeats, 8)):
        np.asarray(warm[i % 8 : i % 8 + 1])
    samples = []
    for i in range(repeats):
        t0 = time.perf_counter()
        np.asarray(x[i % 8 : i % 8 + 1])
        samples.append(time.perf_counter() - t0)
    return float(np.median(samples))


def timeit(fn, repeats, *, sync=None, variants=None):
    """Best-of-``repeats`` wall-clock seconds per call.

    Every timed call ends in ``_host_sync`` (a one-element device→host
    fetch) — ``sync`` (e.g. block_until_ready on the right output) still
    runs first when given, but completion is only trusted once data crossed
    back to the host (see `_host_sync`: the tunnel acks block_until_ready
    early).  The fetch adds `fetch_floor_s()` per call — amortize or
    subtract when timing sub-10ms kernels.

    ``variants`` (optional): zero-arg callables over *distinct* inputs.
    Variant 0 is the sacrificial warmup (compile only — its input is never
    timed); each timed round then consumes ONE not-yet-executed variant, so
    no timed dispatch ever repeats an input this process has executed.
    Repeat calls on identical inputs can be served from an execution-result
    cache on remote-tunneled backends (observed on axon: ~0 ms "runs" of a
    2 Mvox flood), which would report cache latency as kernel time; warming
    up on the timed inputs would re-populate exactly that cache, hence the
    sacrificial variant.  Rounds are capped at ``len(variants) - 1`` — pass
    ``repeats + 1`` variants for the full count (``_rolled(x, repeats + 1)``).
    """
    if not variants:
        r = fn()  # warmup / compile
        if sync is not None:
            sync(r)
        _host_sync(r)
        best = float("inf")
        for _ in range(max(repeats, 1)):
            t0 = time.perf_counter()
            r = fn()
            if sync is not None:
                sync(r)
            _host_sync(r)
            best = min(best, time.perf_counter() - t0)
        return best

    r = variants[0]()  # warmup / compile (same shapes -> one compilation)
    if sync is not None:
        sync(r)
    _host_sync(r)
    best = float("inf")
    for c in variants[1 : max(repeats, 1) + 1]:
        t0 = time.perf_counter()
        r = c()
        if sync is not None:
            sync(r)
        _host_sync(r)
        best = min(best, time.perf_counter() - t0)
    return best


def _rolled(x, n, axis=1, start=0):
    """n distinct same-shape variants of a volume (rolled along ``axis``) —
    statistically identical workloads for ``timeit(variants=...)``.  The
    first returned element is unshifted when ``start == 0`` (the sacrificial
    warmup slot); ``start`` offsets the roll sequence so disjoint slices can
    be built lazily per sweep mode."""
    return [
        np.roll(x, 7 * i, axis=axis) if i else x
        for i in range(start, start + n)
    ]


def rolled_pair_variants(x, labels, n, call):
    """n ``timeit`` variants over (labels, volume) pairs rolled in lockstep
    (index 0 unshifted — the warmup slot): distinct inputs at zero extra
    segmentation cost, identical label↔intensity correspondence everywhere
    except the wrap seam.  ``call(labels_dev, volume_dev)`` runs the kernel."""
    import jax.numpy as jnp

    out = []
    for i in range(n):
        lab = np.roll(labels, 7 * i, axis=1) if i else labels
        vol = np.roll(x, 7 * i, axis=1) if i else x
        out.append(
            (lambda l, v: lambda: call(l, v))(jnp.asarray(lab), jnp.asarray(vol))
        )
    return out


# ---------------------------------------------------------------------------


def _sweep_then_headline(x, crop_dims, repeats, make_input, call):
    """Shared sweep-mode scaffolding of the dtws/cc configs: compare the
    modes with ONE warm call each on a crop (the losing mode on a
    work-bound backend can be orders of magnitude slower per call —
    measured 136 s vs 12 s at the calibrated full shape), then time the
    full-shape headline with full repeats in the winning mode only.

    Roll-index budget (the never-re-dispatch-an-executed-input invariant of
    ``timeit``): sweep uses rolls 0..3, headline 4..4+repeats; callers
    needing more variants (e.g. the pallas CC block) start at
    ``repeats + 5``.  Returns ``(t_dev_s, mode, {mode: crop_seconds})``."""
    from cluster_tools_tpu.ops import _backend

    crop = x[tuple(slice(0, min(s, c)) for s, c in zip(x.shape, crop_dims))]

    def measure(i):
        inputs = [make_input(v) for v in _rolled(crop, 2, start=i * 2)]
        return timeit(
            None, 1,
            sync=lambda r: jax_first_leaf_block(r),
            variants=[(lambda m: lambda: call(m))(m) for m in inputs],
        )

    _, mode, times = _best_sweep_mode(measure)
    span = repeats + 1
    with _backend.force_sweep_mode(mode):
        inputs = [make_input(v) for v in _rolled(x, span, start=4)]
        t_dev = timeit(
            None, repeats,
            sync=lambda r: jax_first_leaf_block(r),
            variants=[(lambda m: lambda: call(m))(m) for m in inputs],
        )
        del inputs  # release the headline span's HBM before any follow-up
    return t_dev, mode, times


def jax_first_leaf_block(r):
    """block_until_ready on the first array leaf (the ``sync`` the dtws/cc
    timings used individually)."""
    leaf = r[0] if isinstance(r, tuple) else r
    return leaf.block_until_ready()


def _best_sweep_mode(measure):
    """Measure a kernel under both sweep modes (the assoc-vs-seq choice of
    ops/_backend.py is backend-perf-dependent) and return
    ``(best_seconds, best_mode, {mode: seconds})``.  The winning mode is an
    achievable production configuration (pin it with CTT_SWEEP_MODE=<mode>)
    and is reported alongside what the unpinned default would pick — bench is
    self-tuning but transparent.

    ``measure`` receives the mode index (0/1) so it can hand each mode a
    disjoint slice of distinct inputs — the second mode must not re-dispatch
    inputs the first already executed (see ``timeit``'s cache note)."""
    from cluster_tools_tpu.ops import _backend

    times = {}
    for i, mode in enumerate(("assoc", "seq")):
        with _backend.force_sweep_mode(mode):
            times[mode] = measure(i)
    best = min(times, key=times.get)
    return times[best], best, times


def _suspect_throughput(mvox, extra, key):
    """Flag implausible per-chip rates (non-blocking sync on a half-dead
    tunnel would report dispatch latency as kernel time — no single chip
    floods 50 Gvox/s)."""
    if mvox > 50_000:
        extra[key] = True
        log(f"[{key}] WARNING: implausible throughput, timing suspect")


def bench_dtws(x, repeats):
    """Fused device DT-watershed vs single-core C++ (native.dt_watershed_cpu).

    The assoc-vs-seq sweep comparison runs on a small CROP of the fixture
    (one warm call per mode): the losing mode on a work-bound backend can
    be two orders of magnitude slower per call (measured on the CPU
    fallback at the CREMI-calibrated full shape: assoc 136 s vs seq 12 s
    warm — round-dominated), and paying full repeats at full shape for a
    mode that loses would eat the whole config budget.  The headline
    number then gets full repeats at full shape in the WINNING mode;
    ``dtws_{assoc,seq}_ms`` report the crop-shape comparison.  (On chip,
    tools/tpu_validate.py independently compares the modes at full shape.)
    """
    import jax
    import jax.numpy as jnp

    from cluster_tools_tpu import native
    from cluster_tools_tpu.ops import _backend
    from cluster_tools_tpu.ops.watershed import dt_watershed

    t_dev, mode, times = _sweep_then_headline(
        x, (16, 128, 128), repeats,
        make_input=lambda v: jax.device_put(jnp.asarray(v)),
        call=lambda v: dt_watershed(v, threshold=0.5),
    )
    host_seg, _ = native.dt_watershed_cpu(x, threshold=0.5)  # warmup + stats
    t_host = timeit(
        lambda: native.dt_watershed_cpu(x, threshold=0.5), max(repeats // 2, 1)
    )
    mvox = x.size / t_dev / 1e6
    log(
        f"[dtws] device {t_dev*1e3:.1f} ms ({mvox:.1f} Mvox/s, sweep={mode}, "
        f"assoc {times['assoc']*1e3:.1f} / seq {times['seq']*1e3:.1f} ms)  "
        f"C++ 1-core {t_host*1e3:.1f} ms ({x.size/t_host/1e6:.1f} Mvox/s)"
    )
    # fixture calibration evidence (see make_volume): fragment/boundary
    # statistics of the exact volume the headline number is measured on
    # (reuses the seg the host-timing warmup just computed — no extra run)
    frag_sizes = np.bincount(host_seg.ravel())[1:]
    frag_sizes = frag_sizes[frag_sizes > 0]
    extra = {
        "dtws_sweep_mode": mode,
        "dtws_default_mode": "assoc" if _backend.use_assoc() else "seq",
        "dtws_assoc_ms": round(times["assoc"] * 1e3, 1),
        "dtws_seq_ms": round(times["seq"] * 1e3, 1),
        "fixture_boundary_frac": round(float((x > 0.5).mean()), 3),
        "fixture_n_fragments": int(len(frag_sizes)),
        "fixture_mean_fragment_vox": (
            round(float(frag_sizes.mean()), 1) if len(frag_sizes) else 0.0
        ),
    }
    _suspect_throughput(mvox, extra, "dtws_timing_suspect")
    return mvox, t_host / t_dev, extra


def bench_dtws_batched(x, batch, repeats):
    """One vmapped dispatch over a block batch (device_batch_size pipelining)."""
    import jax
    import jax.numpy as jnp

    from cluster_tools_tpu.ops.watershed import dt_watershed

    on_cpu = jax.default_backend() == "cpu"
    if on_cpu:
        # the work-bound CPU fallback (dead tunnel) blows the config's time
        # budget at full batch x repeats — shrink instead of skipping, so a
        # fallback run still reports a (flagged) number
        batch = min(batch, 2)
        repeats = min(repeats, 1)
        log(f"[dtws_batched] cpu backend: shrunk to batch={batch}, "
            f"repeats={repeats}")

    # distinct stack per timed round (+1 warmup), built on device inside
    # measure() so only one mode's span is HBM-resident at a time (a flat
    # 2*(repeats+1)-stack pool would hold ~100 block volumes); rolls differ
    # across modes, rounds, AND the blocks inside a stack
    span = repeats + 1
    fn = jax.jit(jax.vmap(lambda v: dt_watershed(v, threshold=0.5)[0]))

    def measure(i):
        stacks = [
            jnp.stack([jnp.asarray(np.roll(x, 997 * i + 101 * r + 7 * j, axis=1))
                       for j in range(batch)])
            for r in range(span)
        ]
        return timeit(
            None, repeats, sync=lambda r: r.block_until_ready(),
            variants=[(lambda s: lambda: fn(s))(s) for s in stacks],
        )

    if on_cpu:
        # one mode only on the fallback: the losing assoc mode costs
        # minutes per batched call at the calibrated full shape (the
        # dtws config already reports the mode comparison from its crop)
        t = measure(0)
        mode_note = "default (no sweep run on the fallback)"
    else:
        t, mode, _ = _best_sweep_mode(measure)
        mode_note = mode
    mvox = batch * x.size / t / 1e6
    log(f"[dtws_batched x{batch}] {t*1e3:.1f} ms ({mvox:.1f} Mvox/s, "
        f"sweep={mode_note})")
    return mvox


def bench_cc(x, repeats):
    """Thresholded connected components: XLA CC vs scipy.ndimage.label.

    ctt-cc contract: the headline follows the DEFAULT dispatch
    (``_backend.use_coarse_cc()`` — flat seq-sweep on the CPU fallback,
    coarse-to-fine on TPU), and ``extra`` records BOTH paths on the same
    fixture (``cc_flat_*`` / ``cc_coarse_*`` + the winning tile of a small
    tile sweep) plus the fixpoint round counts on the bench fixture and the
    serpentine worst case, so the r06+ trajectory shows the flat/coarse
    before/after regardless of which one a backend defaults to."""
    import jax.numpy as jnp

    from cluster_tools_tpu.ops import _backend as ctt_backend
    from cluster_tools_tpu.ops.cc import (
        connected_components,
        connected_components_coarse_raw,
        connected_components_raw_with_iters,
        resolve_coarse_tile,
        serpentine_mask,
    )

    mask_np = x < 0.5
    t_dev, mode, times = _sweep_then_headline(
        x, (32, 256, 256), repeats,
        make_input=lambda v: jnp.asarray(v < 0.5),
        call=lambda m: connected_components(m, connectivity=1),
    )
    t_host = timeit(lambda: ndimage.label(mask_np), max(repeats // 2, 1))
    mvox = x.size / t_dev / 1e6
    log(
        f"[cc] device {t_dev*1e3:.1f} ms ({mvox:.1f} Mvox/s, sweep={mode})  "
        f"scipy 1-core {t_host*1e3:.1f} ms"
    )
    extra = {}
    import jax

    # -- flat vs coarse on the same fixture (+ tile sweep) -------------------
    m_dev = jnp.asarray(mask_np)
    extra["cc_default_mode"] = (
        "coarse" if ctt_backend.use_coarse_cc() else "flat"
    )
    reps = max(repeats // 2, 1)
    span = reps + 1
    # distinct-input variants per timing (the _rolled result-cache idiom);
    # roll indices start past the headline's and the pallas block's budgets
    base = 2 * repeats + 12

    def _variants(start, call):
        return [
            (lambda m: lambda: call(m))(jnp.asarray(v < 0.5))
            for v in _rolled(x, span, start=start)
        ]

    sync = lambda r: r[0].block_until_ready()  # noqa: E731
    with ctt_backend.force_cc_mode("flat"):
        t_flat = timeit(
            None, reps, sync=sync,
            variants=_variants(base, connected_components),
        )
        _, it_flat = jax.block_until_ready(
            connected_components_raw_with_iters(m_dev)
        )
    extra["cc_flat_mvox_s"] = round(x.size / t_flat / 1e6, 3)
    extra["cc_flat_vs_baseline"] = round(t_host / t_flat, 3)
    extra["cc_fixpoint_iters_flat"] = int(it_flat)

    sweep_tiles = {resolve_coarse_tile(x.shape, None)}
    sweep_tiles.update(
        resolve_coarse_tile(x.shape, t)
        for t in ((8, 64, 64), (16, 128, 128), (32, 256, 256))
    )
    best = None
    tile_sweep = {}
    for i, tile in enumerate(sorted(sweep_tiles)):
        t_c = timeit(
            None, reps, sync=sync,
            variants=_variants(
                base + span * (i + 1),
                lambda m, t=tile: connected_components(m, coarse_tile=t),
            ),
        )
        tile_sweep[",".join(map(str, tile))] = round(x.size / t_c / 1e6, 3)
        if best is None or t_c < best[1]:
            best = (tile, t_c)
    tile, t_coarse = best
    _, stats = jax.block_until_ready(
        connected_components_coarse_raw(m_dev, 1, None, False, tile)
    )
    extra["cc_coarse_mvox_s"] = round(x.size / t_coarse / 1e6, 3)
    extra["cc_coarse_vs_baseline"] = round(t_host / t_coarse, 3)
    extra["cc_coarse_tile"] = list(tile)
    extra["cc_tile_sweep"] = tile_sweep
    extra["cc_fixpoint_iters_coarse"] = int(stats["fixpoint_iters"])
    extra["cc_live_tile_rounds"] = int(stats["live_tile_rounds"])
    extra["cc_merge_pairs"] = int(stats["merge_pairs"])
    log(
        f"[cc] flat {t_flat*1e3:.1f} ms ({it_flat} rounds)  "
        f"coarse {t_coarse*1e3:.1f} ms (tile {tile}, "
        f"{int(stats['fixpoint_iters'])} rounds)  default="
        f"{extra['cc_default_mode']}"
    )

    # serpentine worst case: the structural round-count win (tile-bounded
    # vs diameter-bounded) that the random fixture cannot show
    serp = jnp.asarray(serpentine_mask((4, 128, 128)))
    _, it_s_flat = jax.block_until_ready(
        connected_components_raw_with_iters(serp)
    )
    s_tile = resolve_coarse_tile(serp.shape, None)
    _, s_stats = jax.block_until_ready(
        connected_components_coarse_raw(serp, 1, None, False, s_tile)
    )
    extra["cc_serpentine_iters_flat"] = int(it_s_flat)
    extra["cc_serpentine_iters_coarse"] = int(s_stats["fixpoint_iters"])
    log(
        f"[cc] serpentine rounds: flat {int(it_s_flat)} -> coarse "
        f"{int(s_stats['fixpoint_iters'])}"
    )

    if jax.default_backend() == "tpu" and not (
        x.shape[1] % 8 or x.shape[2] % 128
    ):
        # the VMEM-resident per-slice kernel + z-merge — candidate default
        # (tools/tpu_validate.py decides; this records its bench-volume rate)
        from cluster_tools_tpu.ops.pallas_cc import pallas_connected_components

        try:
            span = repeats + 1
            t_pal = timeit(
                None, repeats,
                sync=lambda r: r[0].block_until_ready(),
                variants=[
                    (lambda m: lambda: pallas_connected_components(m))(m)
                    for m in (
                        jnp.asarray(v < 0.5)
                        # first roll index past the headline's 4..4+repeats
                        # (see _sweep_then_headline's roll-index budget)
                        for v in _rolled(x, span, start=repeats + 5)
                    )
                ],
            )
            extra["cc_pallas_mvox_s"] = round(x.size / t_pal / 1e6, 3)
            log(f"[cc] pallas {t_pal*1e3:.1f} ms "
                f"({x.size/t_pal/1e6:.1f} Mvox/s)")
        except Exception as e:
            extra["cc_pallas_error"] = f"{type(e).__name__}: {e}"[:200]
            log(f"[cc] pallas FAILED: {e}")
    return mvox, t_host / t_dev, extra


def bench_mws(shape, repeats):
    """Kernel-only blocked MWS vs whole-volume 1-core (no stitching on the
    blocked side — see module docstring)."""
    from cluster_tools_tpu.ops.mws import compute_mws_segmentation
    from cluster_tools_tpu.utils.blocking import Blocking

    offsets = [
        [-1, 0, 0], [0, -1, 0], [0, 0, -1],
        [-2, 0, 0], [0, -4, 0], [0, 0, -4],
    ]
    rng = np.random.default_rng(1)
    affs = ndimage.gaussian_filter(
        rng.random((len(offsets),) + tuple(shape)).astype(np.float32),
        (0, 1, 2, 2),
    )
    strides = [1, 2, 2]
    n_vox = int(np.prod(shape))

    t_host = timeit(
        lambda: compute_mws_segmentation(affs, offsets, strides=strides),
        max(repeats // 2, 1),
    )

    block_shape = tuple(max(s // 2, 1) for s in shape)
    blocking = Blocking(shape, block_shape)

    def blocked():
        for bid in range(blocking.n_blocks):
            bb = blocking.block(bid).slicing
            compute_mws_segmentation(
                affs[(slice(None),) + bb], offsets, strides=strides
            )

    t_blocked = timeit(blocked, max(repeats // 2, 1))
    mvox = n_vox / t_blocked / 1e6

    # device formulation (mutually-best-edge parallel greedy,
    # ops/mws_device.py).  Round count is data-dependent (monotone
    # attractive chains serialize — see the kernel docstring), so this
    # variant runs on a SMALL sub-volume with a wall-clock guard: it
    # characterizes the kernel without eating the bench budget.  Fresh
    # noise per timed round so a remote execution cache cannot fake the
    # timing.
    from cluster_tools_tpu.ops import _backend

    dev_shape = tuple(min(s, c) for s, c in zip(shape, (8, 16, 16)))
    dev_affs = affs[(slice(None),) + tuple(slice(0, s) for s in dev_shape)]
    dev_vox = int(np.prod(dev_shape))
    dev_mvox = dev_err = None
    try:
        with _backend.force_mws_mode("device"):
            t0 = time.perf_counter()
            compute_mws_segmentation(dev_affs, offsets, strides=strides)
            warm = time.perf_counter() - t0
            if warm > 120.0:
                log(f"[mws] device variant skipped (warmup {warm:.0f}s > 120s)")
            else:
                t_device = timeit(
                    None, 2,
                    variants=[
                        partial(
                            compute_mws_segmentation, dev_affs, offsets,
                            strides=strides, noise_level=1e-4, seed=100 + i,
                        )
                        for i in range(3)
                    ],
                )
                dev_mvox = dev_vox / t_device / 1e6
                log(
                    f"[mws] device {t_device*1e3:.1f} ms on {dev_shape} "
                    f"({dev_mvox:.3f} Mvox/s)"
                )
    except Exception as e:  # experimental path must not sink the run
        dev_err = f"{type(e).__name__}: {e}"
        log(f"[mws] device variant failed: {dev_err}")
    log(
        f"[mws] blocked {t_blocked*1e3:.1f} ms ({mvox:.1f} Mvox/s)  "
        f"whole-volume 1-core {t_host*1e3:.1f} ms"
    )
    return mvox, t_host / t_blocked, dev_mvox, dev_err


def bench_rag(x, repeats):
    """RAG 10-feature accumulation over watershed supervoxels."""
    from cluster_tools_tpu import native
    from cluster_tools_tpu.ops import rag

    labels, _ = native.dt_watershed_cpu(x, threshold=0.5)
    labels = labels.astype(np.uint64)
    t_host = timeit(lambda: rag.boundary_edge_features(labels, x), repeats)
    dev_fn = getattr(rag, "boundary_edge_features_device", None)
    if dev_fn is None:
        # no device kernel yet: report the host rate honestly, no ratio
        mvox = x.size / t_host / 1e6
        log(f"[rag] no device kernel; host numpy 1-core {t_host*1e3:.1f} ms "
            f"({mvox:.1f} Mvox/s)")
        return mvox, None
    import jax.numpy as jnp

    # production (boundary_edge_features_tpu) packs the sort key whenever
    # the compact label space fits 15 bits — measure the same path
    from cluster_tools_tpu.ops.rag import (
        PACK_MAX_ID, count_boundary_samples, sample_capacity,
    )

    packed = int(labels.max()) <= PACK_MAX_ID
    # production sizing: pre-sort compaction capacity from the exact host
    # count (boundary_edge_features_tpu does the same) — maxed over the
    # rolled timing variants, whose wrap seam adds boundary faces the
    # unrolled volume does not have
    lab32 = labels.astype(np.int32)
    cap = sample_capacity(max(
        count_boundary_samples(np.roll(lab32, 7 * i, axis=1) if i else lab32)
        for i in range(repeats + 1)
    ))
    t_dev = timeit(
        None,
        repeats,
        sync=lambda r: r[0].block_until_ready(),
        variants=rolled_pair_variants(
            x, labels.astype(np.int32), repeats + 1,
            lambda l, v: dev_fn(
                l, v, max_edges=65536, packed=packed, max_samples=cap
            ),
        ),
    )
    mvox = x.size / t_dev / 1e6
    log(
        f"[rag] device {t_dev*1e3:.1f} ms ({mvox:.1f} Mvox/s)  "
        f"numpy 1-core {t_host*1e3:.1f} ms"
    )
    return mvox, t_host / t_dev


def bench_inference(repeats, shape=(32, 256, 256), quick=False):
    """3D U-Net forward throughput — the MXU workload (bf16 convs).

    The reference's inference subsystem is its production NN path
    (inference/inference.py; frameworks wrap external torch models); here
    the jax/flax UNet3D predictor runs the same block geometry.  Baseline:
    the IDENTICAL model on the host XLA-CPU backend in a subprocess (the
    same same-framework/local-backend methodology as the e2e configs)."""
    import jax
    import jax.numpy as jnp

    from cluster_tools_tpu.models.unet import UNet3D

    shrunk = not quick and jax.default_backend() == "cpu"
    if quick or shrunk:
        # the fallback pays ~a minute per full-shape conv forward on one
        # core — the quick geometry keeps the config inside its budget
        shape = (16, 128, 128)
    model = UNet3D(out_channels=3, initial_features=16, depth=3,
                   scale_factors=[[1, 2, 2], [2, 2, 2]])
    rng0 = jax.random.PRNGKey(0)
    x0 = jnp.zeros((1, 1) + shape, jnp.float32)
    params = model.init(rng0, x0)
    fwd = jax.jit(lambda p, v: model.apply(p, v))

    vol = make_volume(shape, seed=5)
    variants = [
        (lambda v: lambda: fwd(params, jnp.asarray(v[None, None])))(v)
        for v in _rolled(vol, repeats + 1)
    ]
    t_dev = timeit(None, repeats, variants=variants)
    mvox = np.prod(shape) / t_dev / 1e6
    res = {"infer_mvox_s": round(mvox, 3)}
    if shrunk:
        # a small-shape CPU number must not read as a full-shape chip
        # number, even outside driver mode (no platform key there)
        res["infer_shape"] = list(shape)
    _suspect_throughput(mvox, res, "infer_timing_suspect")

    here = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "infer_cpu.py")
        with open(script, "w") as f:
            f.write(
                "import json, os, sys, time\n"
                "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
                f"sys.path.insert(0, {here!r})\n"
                "import jax\n"
                "jax.config.update('jax_platforms', 'cpu')\n"
                "from cluster_tools_tpu.utils.compile_cache import "
                "enable_compile_cache\n"
                "enable_compile_cache()\n"  # fresh process, cached compiles
                "import jax.numpy as jnp\n"
                "import numpy as np\n"
                "from cluster_tools_tpu.models.unet import UNet3D\n"
                "from bench import make_volume, timeit\n"
                "model = UNet3D(out_channels=3, initial_features=16, "
                "depth=3, scale_factors=[[1, 2, 2], [2, 2, 2]])\n"
                f"shape = {tuple(shape)!r}\n"
                "x0 = jnp.zeros((1, 1) + shape, jnp.float32)\n"
                "params = model.init(jax.random.PRNGKey(0), x0)\n"
                "fwd = jax.jit(lambda p, v: model.apply(p, v))\n"
                "vol = make_volume(shape, seed=5)\n"
                "t = timeit(lambda: fwd(params, "
                "jnp.asarray(vol[None, None])), 2)\n"
                "print(json.dumps({'t': t}))\n"
            )
        try:
            # well under the driver's 150 s infer budget: a slow baseline
            # must not take the measured device numbers down with it
            out = subprocess.run(
                [sys.executable, script], capture_output=True, text=True,
                timeout=90,
            )
            if out.returncode != 0:
                raise RuntimeError(out.stderr[-400:])
            t_host = json.loads(out.stdout.strip().splitlines()[-1])["t"]
            res["infer_vs_local"] = round(t_host / t_dev, 2)
            log(f"[infer] device {t_dev*1e3:.1f} ms ({mvox:.1f} Mvox/s)  "
                f"cpu-local {t_host*1e3:.1f} ms -> {res['infer_vs_local']}x")
        except Exception as e:
            log(f"[infer] cpu baseline failed ({e}); device "
                f"{t_dev*1e3:.1f} ms ({mvox:.1f} Mvox/s)")
    return res


def bench_ws_e2e(x, block_shape):
    """WatershedWorkflow wall-clock, tpu vs cpu-local — the literal
    BASELINE.md north-star workload (block IO + fused DT-WS dispatch +
    label writes, no multicut stages).  Warm-to-warm is the steady-state
    comparison a production sweep pays; both sides report cold too.  The
    device run is in-process and inherits the session platform (the chip
    under the driver, or whatever --platform forced in main)."""
    from bench_e2e_lib import run_ws_pipeline

    here = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory() as td:
        vol_path = os.path.join(td, "vol.npy")
        np.save(vol_path, x)

        t_dev, t_dev_warm, dev_stages = run_ws_pipeline(
            vol_path, x.shape, block_shape, "tpu", warm=True
        )
        stage_note = " ".join(
            f"{k}={v}" for k, v in sorted(dev_stages.items())
        )
        log(f"[ws-e2e] tpu target {t_dev:.2f} s (warm {t_dev_warm:.2f} s"
            + (f"; {stage_note}" if stage_note else "") + ")")
        t_sh = t_sh_warm = None
        try:
            # the collective whole-volume watershed (one upload, one
            # program) — the path designed to win on a tunneled chip
            t_sh, t_sh_warm, _ = run_ws_pipeline(
                vol_path, x.shape, block_shape, "tpu", warm=True,
                sharded=True,
            )
            log(f"[ws-e2e] sharded collective {t_sh:.2f} s "
                f"(warm {t_sh_warm:.2f} s)")
        except Exception as e:
            log(f"[ws-e2e] sharded variant failed: {e}")

        script = os.path.join(td, "ws_cpu.py")
        with open(script, "w") as f:
            f.write(
                "import json, os, sys\n"
                # env var AND config update, like e2e_cpu.py: sitecustomize
                # pins the tunnel platform, and an accidental tunnel client
                # here would collide with the parent's chip session
                "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
                f"sys.path.insert(0, {here!r})\n"
                "import jax\n"
                "jax.config.update('jax_platforms', 'cpu')\n"
                "from bench_e2e_lib import run_ws_pipeline\n"
                f"t, t_warm, _ = run_ws_pipeline({vol_path!r}, "
                f"{tuple(x.shape)!r}, {tuple(block_shape)!r}, 'local', "
                "warm=True)\n"
                "print(json.dumps({'wall_s': t, 'warm_s': t_warm}))\n"
            )
        res = {
            "ws_e2e_wall_s": round(t_dev, 2),
            "ws_e2e_warm_wall_s": round(t_dev_warm, 2),
        }
        try:
            from bench_e2e_lib import flood_rounds_probe

            res.update(flood_rounds_probe(x))
            log(
                "[ws-e2e] flood rounds (alt+assign): flat "
                f"{res['ws_flood_alt_iters_flat']}"
                f"+{res['ws_flood_assign_iters_flat']} -> tiled "
                f"{res['ws_flood_alt_iters_tiled']}"
                f"+{res['ws_flood_assign_iters_tiled']}"
            )
        except Exception as e:
            log(f"[ws-e2e] flood rounds probe failed: {e}")
        # the warm run's three-stage pipeline breakdown: where the host
        # pipeline spent its stage seconds (read/compute/write occupancy),
        # so the IO-hiding claim is measurable in the contract, not asserted
        for key, val in dev_stages.items():
            res[f"ws_e2e_{key}"] = val
        if t_sh_warm is not None:
            res["ws_e2e_sharded_wall_s"] = round(t_sh, 2)
            res["ws_e2e_sharded_warm_wall_s"] = round(t_sh_warm, 2)
        try:
            # ctt-stream: fused threshold→CC→watershed chain vs the same
            # workflow task-at-a-time — store-byte traffic for both, so
            # the scratch round-trip reduction is a recorded number
            from bench_e2e_lib import run_stream_pipeline

            stream_res = run_stream_pipeline(
                vol_path, x.shape, block_shape, "tpu"
            )
            res.update(stream_res)
            log(
                "[ws-e2e] ctt-stream fused chain: bytes_read "
                f"{stream_res['ws_e2e_store_bytes_read']} -> "
                f"{stream_res['ws_e2e_stream_store_bytes_read']} "
                f"({stream_res['ws_e2e_stream_read_reduction']}x), warm "
                f"wall {stream_res['ws_e2e_stream_warm_wall_s']} s vs "
                f"unfused {stream_res['ws_e2e_stream_unfused_warm_wall_s']}"
                f" s, parity {stream_res['ws_e2e_stream_parity']}"
            )
        except Exception as e:
            log(f"[ws-e2e] ctt-stream bench failed: {e}")
        try:
            # ctt-steal: static round-robin vs work-stealing queue on the
            # async stub scheduler over the skewed-cost (hot z-slab ~8x)
            # fixture — the scheduler A/B, independent of the device
            from bench_e2e_lib import run_steal_pipeline

            steal_res = run_steal_pipeline()
            res.update(steal_res)
            log(
                "[ws-e2e] ctt-steal skewed-cost A/B: static "
                f"{steal_res['ws_e2e_steal_static_wall_s']} s -> steal "
                f"{steal_res['ws_e2e_steal_wall_s']} s "
                f"({steal_res['ws_e2e_steal_speedup']}x), parity "
                f"{steal_res['ws_e2e_steal_parity']}"
            )
        except Exception as e:
            log(f"[ws-e2e] ctt-steal bench failed: {e}")
        try:
            # ctt-serve: N back-to-back small workflows, fresh process
            # per workflow vs one warm daemon — the setup-amortization
            # headline, independent of the device (pinned cpu)
            from bench_e2e_lib import run_serve_pipeline

            serve_res = run_serve_pipeline()
            res.update(serve_res)
            log(
                "[ws-e2e] ctt-serve daemon A/B: "
                f"{serve_res['ws_e2e_serve_jobs']} jobs cold-process "
                f"{serve_res['ws_e2e_serve_cold_wall_s']} s -> daemon "
                f"{serve_res['ws_e2e_serve_wall_s']} s "
                f"({serve_res['ws_e2e_serve_speedup']}x), parity "
                f"{serve_res['ws_e2e_serve_parity']}"
            )
        except Exception as e:
            log(f"[ws-e2e] ctt-serve bench failed: {e}")
        try:
            # ctt-hbm: two back-to-back serve jobs on the same volume —
            # warm device-buffer cache + aggregated dispatch + transfer
            # stage vs the PR 9/10 serve warm path (pinned cpu: transfer
            # and dispatch economics, not kernel throughput)
            from bench_e2e_lib import run_hbm_pipeline

            hbm_res = run_hbm_pipeline()
            res.update(hbm_res)
            log(
                "[ws-e2e] ctt-hbm warm HBM A/B: upload bytes cold "
                f"{hbm_res['ws_e2e_hbm_upload_bytes_cold']} -> warm "
                f"{hbm_res['ws_e2e_hbm_upload_bytes_warm']}, dispatches "
                f"{hbm_res['ws_e2e_hbm_dispatches']} for "
                f"{hbm_res['ws_e2e_hbm_blocks']} blocks, warm wall "
                f"{hbm_res['ws_e2e_hbm_warm_wall_s']} s vs base "
                f"{hbm_res['ws_e2e_hbm_base_warm_wall_s']} s "
                f"({hbm_res['ws_e2e_hbm_warm_speedup']}x), parity "
                f"{hbm_res['ws_e2e_hbm_parity']}"
            )
        except Exception as e:
            log(f"[ws-e2e] ctt-hbm bench failed: {e}")
        try:
            # ctt-hier: build the merge hierarchy once through a serve
            # daemon, sweep thresholds as warm resegment jobs vs a full
            # pipeline re-run per threshold (pinned cpu: amortization
            # structure, not kernel throughput)
            from bench_e2e_lib import run_hier_pipeline

            hier_res = run_hier_pipeline()
            res.update(hier_res)
            log(
                "[ws-e2e] ctt-hier one-flood hierarchy: build "
                f"{hier_res['ws_e2e_hier_build_wall_s']} s "
                f"({hier_res['ws_e2e_hier_edges']} edges), warm sweep "
                f"{hier_res['ws_e2e_hier_sweep_ms_warm']} ms vs full "
                f"re-run {hier_res['ws_e2e_hier_full_rerun_s']} s "
                f"({hier_res['ws_e2e_hier_sweep_speedup']}x), volume "
                f"re-cut {hier_res['ws_e2e_hier_recut_volume_s']} s, "
                f"warm upload bytes "
                f"{hier_res['ws_e2e_hier_upload_bytes_warm']}, parity "
                f"{hier_res['ws_e2e_hier_parity']}"
            )
        except Exception as e:
            log(f"[ws-e2e] ctt-hier bench failed: {e}")
        try:
            # ctt-events: batched frame-CC event building vs the
            # per-frame scipy baseline, plus the serve soak at the
            # admission edge (clean 429s, zero leaked threads/fds)
            from bench_e2e_lib import run_events_pipeline

            ev_res = run_events_pipeline()
            res.update(ev_res)
            log(
                "[ws-e2e] ctt-events frame-CC: "
                f"{ev_res['ws_e2e_events_frames_per_s']} frames/s vs "
                f"scipy {ev_res['ws_e2e_events_scipy_frames_per_s']} "
                f"({ev_res['ws_e2e_events_speedup']}x), parity "
                f"{ev_res['ws_e2e_events_parity']}; soak "
                f"{ev_res['ws_e2e_events_soak_submissions']} submissions"
                f" -> {ev_res['ws_e2e_events_soak_rejections']} clean "
                f"429s, leaks clean="
                f"{ev_res['ws_e2e_events_soak_thread_parity']}"
            )
        except Exception as e:
            log(f"[ws-e2e] ctt-events bench failed: {e}")
        try:
            # ctt-microbatch: a mixed-tenant burst of small event_batch
            # jobs through one daemon — aggregation window on vs window 0
            # (per-job dispatch), byte-identical outputs, per-tenant
            # accounting summing exactly to the control
            from bench_e2e_lib import run_microbatch_pipeline

            mb_res = run_microbatch_pipeline()
            res.update(mb_res)
            log(
                "[ws-e2e] ctt-microbatch burst A/B: "
                f"{mb_res['ws_e2e_microbatch_jobs']} jobs window-0 "
                f"{mb_res['ws_e2e_microbatch_solo_wall_s']} s -> window-on "
                f"{mb_res['ws_e2e_microbatch_wall_s']} s "
                f"({mb_res['ws_e2e_microbatch_speedup']}x), "
                f"{mb_res['ws_e2e_microbatch_jobs_per_dispatch']} jobs/"
                f"dispatch over {mb_res['ws_e2e_microbatch_batches']} "
                "stacked dispatches, p99 "
                f"{mb_res['ws_e2e_microbatch_p99_s']} s (bounded "
                f"{mb_res['ws_e2e_microbatch_p99_bounded']}), parity "
                f"{mb_res['ws_e2e_microbatch_parity']}; daemon-hist e2e "
                f"p50 {mb_res['ws_e2e_mb_e2e_p50_s']} s / p99 "
                f"{mb_res['ws_e2e_mb_e2e_p99_s']} s over "
                f"{mb_res['ws_e2e_mb_e2e_samples']} samples (consistent "
                f"{mb_res['ws_e2e_mb_e2e_hist_consistent']})"
            )
        except Exception as e:
            log(f"[ws-e2e] ctt-microbatch bench failed: {e}")
        try:
            # ctt-cloud: the same watershed against the stub object store
            # (subprocess HTTP server) vs POSIX — remote walls, IO hidden
            # behind compute, and chunk-digest parity
            from bench_e2e_lib import run_remote_pipeline

            remote_res = run_remote_pipeline(
                vol_path, x.shape, block_shape, "tpu"
            )
            res.update(remote_res)
            log(
                "[ws-e2e] ctt-cloud remote store: cold "
                f"{remote_res['ws_e2e_remote_cold_wall_s']} s, warm "
                f"{remote_res['ws_e2e_remote_warm_wall_s']} s "
                f"({remote_res['ws_e2e_remote_vs_posix_warm']}x the posix "
                f"warm wall {remote_res['ws_e2e_remote_posix_warm_wall_s']}"
                f" s), read hidden "
                f"{remote_res['ws_e2e_remote_read_hidden_s']} s, parity "
                f"{remote_res['ws_e2e_remote_parity']}"
            )
        except Exception as e:
            log(f"[ws-e2e] ctt-cloud bench failed: {e}")
        try:
            # below the driver's 450 s ws budget so a slow baseline can
            # never take the already-measured device numbers down with it
            out = subprocess.run(
                [sys.executable, script], capture_output=True, text=True,
                timeout=300,
            )
        except subprocess.TimeoutExpired:
            log("[ws-e2e] cpu baseline timed out; reporting device side only")
            return res
        if out.returncode != 0:
            log(f"[ws-e2e] cpu baseline failed:\n{out.stderr[-1000:]}")
            return res
        host = json.loads(out.stdout.strip().splitlines()[-1])
        res["ws_e2e_local_wall_s"] = round(host["wall_s"], 2)
        res["ws_e2e_local_warm_wall_s"] = round(host["warm_s"], 2)
        res["ws_e2e_speedup_warm"] = round(host["warm_s"] / t_dev_warm, 2)
        if t_sh_warm is not None:
            res["ws_e2e_sharded_speedup_warm"] = round(
                host["warm_s"] / t_sh_warm, 2
            )
        log(
            f"[ws-e2e] cpu-local {host['wall_s']:.2f} s "
            f"(warm {host['warm_s']:.2f} s) -> warm speedup "
            f"{res['ws_e2e_speedup_warm']}x"
        )
    return res


def bench_e2e(x, block_shape, platform=None):
    """Full watershed→graph→features→costs→multicut pipeline wall-clock."""
    from bench_e2e_lib import run_pipeline

    here = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory() as td:
        vol_path = os.path.join(td, "vol.npy")
        np.save(vol_path, x)

        # candidate: this process, default device (the TPU chip under the
        # driver); warm=True also reports the jit-cache-warm re-run — the
        # steady-state number a production sweep over many ROIs pays
        dev_seg_path = os.path.join(td, "seg_dev.npy")
        t_dev, t_dev_warm = run_pipeline(
            vol_path, x.shape, block_shape, "tpu", warm=True,
            seg_export=dev_seg_path,
        )
        log(f"[e2e] tpu target {t_dev:.2f} s (warm {t_dev_warm:.2f} s)")

        # the collective problem path (one-program RAG+features + global
        # solve) on the same volume — in a fresh subprocess on the SAME
        # default device, so its jit caches are as cold as the block path's
        # were (in-process it would inherit the shared stages' compiles and
        # report an incomparably warm wall-clock)
        sh_script = os.path.join(td, "e2e_sharded.py")
        # inherit an explicit --platform (debug runs); default = the chip
        force = (
            f"import jax; jax.config.update('jax_platforms', {platform!r})\n"
            if platform else ""
        )
        with open(sh_script, "w") as f:
            f.write(
                "import json, sys\n"
                f"sys.path.insert(0, {here!r})\n"
                + force +
                "from bench_e2e_lib import run_pipeline\n"
                f"t, t_warm = run_pipeline({vol_path!r}, {tuple(x.shape)!r}, "
                f"{tuple(block_shape)!r}, 'tpu', sharded_problem=True, "
                "sharded_ws=True, warm=True)\n"
                "print(json.dumps({'wall_s': t, 'warm_s': t_warm}))\n"
            )
        try:
            sh_out = subprocess.run(
                [sys.executable, sh_script], capture_output=True, text=True,
                # warm=True runs the pipeline twice, but the share of the
                # driver's 840 s e2e budget left for the baseline caps this
                timeout=360,
            )
            if sh_out.returncode != 0:
                raise RuntimeError(sh_out.stderr[-500:])
            sh_res = json.loads(sh_out.stdout.strip().splitlines()[-1])
            t_sharded = sh_res["wall_s"]
            t_sharded_warm = sh_res.get("warm_s")
            warm_note = (
                f", warm {t_sharded_warm:.2f} s"
                if t_sharded_warm is not None else ""
            )
            log(f"[e2e] tpu sharded-problem {t_sharded:.2f} s "
                f"(cold subprocess{warm_note})")
        except Exception as e:  # report the block path regardless
            log(f"[e2e] sharded-problem variant failed: {e}")
            t_sharded = None
            t_sharded_warm = None

        # baseline: same framework, host XLA-CPU backend, local target
        script = os.path.join(td, "e2e_cpu.py")
        host_seg_path = os.path.join(td, "seg_host.npy")
        with open(script, "w") as f:
            f.write(
                "import json, os, sys\n"
                "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
                f"sys.path.insert(0, {here!r})\n"
                "import jax\n"
                "jax.config.update('jax_platforms', 'cpu')\n"
                "from bench_e2e_lib import run_pipeline\n"
                f"t = run_pipeline({vol_path!r}, {tuple(x.shape)!r}, "
                f"{tuple(block_shape)!r}, 'local', "
                f"seg_export={host_seg_path!r})\n"
                "print(json.dumps({'wall_s': t}))\n"
            )
        t0 = time.perf_counter()
        warm = {"e2e_warm_wall_s": round(t_dev_warm, 2)}
        if t_sharded_warm is not None:
            warm["e2e_sharded_problem_warm_wall_s"] = round(t_sharded_warm, 2)
        # keep the baseline timeout safely below the driver's e2e config
        # budget: a slow CPU baseline must cost only the vs_baseline ratio,
        # never the device numbers already measured above
        baseline_budget = float(
            os.environ.get("CTT_BENCH_E2E_BASELINE_TIMEOUT_S", "360")
        )
        try:
            out = subprocess.run(
                [sys.executable, script], capture_output=True, text=True,
                timeout=baseline_budget,
            )
        except subprocess.TimeoutExpired:
            log(f"[e2e] cpu baseline timed out after {baseline_budget:.0f}s; "
                "reporting device numbers without vs_baseline")
            return x.size / t_dev / 1e6, None, t_sharded, warm
        if out.returncode != 0:
            log(f"[e2e] cpu baseline failed:\n{out.stderr[-2000:]}")
            return x.size / t_dev / 1e6, None, t_sharded, warm
        t_host = json.loads(out.stdout.strip().splitlines()[-1])["wall_s"]
        log(
            f"[e2e] cpu-local baseline {t_host:.2f} s (subprocess total "
            f"{time.perf_counter()-t0:.1f} s)"
        )
        # segmentation parity vs the local target — the BASELINE.md north
        # star is defined at "segmentation-identical Rand/VoI", so the
        # contract carries the measured agreement of the two cold runs
        try:
            from cluster_tools_tpu.ops.evaluation import (
                evaluate_segmentation,
            )

            dev_seg = np.load(dev_seg_path)
            host_seg = np.load(host_seg_path)
            # ignore_gt_zero=False: this is a PARITY check, not a gt
            # evaluation — background disagreement (flood-mask/size-filter
            # differences) must count, and the metric must be symmetric
            m = evaluate_segmentation(dev_seg, host_seg,
                                      ignore_gt_zero=False)
            warm["e2e_parity_rand_index"] = round(m["rand_index"], 6)
            warm["e2e_parity_vi_split"] = round(m["vi_split"], 6)
            warm["e2e_parity_vi_merge"] = round(m["vi_merge"], 6)
            log(f"[e2e] tpu-vs-local parity: RI {m['rand_index']:.6f}, "
                f"VoI {m['vi_split']:.4f}/{m['vi_merge']:.4f}")
        except Exception as e:
            log(f"[e2e] parity metrics unavailable: {e}")
    return x.size / t_dev / 1e6, t_host / t_dev, t_sharded, warm


# ---------------------------------------------------------------------------


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true", help="small shapes")
    parser.add_argument("--repeats", type=int, default=5)
    parser.add_argument(
        "--only", default=None,
        help="comma-separated subset: dtws,batched,cc,mws,rag,infer,ws,e2e",
    )
    parser.add_argument(
        "--platform", default=None,
        help="force a jax platform (e.g. cpu) — debugging aid; the image's "
        "sitecustomize pins JAX_PLATFORMS, so the env var alone is too late",
    )
    args = parser.parse_args()

    # persistent XLA executable cache — cold kernel configs and the e2e
    # subprocesses all profit across runs (CTT_COMPILE_CACHE=0 disables)
    from cluster_tools_tpu.obs import trace as obs_trace
    from cluster_tools_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    # ctt-obs: when CTT_TRACE_DIR is set, every bench (sub)process joins
    # ONE traced run — enable() exported CTT_RUN_ID at bootstrap, so the
    # per-config subprocesses below inherit it and the run id rides the
    # contract, making bench runs diffable (obs diff <run_a> <run_b>)
    obs_run_id = obs_trace.current_run_id()
    if obs_run_id is not None:
        log(f"[bench] ctt-obs tracing on: run {obs_run_id}")
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    if args.only is None:
        # Default (driver) mode: run every config in its own subprocess with a
        # per-config timeout, so one slow/failing/hanging config cannot lose
        # the headline metric or the JSON line.  Sequential — the single TPU
        # chip tolerates no concurrent clients.
        #
        # The contract is UNLOSABLE by construction (round 3 lost it to a
        # dead tunnel, round 4 to a driver-budget mismatch — see VERDICT r4):
        #   * the merged JSON line is (re)printed after EVERY config, flushed
        #     — the last stdout line always wins, so a SIGKILL mid-run still
        #     leaves the best contract measured so far;
        #   * a global wall-clock deadline is enforced HERE, inside bench.py
        #     (CTT_BENCH_DEADLINE_S, default 2400 s), clamping each config's
        #     budget to the time remaining and skipping configs that no
        #     longer fit — bench.py exits 0 with a valid contract well before
        #     any sane driver budget expires;
        #   * configs run in priority order: the headline metric first, then
        #     the north-star workloads, then the per-kernel configs.
        t_start = time.perf_counter()
        deadline_s = parse_deadline_env()
        merged = {
            "metric": "dt_watershed_throughput_per_chip",
            "value": None,
            "unit": "Mvox/s",
            "vs_baseline": None,
            "extra": {},
        }
        if obs_run_id is not None:
            merged["extra"]["obs_run_id"] = obs_run_id

        def emit():
            print(json.dumps(merged), flush=True)

        emit()  # a valid (null) contract exists from second zero
        if args.platform is None:
            # the default backend is the TPU chip behind the axon tunnel; a
            # wedged tunnel makes every device query HANG (not fail), which
            # would burn each config's whole timeout budget and report nulls.
            # Probe in a disposable subprocess first; if the chip is
            # unreachable, fall back to honestly-labeled CPU numbers.
            try:
                # require an actual TPU device — a CPU-only jax would exit 0
                # from a bare devices() call and get mislabeled as chip numbers
                probe = subprocess.run(
                    [sys.executable, "-c",
                     "import sys, jax; jax.devices(); "
                     "sys.exit(0 if jax.default_backend() == 'tpu' else 3)"],
                    capture_output=True, timeout=150,
                )
                alive = probe.returncode == 0
            except subprocess.TimeoutExpired:
                alive = False
            if not alive:
                log("[bench] TPU unreachable (device probe hung/failed); "
                    "falling back to the CPU backend — numbers below are NOT "
                    "chip numbers")
                args.platform = "cpu"
                merged["extra"]["tpu_unreachable"] = True
        merged["extra"]["platform"] = args.platform or "default(tpu)"
        here = os.path.abspath(__file__)
        if args.platform == "cpu" and args.repeats > 3:
            # the CPU fallback pays seconds per kernel call (the assoc
            # sweeps at full shape are ~30 s each) — repeats 5 blew the
            # dtws budget in dry runs; 3 keeps every config inside it.
            # Chip runs keep the full count (calls are ms there).
            args.repeats = 3
        # Priority order; worst-case static sum (2370 s) fits the default
        # deadline, and the remaining-time clamp keeps any overrun honest.
        # (Measured CPU-fallback walls: dtws ~210 s, ws ~120 s, cc ~145 s,
        # mws ~50 s — the tail configs may time out there and are skipped;
        # on chip every config fits with room.)
        for cfg, budget_s in [
            ("dtws", 480), ("ws", 390), ("e2e", 840),
            ("cc", 180), ("mws", 90), ("rag", 120),
            ("batched", 90), ("infer", 180),
        ]:
            remaining = deadline_s - (time.perf_counter() - t_start)
            budget_s = min(budget_s, int(remaining) - 15)
            if budget_s < 60:
                log(f"[{cfg}] skipped: {remaining:.0f}s left of the "
                    f"{deadline_s:.0f}s global bench deadline")
                continue
            cmd = [sys.executable, here, "--only", cfg,
                   "--repeats", str(args.repeats)]
            if args.quick:
                cmd.append("--quick")
            if args.platform:
                cmd += ["--platform", args.platform]
            try:
                out = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=budget_s
                )
            except subprocess.TimeoutExpired:
                log(f"[{cfg}] timed out after {budget_s}s; skipping")
                continue
            sys.stderr.write(out.stderr)
            if out.returncode != 0:
                log(f"[{cfg}] failed (exit {out.returncode})")
                continue
            try:
                part = json.loads(out.stdout.strip().splitlines()[-1])
            except (json.JSONDecodeError, IndexError):
                log(f"[{cfg}] produced no JSON line")
                continue
            if cfg == "dtws":
                merged["value"] = part["value"]
                merged["vs_baseline"] = part["vs_baseline"]
            merged["extra"].update(part.get("extra") or {})
            emit()  # checkpoint the contract — last line wins
        emit()
        return

    only = set(args.only.split(","))

    def want(name):
        return name in only

    block = (16, 128, 128) if args.quick else (32, 256, 256)
    cc_shape = (32, 256, 256) if args.quick else (64, 512, 512)
    mws_shape = (16, 128, 128) if args.quick else (32, 256, 256)
    e2e_shape = (32, 128, 128) if args.quick else (64, 256, 256)
    e2e_block = (16, 128, 128)
    batch = 4 if args.quick else 8

    extra = {}
    if obs_run_id is not None:
        extra["obs_run_id"] = obs_run_id
    value, vs = None, None

    if want("dtws"):
        value, vs, dtws_extra = bench_dtws(make_volume(block), args.repeats)
        extra.update(dtws_extra)
    if want("batched"):
        b_v = bench_dtws_batched(make_volume(block), batch, args.repeats)
        extra["dtws_batched_mvox_s"] = round(b_v, 3)
        _suspect_throughput(b_v, extra, "dtws_batched_timing_suspect")
    if want("cc"):
        cc_v, cc_r, cc_extra = bench_cc(
            make_volume(cc_shape, seed=2), args.repeats
        )
        extra["cc_mvox_s"] = round(cc_v, 3)
        extra["cc_vs_baseline"] = round(cc_r, 3)
        extra.update(cc_extra)
        _suspect_throughput(cc_v, extra, "cc_timing_suspect")
    if want("mws"):
        mws_v, mws_r, mwsd_v, mwsd_err = bench_mws(mws_shape, args.repeats)
        extra["mws_kernel_mvox_s"] = round(mws_v, 3)
        extra["mws_kernel_vs_baseline"] = round(mws_r, 3)
        extra["mws_device_mvox_s"] = (
            round(mwsd_v, 6) if mwsd_v is not None else None
        )
        if mwsd_err:
            extra["mws_device_error"] = mwsd_err
    if want("rag"):
        rag_v, rag_r = bench_rag(make_volume(block), args.repeats)
        extra["rag_mvox_s"] = round(rag_v, 3)
        extra["rag_vs_baseline"] = round(rag_r, 3) if rag_r is not None else None
        _suspect_throughput(rag_v, extra, "rag_timing_suspect")
    if want("infer"):
        extra.update(bench_inference(args.repeats, quick=args.quick))
    if want("ws"):
        extra.update(bench_ws_e2e(make_volume(e2e_shape, seed=3), e2e_block))
    if want("e2e"):
        e2e_v, e2e_r, e2e_sharded, e2e_warm = bench_e2e(
            make_volume(e2e_shape, seed=3), e2e_block, platform=args.platform
        )
        extra["e2e_multicut_mvox_s"] = round(e2e_v, 3)
        extra["e2e_multicut_vs_baseline"] = (
            round(e2e_r, 3) if e2e_r is not None else None
        )
        if e2e_sharded is not None:
            extra["e2e_sharded_problem_wall_s"] = round(e2e_sharded, 2)
        extra.update(e2e_warm)

    print(
        json.dumps(
            {
                "metric": "dt_watershed_throughput_per_chip",
                "value": round(value, 3) if value is not None else None,
                "unit": "Mvox/s",
                "vs_baseline": round(vs, 3) if vs is not None else None,
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    main()
