#!/usr/bin/env python
"""Skeletonize a segmentation (the role of the reference's
example/skeletons.py): per-segment morphology → bbox crop → thinning →
varlength skeleton serialization."""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.workflows import SkeletonWorkflow
from cluster_tools_tpu.workflows.watershed import WatershedWorkflow


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--demo", action="store_true")
    p.add_argument("--input", default="demo_data.n5")
    p.add_argument("--seg-key", default="segmentation/watershed")
    p.add_argument("--target", default="tpu",
                   choices=("tpu", "local", "slurm", "lsf"))
    args = p.parse_args()

    config_dir, tmp_folder = "configs_skel", "tmp_skel"
    cfg.write_global_config(config_dir, {
        "block_shape": [16, 32, 32], "target": args.target,
    })
    if args.demo:
        from _demo_data import make_demo_volume

        make_demo_volume(args.input)
        cfg.write_config(config_dir, "watershed", {
            "threshold": 0.4, "sigma_seeds": 1.0, "size_filter": 25,
            "apply_dt_2d": False, "apply_ws_2d": False, "halo": [2, 4, 4],
        })
        ws = WatershedWorkflow(
            tmp_folder, config_dir,
            input_path=args.input, input_key="boundaries",
            output_path=args.input, output_key=args.seg_key,
        )
        assert build([ws])

    wf = SkeletonWorkflow(
        tmp_folder, config_dir,
        input_path=args.input, input_key=args.seg_key,
    )
    if not build([wf]):
        raise RuntimeError("skeleton workflow failed")
    from cluster_tools_tpu.tasks.skeletons import SKELETONS_KEY
    from cluster_tools_tpu.tasks.base import scratch_store_path
    from cluster_tools_tpu.utils import file_reader

    skels = file_reader(scratch_store_path(tmp_folder), "r")[SKELETONS_KEY]
    n = sum(
        1 for i in range(skels.grid_shape[0])
        if skels.read_chunk((i,)) is not None
    )
    print(f"skeletonized {n} segments -> {scratch_store_path(tmp_folder)}")


if __name__ == "__main__":
    main()
