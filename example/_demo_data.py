"""Synthetic CREMI-like demo volume shared by the example scripts."""

import numpy as np
from scipy import ndimage

from cluster_tools_tpu.utils import file_reader


def make_demo_volume(path, shape=(32, 64, 64), seed=0):
    """Write a smooth boundary-probability volume (plus a ground-truth-ish
    label volume from its basins) into an n5 container."""
    rng = np.random.default_rng(seed)
    raw = ndimage.gaussian_filter(rng.random(shape), (1.5, 2.5, 2.5))
    raw = ((raw - raw.min()) / (raw.max() - raw.min())).astype("float32")
    f = file_reader(path)
    chunks = tuple(min(16, s) for s in shape)
    if "boundaries" not in f:
        f.create_dataset("boundaries", data=raw, chunks=chunks)
    return path, "boundaries"
