#!/usr/bin/env python
"""Postprocess a segmentation: remove small fragments and re-flood the freed
voxels from the surviving segments (the role of the reference's
example/postprocessing.py size-filter path).

One composite does the whole chain — morphology (per-segment sizes) → size
filter → filling re-flood over the boundary map → consecutive relabel:
``SizeFilterWorkflow(min_size=..., hmap_path=..., relabel=True)``.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import SizeFilterWorkflow
from cluster_tools_tpu.workflows.watershed import WatershedWorkflow


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--demo", action="store_true")
    p.add_argument("--input", default="demo_data.n5")
    p.add_argument("--input-key", default="boundaries")
    p.add_argument("--seg-key", default="segmentation/watershed")
    p.add_argument("--output-key", default="segmentation/size_filtered")
    p.add_argument("--min-size", type=int, default=50)
    p.add_argument("--target", default="tpu",
                   choices=("tpu", "local", "slurm", "lsf"))
    args = p.parse_args()

    config_dir = "configs_pp"
    cfg.write_global_config(config_dir, {
        "block_shape": [16, 32, 32], "target": args.target,
    })
    if args.demo:
        from _demo_data import make_demo_volume

        make_demo_volume(args.input)
        cfg.write_config(config_dir, "watershed", {
            "threshold": 0.4, "sigma_seeds": 1.0, "size_filter": 0,
            "apply_dt_2d": False, "apply_ws_2d": False, "halo": [2, 4, 4],
        })
        ws = WatershedWorkflow(
            "tmp_ws_pp", config_dir,
            input_path=args.input, input_key=args.input_key,
            output_path=args.input, output_key=args.seg_key,
        )
        assert build([ws])

    wf = SizeFilterWorkflow(
        "tmp_pp", config_dir,
        input_path=args.input, input_key=args.seg_key,
        output_path=args.input, output_key=args.output_key,
        min_size=args.min_size,
        hmap_path=args.input, hmap_key=args.input_key,  # filling re-flood
        relabel=True,
    )
    if not build([wf]):
        raise RuntimeError("size filter workflow failed")

    f = file_reader(args.input, "r")
    n_before = len(np.unique(f[args.seg_key][:])) - 1
    out = f[args.output_key][:]
    n_after = len(np.unique(out)) - 1
    print(f"size filter: {n_before} -> {n_after} segments "
          f"(< {args.min_size} vox re-flooded into survivors) "
          f"-> {args.input}:{args.output_key}")


if __name__ == "__main__":
    main()
