#!/usr/bin/env python
"""Postprocess a segmentation: remove small fragments and re-flood the freed
voxels from the surviving segments (the role of the reference's
example/postprocessing.py size-filter path).

Chain: morphology (per-segment sizes) → size filter (assignment table of
kept ids) → filling size filter (discarded voxels re-flooded over the
boundary map, reference filling_size_filter.py).
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.tasks.postprocess import (
    SIZE_FILTER_NAME,
    FillingSizeFilterTask,
    SizeFilterTask,
)
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import MorphologyWorkflow
from cluster_tools_tpu.workflows.watershed import WatershedWorkflow


def run_size_filter(path, seg_key, hmap_key, out_key, min_size,
                    tmp_folder="tmp_pp", config_dir="configs_pp",
                    target="tpu"):
    cfg.write_global_config(config_dir, {
        "block_shape": [16, 32, 32], "target": target,
    })

    morpho = MorphologyWorkflow(
        tmp_folder, config_dir, input_path=path, input_key=seg_key,
    )
    size_filter = SizeFilterTask(
        tmp_folder, config_dir, dependencies=[morpho], min_size=min_size,
        relabel=False,
    )
    if not build([size_filter]):
        raise RuntimeError("size filter failed")

    # kept-id table → discard list for the filling re-flood
    kept = np.load(os.path.join(tmp_folder, SIZE_FILTER_NAME))[:, 0]
    seg_ids = file_reader(path, "r")[seg_key][:]
    all_ids = np.unique(seg_ids)
    discard = np.setdiff1d(all_ids[all_ids > 0], kept)
    discard_path = os.path.join(tmp_folder, "discard_ids.npy")
    np.save(discard_path, discard.astype("uint64"))

    fill = FillingSizeFilterTask(
        tmp_folder, config_dir,
        input_path=path, input_key=seg_key,
        output_path=path, output_key=out_key,
        hmap_path=path, hmap_key=hmap_key,
        res_path=discard_path,
    )
    if not build([fill]):
        raise RuntimeError("filling size filter failed")
    return discard.size


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--demo", action="store_true")
    p.add_argument("--input", default="demo_data.n5")
    p.add_argument("--input-key", default="boundaries")
    p.add_argument("--seg-key", default="segmentation/watershed")
    p.add_argument("--output-key", default="segmentation/size_filtered")
    p.add_argument("--min-size", type=int, default=50)
    p.add_argument("--target", default="tpu",
                   choices=("tpu", "local", "slurm", "lsf"))
    args = p.parse_args()

    if args.demo:
        from _demo_data import make_demo_volume

        make_demo_volume(args.input)
        cfg.write_global_config("configs_ws_pp", {
            "block_shape": [16, 32, 32], "target": args.target,
        })
        cfg.write_config("configs_ws_pp", "watershed", {
            "threshold": 0.4, "sigma_seeds": 1.0, "size_filter": 0,
            "apply_dt_2d": False, "apply_ws_2d": False, "halo": [2, 4, 4],
        })
        ws = WatershedWorkflow(
            "tmp_ws_pp", "configs_ws_pp",
            input_path=args.input, input_key=args.input_key,
            output_path=args.input, output_key=args.seg_key,
        )
        assert build([ws])

    n_removed = run_size_filter(
        args.input, args.seg_key, args.input_key, args.output_key,
        args.min_size, target=args.target,
    )
    out = file_reader(args.input, "r")[args.output_key][:]
    print(f"size filter removed {n_removed} fragments < {args.min_size} vox; "
          f"{len(np.unique(out)) - 1} segments remain "
          f"-> {args.input}:{args.output_key}")


if __name__ == "__main__":
    main()
