#!/usr/bin/env python
"""Build a multiscale pyramid with paintera/bdv metadata
(the role of the reference's example/downscale.py)."""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import DownscalingWorkflow


def run_downscale(input_path, input_key, output_key_prefix,
                  scale_factors=((1, 2, 2), (1, 2, 2), (2, 2, 2)),
                  tmp_folder="tmp_ds", config_dir="configs_ds",
                  target="tpu", metadata_format="paintera"):
    cfg.write_global_config(config_dir, {
        "block_shape": [16, 32, 32], "target": target,
    })
    wf = DownscalingWorkflow(
        tmp_folder, config_dir,
        input_path=input_path, input_key=input_key,
        scale_factors=scale_factors,
        output_path=input_path,
        output_key_prefix=output_key_prefix,
        metadata_format=metadata_format,
        metadata_dict={"resolution": [40, 4, 4], "unit": "nm"},
    )
    if not build([wf]):
        raise RuntimeError("downscaling failed")


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--demo", action="store_true")
    p.add_argument("--input", default="demo_data.n5")
    p.add_argument("--input-key", default="boundaries")
    p.add_argument("--output-key-prefix", default="pyramid")
    p.add_argument("--target", default="tpu",
                   choices=("tpu", "local", "slurm", "lsf"))
    args = p.parse_args()

    if args.demo:
        from _demo_data import make_demo_volume

        make_demo_volume(args.input)
    run_downscale(
        args.input, args.input_key, args.output_key_prefix, target=args.target
    )
    f = file_reader(args.input, "r")
    scales = sorted(k for k in f[args.output_key_prefix].keys())
    shapes = [f[f"{args.output_key_prefix}/{s}"].shape for s in scales]
    print(f"pyramid written: {dict(zip(scales, shapes))}")


if __name__ == "__main__":
    main()
