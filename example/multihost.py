"""Multi-process collective kernels over a global device mesh.

Launches N worker processes that join one jax runtime
(``parallel.mesh.init_distributed`` — the framework's NCCL/MPI-bootstrap
analog; Gloo/gRPC stands in for DCN on CPU) and run the collective
connected-components kernel over the GLOBAL mesh: every worker holds the
full host volume (the shared-storage model), materializes only its
addressable shards (``put_global`` inside the kernel), and reads back its
own slab (``fetch_local``).

Run:  python example/multihost.py            (spawns 2 CPU workers x 4 devices)
      CTT_PROCESS_ID=0 CTT_NUM_PROCESSES=2 CTT_COORDINATOR=host0:1234 \
          python example/multihost.py --worker   (one process per TPU host)
"""

import os
import socket
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


def worker():
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, ROOT)

    import numpy as np
    from scipy import ndimage

    from cluster_tools_tpu.parallel import mesh as mesh_mod
    from cluster_tools_tpu.parallel.sharded import sharded_connected_components

    # join the multi-process runtime BEFORE any other jax use
    assert mesh_mod.init_distributed(), "set CTT_COORDINATOR & friends"
    pid = int(os.environ["CTT_PROCESS_ID"])
    mesh = mesh_mod.get_mesh(mesh_mod.resolve_devices({"devices": "global"}))
    print(f"[p{pid}] mesh over {mesh.size} devices "
          f"({jax.process_count()} processes)", flush=True)

    rng = np.random.default_rng(0)
    shape = (mesh.size * 4, 32, 32)
    raw = ndimage.gaussian_filter(rng.random(shape), 1.0)
    mask = raw > raw.mean()

    labels = sharded_connected_components(mask, mesh=mesh)
    z0, local = mesh_mod.fetch_local(labels)
    want, n_want = ndimage.label(mask)
    got = np.where(local < 0, 0, local + 1)
    want_local = want[z0 : z0 + local.shape[0]]
    m = mask[z0 : z0 + local.shape[0]]
    pairs = np.unique(np.stack([got[m], want_local[m]], axis=1), axis=0)
    assert len(pairs) == len(np.unique(got[m]))
    print(f"[p{pid}] slab z={z0}..{z0 + local.shape[0]}: partition matches "
          f"scipy ({n_want} components globally)", flush=True)


def launch(n_proc=2, devices_per_proc=4):
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env_base = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=f"--xla_force_host_platform_device_count={devices_per_proc}",
        CTT_COORDINATOR=f"127.0.0.1:{port}",
        CTT_NUM_PROCESSES=str(n_proc),
    )
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env={**env_base, "CTT_PROCESS_ID": str(pid)},
        )
        for pid in range(n_proc)
    ]
    try:
        codes = [p.wait(timeout=600) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(codes):
        raise SystemExit(f"worker exit codes: {codes}")
    print("multihost example OK")


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    else:
        launch()
