#!/usr/bin/env python
"""This framework's collective path: whole-volume kernels over the device
mesh (no reference analog — the reference merges cross-block results through
the filesystem; here the volume z-shards over the mesh and every cross-shard
dependency rides an ICI collective inside one jit program).

Two entry points:
  * `ThresholdedComponentsWorkflow(sharded=True)` — global connected
    components, cross-shard merge via ppermute'd boundary planes;
  * `WatershedWorkflow(sharded=True)` — the ENTIRE DT-watershed collective:
    cross-shard EDT, halo'd smoothing, sharded seed-CC, collective flood —
    one globally-consistent fragmentation, no block offsets, no stitching.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import ThresholdedComponentsWorkflow
from cluster_tools_tpu.workflows.watershed import WatershedWorkflow


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--demo", action="store_true")
    p.add_argument("--input", default="demo_data.n5")
    p.add_argument("--input-key", default="boundaries")
    args = p.parse_args()

    if args.demo:
        from _demo_data import make_demo_volume

        make_demo_volume(args.input)

    config_dir, tmp_folder = "configs_sharded", "tmp_sharded"
    cfg.write_global_config(config_dir, {
        "block_shape": [16, 32, 32], "target": "tpu",
    })
    cfg.write_config(config_dir, "sharded_components", {"threshold": 0.5})
    cfg.write_config(config_dir, "sharded_watershed", {
        "threshold": 0.4, "sigma_seeds": 1.0, "size_filter": 10,
    })

    cc = ThresholdedComponentsWorkflow(
        tmp_folder + "_cc", config_dir,
        input_path=args.input, input_key=args.input_key,
        output_path=args.input, output_key="sharded/components",
        sharded=True,
    )
    ws = WatershedWorkflow(
        tmp_folder + "_ws", config_dir,
        input_path=args.input, input_key=args.input_key,
        output_path=args.input, output_key="sharded/watershed",
        sharded=True,
    )
    if not build([cc, ws]):
        raise RuntimeError("sharded workflows failed")

    # full multicut with the collective problem extraction (one-program RAG
    # + features feeding the global solve)
    from cluster_tools_tpu.workflows import MulticutSegmentationWorkflow

    cfg.write_config(config_dir, "watershed", {
        "threshold": 0.4, "sigma_seeds": 1.0, "size_filter": 5,
        "apply_dt_2d": False, "apply_ws_2d": False, "halo": [2, 4, 4],
    })
    mc = MulticutSegmentationWorkflow(
        tmp_folder + "_mc", config_dir,
        input_path=args.input, input_key=args.input_key,
        ws_path=args.input, ws_key="sharded/mc_ws",
        output_path=args.input, output_key="sharded/multicut",
        sharded_problem=True,
    )
    if not build([mc]):
        raise RuntimeError("sharded-problem multicut failed")

    # the fully device-resident front (sharded_ws=True): watershed + RAG +
    # features in ONE collective session — the boundary volume crosses
    # host→device once and stays on the mesh through both stages; fastest
    # path on real chips (per-stage store round-trips disappear)
    cfg.write_config(config_dir, "sharded_ws_problem", {
        "threshold": 0.4, "sigma_seeds": 1.0, "size_filter": 5,
    })
    fused = MulticutSegmentationWorkflow(
        tmp_folder + "_fused", config_dir,
        input_path=args.input, input_key=args.input_key,
        ws_path=args.input, ws_key="sharded/fused_ws",
        output_path=args.input, output_key="sharded/fused_multicut",
        sharded_problem=True, sharded_ws=True,
    )
    if not build([fused]):
        raise RuntimeError("fused sharded multicut failed")

    f = file_reader(args.input, "r")
    n_cc = len(np.unique(f["sharded/components"][:])) - 1
    n_ws = len(np.unique(f["sharded/watershed"][:])) - 1
    n_mc = len(np.unique(f["sharded/multicut"][:])) - 1
    n_f = len(np.unique(f["sharded/fused_multicut"][:])) - 1
    import jax

    print(f"collective CC: {n_cc} components, collective DT-watershed: "
          f"{n_ws} fragments, collective-problem multicut: {n_mc} segments, "
          f"fused device-resident multicut: {n_f} segments "
          f"over {jax.device_count()} devices")


if __name__ == "__main__":
    main()
