#!/usr/bin/env python
"""Multicut segmentation of a boundary-map volume.

The full pipeline (the role of the reference's example/multicut.py):
DT-watershed oversegmentation → region adjacency graph → edge features →
costs → hierarchical multicut → write.  Per-block compute runs as fused jit
programs batched over the device mesh (``--target tpu``); cross-block merges
ride the scratch store; re-running resumes from the first incomplete task.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cluster_tools_tpu.runtime import build, config as cfg
from cluster_tools_tpu.utils import file_reader
from cluster_tools_tpu.workflows import MulticutSegmentationWorkflow


def run_multicut(input_path, input_key, output_path, output_key,
                 tmp_folder="tmp_mc", config_dir="configs_mc",
                 target="tpu", block_shape=(16, 32, 32), n_scales=1,
                 invert_inputs=False):
    # two-level config: global.config carries decomposition + scheduling,
    # <task>.config carries per-task behavior (edit the JSONs between runs)
    cfg.write_global_config(config_dir, {
        "block_shape": list(block_shape),
        "target": target,
        "device_batch_size": 4,
    })
    cfg.write_config(config_dir, "watershed", {
        "threshold": 0.4,
        "sigma_seeds": 1.0,
        "size_filter": 5,
        "apply_dt_2d": False,
        "apply_ws_2d": False,
        "halo": [2, 4, 4],
        "invert_inputs": invert_inputs,
    })

    wf = MulticutSegmentationWorkflow(
        tmp_folder, config_dir,
        input_path=input_path, input_key=input_key,
        ws_path=output_path, ws_key=output_key + "_ws",
        output_path=output_path, output_key=output_key,
        n_scales=n_scales,
    )
    if not build([wf]):
        raise RuntimeError("multicut workflow failed — see tmp folder logs")
    return file_reader(output_path, "r")[output_key]


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--demo", action="store_true", help="synthetic volume")
    p.add_argument("--input", default="demo_data.n5")
    p.add_argument("--input-key", default="boundaries")
    p.add_argument("--output", default=None, help="default: the input container")
    p.add_argument("--output-key", default="segmentation/multicut")
    p.add_argument("--target", default="tpu",
                   choices=("tpu", "local", "slurm", "lsf"))
    p.add_argument("--n-scales", type=int, default=1,
                   help="hierarchical solver scales")
    p.add_argument("--invert-inputs", action="store_true",
                   help="set when HIGH boundary evidence = LOW values")
    args = p.parse_args()

    if args.demo:
        from _demo_data import make_demo_volume

        make_demo_volume(args.input)
    seg = run_multicut(
        args.input, args.input_key,
        args.output or args.input, args.output_key,
        target=args.target, n_scales=args.n_scales,
        invert_inputs=args.invert_inputs,
    )
    import numpy as np

    n = len(np.unique(seg[:])) - 1
    print(f"multicut segmentation written: {n} segments "
          f"-> {args.output or args.input}:{args.output_key}")


if __name__ == "__main__":
    main()
