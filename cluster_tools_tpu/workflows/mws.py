"""Mutex watershed workflow (reference mws_workflow.py:14-78):
blockwise MWS → face stitching → write."""

from __future__ import annotations

import os
from typing import Optional

from ..runtime.workflow import WorkflowBase
from ..tasks.mws import MwsBlocksTask
from ..tasks.stitching import (
    STITCH_ASSIGNMENTS_NAME,
    StitchAssignmentsTask,
    StitchFacesTask,
)
from ..tasks.write import WriteTask


class MwsWorkflow(WorkflowBase):
    task_name = "mws_workflow"

    def __init__(
        self,
        tmp_folder,
        config_dir=None,
        max_jobs=None,
        target=None,
        input_path: str = None,
        input_key: str = None,
        output_path: str = None,
        output_key: str = None,
        mask_path: str = None,
        mask_key: str = None,
        stitch: bool = True,
        dependencies=(),
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.mask_path = mask_path
        self.mask_key = mask_key
        self.stitch = stitch

    def requires(self):
        blocks_key = self.output_key + ("_blocks" if self.stitch else "")
        mws = MwsBlocksTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=list(self.dependencies),
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=blocks_key,
            mask_path=self.mask_path, mask_key=self.mask_key,
        )
        if not self.stitch:
            return [mws]
        faces = StitchFacesTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[mws],
            input_path=self.output_path, input_key=blocks_key,
        )
        assignments = StitchAssignmentsTask(
            self.tmp_folder, self.config_dir,
            dependencies=[faces],
            input_path=self.output_path, input_key=blocks_key,
        )
        write = WriteTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[assignments],
            input_path=self.output_path, input_key=blocks_key,
            output_path=self.output_path, output_key=self.output_key,
            assignment_path=os.path.join(self.tmp_folder, STITCH_ASSIGNMENTS_NAME),
            identifier="mws_stitch",
            table_default="identity",
        )
        return [write]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["mws_blocks"] = MwsBlocksTask.default_task_config()
        conf["stitch_faces"] = StitchFacesTask.default_task_config()
        conf["write"] = WriteTask.default_task_config()
        return conf


class TwoPassMwsWorkflow(WorkflowBase):
    """Two-pass mutex watershed (reference mws_workflow.py:80
    TwoPassMwsWorkflow): checkerboard pass 0, then pass 1 seeded by the
    written neighbors — globally consistent labels without stitching."""

    task_name = "two_pass_mws_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path=None, input_key=None, output_path=None,
                 output_key=None, mask_path=None, mask_key=None,
                 dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.mask_path = mask_path
        self.mask_key = mask_key

    def requires(self):
        from ..tasks.mws import TwoPassMwsTask

        pass0 = TwoPassMwsTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=list(self.dependencies),
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=self.output_key,
            mask_path=self.mask_path, mask_key=self.mask_key,
            pass_id=0,
        )
        pass1 = TwoPassMwsTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[pass0],
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=self.output_key,
            mask_path=self.mask_path, mask_key=self.mask_key,
            pass_id=1,
        )
        return [pass1]

    @classmethod
    def get_config(cls):
        from ..tasks.mws import TwoPassMwsTask

        conf = super().get_config()
        conf["two_pass_mws"] = TwoPassMwsTask.default_task_config()
        return conf
