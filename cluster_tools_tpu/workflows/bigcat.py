"""Legacy bigcat export (reference bigcat/bigcat_workflow.py:15-130).

Bigcat reads an HDF5 container with raw + fragment labels + a
``fragment_segment_lut`` [2, n] uint64 table (fragment id → segment id, both
in one id namespace, segments offset past the fragments) and
``next_id``/resolution/offset attributes."""

from __future__ import annotations

import numpy as np

from ..runtime.task import SimpleTask
from ..runtime.workflow import WorkflowBase


class BigcatLabelAssignmentTask(SimpleTask):
    """fragment_segment_lut from a 1d assignment vector
    (reference bigcat_workflow.py:15-45)."""

    task_name = "bigcat_label_assignment"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None,
                 dependencies=(), input_path=None, input_key=None,
                 output_path=None):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path

    def run_impl(self) -> None:
        import h5py

        from ..utils import store

        if self.input_path.endswith((".h5", ".hdf5", ".hdf")):
            with h5py.File(self.input_path, "r") as f:
                assignments = f[self.input_key][:]
        else:
            assignments = store.file_reader(self.input_path, "r")[
                self.input_key
            ][:]
        if assignments.ndim != 1:
            raise ValueError("bigcat assignments must be a 1d vector")

        n = len(assignments)
        lut = np.zeros((2, n), dtype="uint64")
        lut[0] = np.arange(n, dtype="uint64")
        # segment ids live past the fragment id range (reference :31-33)
        lut[1] = assignments.astype("uint64") + np.uint64(n)
        with h5py.File(self.output_path, "a") as f:
            ds = f.require_dataset(
                "fragment_segment_lut", shape=lut.shape, dtype="uint64",
                compression="gzip", maxshape=(2, None),
            )
            ds[:] = lut


class BigcatMetadataTask(SimpleTask):
    """next_id + resolution/offset attrs (reference bigcat_workflow.py:48-90)."""

    task_name = "bigcat_metadata"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None,
                 dependencies=(), input_path=None, raw_key=None, seg_key=None,
                 resolution=(1, 1, 1), offset=None):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        self.input_path = input_path
        self.raw_key = raw_key
        self.seg_key = seg_key
        self.resolution = list(resolution)
        self.offset = list(offset) if offset is not None else [0, 0, 0]

    def run_impl(self) -> None:
        import h5py

        with h5py.File(self.input_path, "a") as f:
            if "fragment_segment_lut" in f:
                next_id = int(f["fragment_segment_lut"][:].max()) + 1
            else:
                next_id = int(f[self.seg_key][:].max()) + 1
            f.attrs["next_id"] = next_id
            f[self.raw_key].attrs["resolution"] = self.resolution
            f[self.raw_key].attrs["offset"] = [0, 0, 0]
            f[self.seg_key].attrs["resolution"] = self.resolution
            f[self.seg_key].attrs["offset"] = self.offset


class BigcatWorkflow(WorkflowBase):
    """Assemble a bigcat h5 container from raw, watershed and assignments.

    The heavy volumes must already live in the h5 container (bigcat is a
    legacy h5-only viewer; our chunk store is zarr/n5) — this workflow adds
    the fragment-segment LUT and metadata."""

    task_name = "bigcat_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 assignment_path=None, assignment_key=None,
                 output_path=None, raw_key: str = "volumes/raw",
                 seg_key: str = "volumes/labels/fragments",
                 resolution=(1, 1, 1), offset=None):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.assignment_path = assignment_path
        self.assignment_key = assignment_key
        self.output_path = output_path
        self.raw_key = raw_key
        self.seg_key = seg_key
        self.resolution = list(resolution)
        self.offset = offset

    def requires(self):
        lut = BigcatLabelAssignmentTask(
            self.tmp_folder, self.config_dir,
            input_path=self.assignment_path, input_key=self.assignment_key,
            output_path=self.output_path,
        )
        meta = BigcatMetadataTask(
            self.tmp_folder, self.config_dir,
            dependencies=[lut],
            input_path=self.output_path,
            raw_key=self.raw_key, seg_key=self.seg_key,
            resolution=self.resolution, offset=self.offset,
        )
        return [lut, meta]
