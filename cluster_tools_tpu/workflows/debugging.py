"""Sanity-check workflows (reference debugging/check_sub_graphs_workflow.py:10,
check_ws_workflow.py:13)."""

from __future__ import annotations

from ..runtime.workflow import WorkflowBase
from ..tasks.debugging import CheckComponentsTask, CheckSubGraphsTask
from .multicut import GraphWorkflow


class CheckSubGraphsWorkflow(WorkflowBase):
    """Extract the graph, then verify every block's serialized node set
    against a recompute."""

    task_name = "check_sub_graphs_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 ws_path=None, ws_key=None, dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.ws_path = ws_path
        self.ws_key = ws_key

    def requires(self):
        graph = GraphWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs, self.target,
            input_path=self.ws_path, input_key=self.ws_key,
            dependencies=list(self.dependencies),
        )
        check = CheckSubGraphsTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[graph],
            input_path=self.ws_path, input_key=self.ws_key,
        )
        return [check]


class CheckComponentsWorkflow(WorkflowBase):
    """Fragmentation sanity check over a segmentation."""

    task_name = "check_components_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path=None, input_key=None,
                 max_blocks_per_label: int = 8, dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.max_blocks_per_label = max_blocks_per_label

    def requires(self):
        check = CheckComponentsTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=list(self.dependencies),
            input_path=self.input_path, input_key=self.input_key,
            max_blocks_per_label=self.max_blocks_per_label,
        )
        return [check]
