"""Intensity-transformation workflow composite
(reference transformations/transformation_workflows.py:7-44)."""

from __future__ import annotations

from ..runtime.workflow import WorkflowBase
from ..tasks.transformations import LinearTransformationTask


class LinearTransformationWorkflow(WorkflowBase):
    """Apply an ``a*x + b`` intensity transform (global or per-z-slice spec
    file).  Omitting ``output_path/output_key`` applies it in place, like the
    reference (transformation_workflows.py:21-24)."""

    task_name = "linear_transformation_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path=None, input_key=None,
                 transformation=None,
                 output_path=None, output_key=None,
                 mask_path=None, mask_key=None,
                 dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.transformation = transformation
        self.output_path = output_path or input_path
        self.output_key = output_key or input_key
        self.mask_path = mask_path
        self.mask_key = mask_key

    def requires(self):
        linear = LinearTransformationTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=list(self.dependencies),
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=self.output_key,
            transformation=self.transformation,
            mask_path=self.mask_path, mask_key=self.mask_key,
        )
        return [linear]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["linear"] = LinearTransformationTask.default_task_config()
        return conf
