"""Relabel workflow: find_uniques → find_labeling → write
(reference relabel_workflow.py:10-74)."""

from __future__ import annotations

import os
from typing import Optional

from ..runtime.workflow import WorkflowBase
from ..tasks.relabel import (
    LABELING_NAME,
    FindLabelingTask,
    FindUniquesTask,
    MergeUniquesTask,
)
from ..tasks.write import WriteTask


class UniqueWorkflow(WorkflowBase):
    """find_uniques → merge_uniques: materialize the sorted unique-id set of a
    label volume (reference relabel_workflow.py:76)."""

    task_name = "unique_workflow"

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        target: Optional[str] = None,
        input_path: str = None,
        input_key: str = None,
        output_path: str = None,
        output_key: str = None,
        dependencies=(),
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key

    def requires(self):
        uniques = FindUniquesTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            dependencies=list(self.dependencies),
            input_path=self.input_path,
            input_key=self.input_key,
        )
        merge = MergeUniquesTask(
            self.tmp_folder,
            self.config_dir,
            dependencies=[uniques],
            input_path=self.input_path,
            input_key=self.input_key,
            output_path=self.output_path,
            output_key=self.output_key,
        )
        return [merge]


class RelabelWorkflow(WorkflowBase):
    task_name = "relabel_workflow"

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        target: Optional[str] = None,
        input_path: str = None,
        input_key: str = None,
        output_path: str = None,
        output_key: str = None,
        dependencies=(),
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key

    def requires(self):
        uniques = FindUniquesTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            dependencies=list(self.dependencies),
            input_path=self.input_path,
            input_key=self.input_key,
        )
        labeling = FindLabelingTask(
            self.tmp_folder,
            self.config_dir,
            dependencies=[uniques],
            input_path=self.input_path,
            input_key=self.input_key,
        )
        write = WriteTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            dependencies=[labeling],
            input_path=self.input_path,
            input_key=self.input_key,
            output_path=self.output_path,
            output_key=self.output_key,
            assignment_path=os.path.join(self.tmp_folder, LABELING_NAME),
            identifier="relabel",
        )
        return [write]
