"""ctt-events workflow: high-rate event building over a frame stream.

``EventBuildingWorkflow`` wraps one :class:`~..tasks.events.EventBuildingTask`
run: an ``(n_frames, h, w)`` frame stack in, a per-frame labels volume plus
ragged per-block event tables out.  This is the workflow the serve
``event_batch`` job type (serve/protocol.py) resolves — a detector
front-end submitting frame batches at rate hits the same warm daemon
path as every other workflow, with a frame-count-blind job signature so
every batch after the first reuses the compiled kernels.
"""

from __future__ import annotations

from typing import Optional

from ..runtime.workflow import WorkflowBase
from ..tasks.events import EventBuildingTask


class EventBuildingWorkflow(WorkflowBase):
    task_name = "events_workflow"

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        target: Optional[str] = None,
        input_path: str = None,
        input_key: str = None,
        output_path: str = None,
        output_key: str = None,
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key

    def requires(self):
        return [
            EventBuildingTask(
                self.tmp_folder,
                self.config_dir,
                self.max_jobs,
                input_path=self.input_path,
                input_key=self.input_key,
                output_path=self.output_path,
                output_key=self.output_key,
            )
        ]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["events"] = EventBuildingTask.default_task_config()
        return conf
