"""ctt-hier workflows: build the merge hierarchy once, re-cut at will.

``HierarchyWorkflow`` runs the one-flood hierarchy build (tasks/hier.py):
blocks → offsets → faces → build → write, producing a GLOBAL-id labels
volume at ``output_key`` plus the sorted-by-saddle hierarchy artifact
beside it.  A single-member fused chain (ctt-stream) lets the blocks task
carry max ids and boundary planes slab-by-slab, covering the offsets and
faces steps — the stitching never re-reads the labels volume.

``ResegmentWorkflow`` wraps one :class:`~..tasks.hier.ResegmentTask` run
(threshold in the ``resegment`` task config): the workflow a proofreading
client submits per threshold — against a warm serve daemon (the
``resegment`` job type, serve/protocol.py) each sweep step is one
union-find pass + one gather per block batch, with the labels volume held
resident in the ctt-hbm DeviceBufferCache.
"""

from __future__ import annotations

import os
from typing import Optional

from ..runtime.stream import FusedChain
from ..runtime.workflow import WorkflowBase
from ..tasks.hier import (
    HIER_ASSIGNMENTS_NAME,
    HIER_OFFSETS_NAME,
    BuildHierarchyTask,
    HierarchyBlocksTask,
    HierarchyFacesTask,
    HierarchyOffsetsTask,
    ResegmentTask,
    default_hierarchy_path,
)
from ..tasks.write import WriteTask


class HierarchyWorkflow(WorkflowBase):
    """One-flood hierarchy build over ``input_path/input_key``: global
    watershed labels at ``output_key`` + the hierarchy artifact
    (``hierarchy_path``, default ``<output_key>_hierarchy.npz`` beside the
    labels volume)."""

    task_name = "hierarchy_workflow"

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        target: Optional[str] = None,
        input_path: str = None,
        input_key: str = None,
        output_path: str = None,
        output_key: str = None,
        hierarchy_path: Optional[str] = None,
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.hierarchy_path = hierarchy_path or (
            default_hierarchy_path(output_path, output_key)
            if output_path and output_key else None
        )

    def _tasks(self):
        """One definition of the member tasks: ``requires()`` and
        ``fused_chains()`` must describe the SAME instances (the
        streaming-workflow convention) or the chain would satisfy
        different status files than the DAG runs."""
        blocks_key = self.output_key + "_blocks"
        blocks = HierarchyBlocksTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            input_path=self.input_path,
            input_key=self.input_key,
            output_path=self.output_path,
            output_key=blocks_key,
        )
        offsets = HierarchyOffsetsTask(
            self.tmp_folder,
            self.config_dir,
            dependencies=[blocks],
            input_path=self.output_path,
            input_key=blocks_key,
        )
        faces = HierarchyFacesTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            dependencies=[offsets],
            input_path=self.output_path,
            input_key=blocks_key,
            heights_path=self.input_path,
            heights_key=self.input_key,
        )
        build = BuildHierarchyTask(
            self.tmp_folder,
            self.config_dir,
            dependencies=[faces],
            input_path=self.output_path,
            input_key=blocks_key,
            hierarchy_path=self.hierarchy_path,
        )
        write = WriteTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            dependencies=[build],
            input_path=self.output_path,
            input_key=blocks_key,
            output_path=self.output_path,
            output_key=self.output_key,
            assignment_path=os.path.join(
                self.tmp_folder, HIER_ASSIGNMENTS_NAME
            ),
            offsets_path=os.path.join(self.tmp_folder, HIER_OFFSETS_NAME),
            identifier="hierarchy",
        )
        return blocks, offsets, faces, build, write

    def requires(self):
        *_, write = self._tasks()
        return [write]

    def fused_chains(self):
        blocks, offsets, faces, _build, _write = self._tasks()
        return [
            FusedChain(
                name="hier_blocks",
                members=[blocks],
                covers=[offsets, faces],
            )
        ]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["hierarchy_blocks"] = HierarchyBlocksTask.default_task_config()
        conf["hierarchy_faces"] = HierarchyFacesTask.default_task_config()
        conf["write"] = WriteTask.default_task_config()
        return conf


class ResegmentWorkflow(WorkflowBase):
    """One threshold re-cut of a built hierarchy (the ``resegment`` task
    config carries the threshold): labels volume + artifact in, merged
    labels volume out — the per-sweep-step workflow."""

    task_name = "resegment_workflow"

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        target: Optional[str] = None,
        labels_path: str = None,
        labels_key: str = None,
        output_path: str = None,
        output_key: str = None,
        hierarchy_path: Optional[str] = None,
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.labels_path = labels_path
        self.labels_key = labels_key
        self.output_path = output_path
        self.output_key = output_key
        self.hierarchy_path = hierarchy_path or (
            default_hierarchy_path(labels_path, labels_key)
            if labels_path and labels_key else None
        )

    def requires(self):
        return [
            ResegmentTask(
                self.tmp_folder,
                self.config_dir,
                self.max_jobs,
                input_path=self.labels_path,
                input_key=self.labels_key,
                output_path=self.output_path,
                output_key=self.output_key,
                hierarchy_path=self.hierarchy_path,
            )
        ]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["resegment"] = ResegmentTask.default_task_config()
        return conf
