"""Paintera export workflows: label multisets, per-block lookups, metadata
(reference label_multisets/label_multiset_workflow.py:10 and
paintera/conversion_workflow.py:20-97)."""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from ..runtime.task import SimpleTask
from ..tasks.label_multisets import CreateMultisetTask, DownscaleMultisetTask
from ..tasks.paintera import LabelBlockMappingTask, UniqueBlockLabelsTask
from ..runtime.workflow import WorkflowBase
from ..utils import store


def _accumulate(scale_factors) -> List[List[int]]:
    eff = [1, 1, 1]
    out = []
    for sf in scale_factors:
        sf3 = [sf] * 3 if isinstance(sf, int) else list(sf)
        eff = [e * s for e, s in zip(eff, sf3)]
        out.append(list(eff))
    return out


class LabelMultisetWorkflow(WorkflowBase):
    """Multiset pyramid under ``output_prefix/s{level}``
    (reference label_multiset_workflow.py:10)."""

    task_name = "label_multiset_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path=None, input_key=None, output_path=None,
                 output_prefix: str = "data",
                 scale_factors: Sequence = (),
                 restrict_sets: Optional[Sequence[int]] = None):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_prefix = output_prefix
        self.scale_factors = list(scale_factors)
        self.restrict_sets = (
            list(restrict_sets)
            if restrict_sets is not None
            else [-1] * len(self.scale_factors)
        )
        if len(self.restrict_sets) != len(self.scale_factors):
            raise ValueError("need one restrict_set per scale factor")

    def requires(self):
        s0_key = os.path.join(self.output_prefix, "s0")
        create = CreateMultisetTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=s0_key,
        )
        tasks = [create]
        dep = create
        in_key = s0_key
        effective = _accumulate(self.scale_factors)
        for i, (sf, restrict) in enumerate(
            zip(self.scale_factors, self.restrict_sets)
        ):
            out_key = os.path.join(self.output_prefix, f"s{i + 1}")
            dep = DownscaleMultisetTask(
                self.tmp_folder, self.config_dir, self.max_jobs,
                dependencies=[dep],
                input_path=self.output_path, input_key=in_key,
                output_path=self.output_path, output_key=out_key,
                scale_factor=sf, restrict_set=restrict,
                effective_scale_factor=effective[i],
                scale_prefix=f"s{i + 1}",
            )
            tasks.append(dep)
            in_key = out_key
        return tasks

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["create_multiset"] = CreateMultisetTask.default_task_config()
        conf["downscale_multiset"] = DownscaleMultisetTask.default_task_config()
        return conf


class WritePainteraMetadataTask(SimpleTask):
    """Top-level paintera label-group metadata
    (reference conversion_workflow.py:20-97)."""

    task_name = "write_paintera_metadata"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None,
                 dependencies=(), path=None, raw_key=None, label_group=None,
                 raw_resolution=(1, 1, 1), label_resolution=(1, 1, 1),
                 n_scales: int = 1, offset=(0, 0, 0), max_id: int = 0):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        self.path = path
        self.raw_key = raw_key
        self.label_group = label_group
        self.raw_resolution = list(raw_resolution)
        self.label_resolution = list(label_resolution)
        self.n_scales = n_scales
        self.offset = list(offset)
        self.max_id = max_id

    def run_impl(self) -> None:
        f = store.file_reader(self.path, "a")
        g = f.require_group(self.label_group)
        g.attrs["painteraData"] = {"type": "label"}
        g.attrs["maxId"] = int(self.max_id)
        g.attrs["labelBlockLookup"] = {
            "type": "n5-filesystem-relative",
            "scaleDatasetPattern": "label-to-block-mapping/s%d",
        }
        data_group = g.require_group("data")
        data_group.attrs["maxId"] = int(self.max_id)
        data_group.attrs["multiScale"] = True
        # java XYZ axis order
        data_group.attrs["offset"] = self.offset[::-1]
        data_group.attrs["resolution"] = self.label_resolution[::-1]

        for aux in ("unique-labels", "label-to-block-mapping"):
            if aux in g:
                aux_group = g.require_group(aux)
                aux_group.attrs["multiScale"] = True
                for scale in range(1, self.n_scales):
                    key = f"s{scale}"
                    factors = data_group[key].attrs.get("downsamplingFactors")
                    if factors and key in aux_group:
                        aux_group[key].attrs["downsamplingFactors"] = factors
        if self.raw_key:
            f.require_group(self.raw_key).attrs["resolution"] = (
                self.raw_resolution[::-1]
            )


class PainteraConversionWorkflow(WorkflowBase):  # ctt: noqa[CTT105] DAG shape depends on the input container's scale metadata (per-scale lookup tasks), so it cannot be built against sentinel paths
    """Full paintera label container: multiset pyramid + per-scale
    unique-labels + label-to-block lookup + metadata
    (reference conversion_workflow.py ConversionWorkflow)."""

    task_name = "paintera_conversion_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path=None, input_key=None, output_path=None,
                 label_group: str = "paintera", raw_key: str = None,
                 scale_factors: Sequence = (),
                 restrict_sets: Optional[Sequence[int]] = None,
                 resolution=(1, 1, 1), offset=(0, 0, 0)):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.label_group = label_group
        self.raw_key = raw_key
        self.scale_factors = list(scale_factors)
        self.restrict_sets = restrict_sets
        self.resolution = list(resolution)
        self.offset = list(offset)

    def requires(self):
        data_prefix = os.path.join(self.label_group, "data")
        multisets = LabelMultisetWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs, self.target,
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_prefix=data_prefix,
            scale_factors=self.scale_factors, restrict_sets=self.restrict_sets,
        )
        tasks = [multisets]
        n_scales = len(self.scale_factors) + 1
        # per-scale unique labels + block lookup: s0 reads the original
        # labels, coarser scales read the multiset levels (the metadata
        # declares the lookup pattern for every scale, so every scale must
        # exist — reference conversion_workflow.py emits all of them too)
        mappings = []
        for scale in range(n_scales):
            if scale == 0:
                in_path, in_key = self.input_path, self.input_key
            else:
                in_path = self.output_path
                in_key = os.path.join(data_prefix, f"s{scale}")
            uniques_key = os.path.join(
                self.label_group, "unique-labels", f"s{scale}"
            )
            uniques = UniqueBlockLabelsTask(
                self.tmp_folder, self.config_dir, self.max_jobs,
                dependencies=[multisets],
                input_path=in_path, input_key=in_key,
                output_path=self.output_path, output_key=uniques_key,
                prefix=f"s{scale}",
            )
            tasks.append(uniques)
            mapping = LabelBlockMappingTask(
                self.tmp_folder, self.config_dir,
                dependencies=[uniques],
                input_path=self.output_path, input_key=uniques_key,
                output_path=self.output_path,
                output_key=os.path.join(
                    self.label_group, "label-to-block-mapping", f"s{scale}"
                ),
                prefix=f"s{scale}",
            )
            tasks.append(mapping)
            mappings.append(mapping)

        max_id = int(
            store.file_reader(self.input_path, "r")[self.input_key].attrs.get(
                "maxId", 0
            )
        )
        meta = WritePainteraMetadataTask(
            self.tmp_folder, self.config_dir,
            dependencies=mappings,
            path=self.output_path, raw_key=self.raw_key,
            label_group=self.label_group,
            raw_resolution=self.resolution,
            label_resolution=self.resolution,
            n_scales=n_scales, offset=self.offset, max_id=max_id,
        )
        tasks.append(meta)
        return tasks
