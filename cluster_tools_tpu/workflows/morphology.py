"""Morphology workflow (reference morphology_workflow.py:11):
per-block morphology partials → merged per-segment table."""

from __future__ import annotations

from ..runtime.workflow import WorkflowBase
from ..tasks.morphology import BlockMorphologyTask, MergeMorphologyTask


class MorphologyWorkflow(WorkflowBase):
    task_name = "morphology_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path: str = None, input_key: str = None,
                 dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key

    def requires(self):
        block = BlockMorphologyTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=list(self.dependencies),
            input_path=self.input_path, input_key=self.input_key,
        )
        merge = MergeMorphologyTask(
            self.tmp_folder, self.config_dir, dependencies=[block],
            input_path=self.input_path, input_key=self.input_key,
        )
        return [merge]
