"""Morphology workflows (reference morphology_workflow.py:11,59):
per-block morphology partials → merged per-segment table, and the
region-centers table built on top of it."""

from __future__ import annotations

from ..runtime.workflow import WorkflowBase
from ..tasks.morphology import (
    BlockMorphologyTask,
    MergeMorphologyTask,
    RegionCentersTask,
)


class MorphologyWorkflow(WorkflowBase):
    task_name = "morphology_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path: str = None, input_key: str = None,
                 dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key

    def requires(self):
        block = BlockMorphologyTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=list(self.dependencies),
            input_path=self.input_path, input_key=self.input_key,
        )
        merge = MergeMorphologyTask(
            self.tmp_folder, self.config_dir, dependencies=[block],
            input_path=self.input_path, input_key=self.input_key,
        )
        return [merge]


class RegionCentersWorkflow(WorkflowBase):
    """morphology → region_centers (reference morphology_workflow.py:59-95):
    per-segment representative interior points as a (n_labels, 3) table."""

    task_name = "region_centers_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path: str = None, input_key: str = None,
                 output_path: str = None, output_key: str = None,
                 ignore_label=None, resolution=(1, 1, 1), dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.ignore_label = ignore_label
        self.resolution = list(resolution)

    def requires(self):
        morpho = MorphologyWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs, self.target,
            input_path=self.input_path, input_key=self.input_key,
            dependencies=list(self.dependencies),
        )
        centers = RegionCentersTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[morpho],
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=self.output_key,
            ignore_label=self.ignore_label, resolution=self.resolution,
        )
        return [centers]
