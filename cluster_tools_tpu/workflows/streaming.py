"""ctt-stream flagship workflow: one streaming pass over the raw volume.

``StreamingSegmentationWorkflow`` wires the reference-shaped task DAG —
threshold → block CC → merge offsets → block faces → union-find → write,
plus the DT-watershed fragmentation of the same raw volume — and declares
the fusible chain over its split-protocol members:

  * the raw volume is read ONCE per block (at the watershed's halo; the
    threshold/CC reads are crops of the same host buffer);
  * the threshold mask is **elided**: it flows threshold → CC as a device
    array and never exists on the store;
  * the CC labels volume is written (the union-find write step needs it),
    but its downstream re-reads are **covered** by carried state: per-block
    max ids become the offsets npz and the face-edge equivalence tables
    become the block-faces chunks — MergeOffsetsTask and BlockFacesTask
    are stamped complete without re-reading a voxel.

Run with ``stream_fusion: false`` (or ``CTT_STREAM_FUSION=0``) and exactly
the same tasks execute task-at-a-time with every intermediate
materialized — the parity oracle; outputs are byte-identical either way.
"""

from __future__ import annotations

import os
from typing import List, Optional

from ..runtime.stream import FusedChain
from ..runtime.workflow import WorkflowBase
from ..tasks.threshold import ThresholdTask
from ..tasks.thresholded_components import (
    ASSIGNMENTS_NAME,
    OFFSETS_NAME,
    BlockComponentsTask,
    BlockFacesTask,
    MergeAssignmentsTask,
    MergeOffsetsTask,
)
from ..tasks.watershed import WatershedTask
from ..tasks.write import WriteTask


class StreamingSegmentationWorkflow(WorkflowBase):
    """Fused threshold → thresholded-components → watershed pipeline.

    Outputs: merged connected components at ``output_key`` and (with
    ``watershed=True``) DT-watershed fragments at ``ws_key`` (default
    ``output_key + "_ws"``), both over ``input_path/input_key``.
    """

    task_name = "streaming_segmentation_workflow"

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        target: Optional[str] = None,
        input_path: str = None,
        input_key: str = None,
        output_path: str = None,
        output_key: str = None,
        ws_key: Optional[str] = None,
        mask_path: str = None,
        mask_key: str = None,
        watershed: bool = True,
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.ws_key = ws_key or (output_key + "_ws" if output_key else None)
        self.mask_path = mask_path
        self.mask_key = mask_key
        self.watershed = watershed

    # -- task wiring ---------------------------------------------------------

    def _tasks(self):
        """One definition of the member tasks — ``requires()`` and
        ``fused_chains()`` must describe the SAME instances (equal
        configuration → equal status paths), or the chain would satisfy
        different tasks than the DAG runs."""
        mask_key = self.output_key + "_mask"
        blocks_key = self.output_key + "_blocks"
        threshold = ThresholdTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            input_path=self.input_path,
            input_key=self.input_key,
            output_path=self.output_path,
            output_key=mask_key,
        )
        components = BlockComponentsTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            dependencies=[threshold],
            input_path=self.output_path,
            input_key=mask_key,
            output_path=self.output_path,
            output_key=blocks_key,
            mask_path=self.mask_path,
            mask_key=self.mask_key,
        )
        offsets = MergeOffsetsTask(
            self.tmp_folder,
            self.config_dir,
            dependencies=[components],
            input_path=self.input_path,
            input_key=self.input_key,
        )
        faces = BlockFacesTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            dependencies=[offsets],
            input_path=self.output_path,
            input_key=blocks_key,
        )
        assignments = MergeAssignmentsTask(
            self.tmp_folder,
            self.config_dir,
            dependencies=[faces],
            input_path=self.input_path,
            input_key=self.input_key,
        )
        write = WriteTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            dependencies=[assignments],
            input_path=self.output_path,
            input_key=blocks_key,
            output_path=self.output_path,
            output_key=self.output_key,
            assignment_path=os.path.join(self.tmp_folder, ASSIGNMENTS_NAME),
            offsets_path=os.path.join(self.tmp_folder, OFFSETS_NAME),
            identifier="streaming_components",
        )
        ws = None
        if self.watershed:
            ws = WatershedTask(
                self.tmp_folder,
                self.config_dir,
                self.max_jobs,
                input_path=self.input_path,
                input_key=self.input_key,
                output_path=self.output_path,
                output_key=self.ws_key,
                mask_path=self.mask_path,
                mask_key=self.mask_key,
            )
        return threshold, components, offsets, faces, write, ws

    def requires(self):
        threshold, components, offsets, faces, write, ws = self._tasks()
        roots: List = [write]
        if ws is not None:
            roots.append(ws)
        return roots

    def fused_chains(self):
        threshold, components, offsets, faces, write, ws = self._tasks()
        members = [threshold, components]
        if ws is not None:
            members.append(ws)
        return [
            FusedChain(
                name="stream_tcw",
                members=members,
                elide={threshold.identifier},
                covers=[offsets, faces],
            )
        ]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["threshold"] = ThresholdTask.default_task_config()
        conf["block_components"] = BlockComponentsTask.default_task_config()
        conf["watershed"] = WatershedTask.default_task_config()
        conf["write"] = WriteTask.default_task_config()
        return conf
