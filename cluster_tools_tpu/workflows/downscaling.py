"""Scale-pyramid workflows: multiscale export with paintera / bdv.n5 metadata.

Reference downscaling/downscaling_workflow.py: chain one DownscalingTask per
pyramid level (each reading the previous level), link/copy the initial scale
into the multiscale group, then write format metadata:

  * ``paintera``  — n5 group with per-scale ``downsamplingFactors`` (reversed
    to java axis order), root ``multiScale``/``resolution``/``offset`` attrs,
    and a mirrored ``maxId`` (reference downscaling_workflow.py:42-71);
  * ``bdv.n5``    — setup/timepoint key layout with per-scale n5 metadata and
    a BigDataViewer XML sidecar (reference downscaling_workflow.py:73-86 via
    pybdv; the XML here is written directly);
  * ``bdv`` / ``bdv.hdf5`` — the classic h5 layout
    (``t00000/s00/<scale>/cells`` datasets plus root ``s00/resolutions`` and
    ``s00/subdivisions`` tables in xyz order, reference
    downscaling_workflow.py:73-86 via pybdv.write_h5_metadata) through the
    store's h5 backend.

``PainteraToBdvWorkflow`` converts an existing paintera multiscale group to
either bdv flavor (reference downscaling_workflow.py:272-330).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

from ..runtime.task import SimpleTask
from ..runtime.workflow import WorkflowBase
from ..tasks.copy_volume import CopyVolumeTask
from ..tasks.downscaling import DownscalingTask, ScaleToBoundariesTask, UpscalingTask
from ..utils import store


H5_EXTS = (".h5", ".hdf5", ".hdf")


def is_h5_path(path: str) -> bool:
    return os.path.splitext(path)[1].lower() in H5_EXTS


def bdv_scale_key(
    scale: int, setup: int = 0, timepoint: int = 0, h5: bool = False
) -> str:
    """Scale-dataset key of the bdv layouts (reference get_scale_key,
    downscaling_workflow.py:160-168 via pybdv.util.get_key)."""
    if h5:
        return f"t{timepoint:05d}/s{setup:02d}/{scale}/cells"
    return f"setup{setup}/timepoint{timepoint}/s{scale}"


def _accumulate_scales(scale_factors) -> List[List[int]]:
    """Effective (cumulative) per-level factors."""
    eff = [1, 1, 1]
    out = []
    for sf in scale_factors:
        sf3 = [sf] * 3 if isinstance(sf, int) else list(sf)
        eff = [e * s for e, s in zip(eff, sf3)]
        out.append(list(eff))
    return out


def write_bdv_xml(
    xml_path: str, data_path: str, shape, resolution, unit, h5: bool = False
) -> None:
    """Minimal single-setup, single-timepoint BigDataViewer XML."""
    sz = " ".join(str(s) for s in shape[::-1])
    res = " ".join(str(r) for r in resolution[::-1])
    affine = []
    for row in range(3):
        vals = [0.0] * 4
        vals[row] = float(resolution[::-1][row])
        affine.extend(vals)
    affine_s = " ".join(str(v) for v in affine)
    rel = os.path.basename(data_path)
    loader = (
        f'<ImageLoader format="bdv.hdf5">\n'
        f'      <hdf5 type="relative">{rel}</hdf5>'
        if h5
        else f'<ImageLoader format="bdv.n5" version="1.0">\n'
        f'      <n5 type="relative">{rel}</n5>'
    )
    xml = f"""<?xml version="1.0" encoding="UTF-8"?>
<SpimData version="0.2">
  <BasePath type="relative">.</BasePath>
  <SequenceDescription>
    {loader}
    </ImageLoader>
    <ViewSetups>
      <ViewSetup>
        <id>0</id>
        <name>setup0</name>
        <size>{sz}</size>
        <voxelSize>
          <unit>{unit}</unit>
          <size>{res}</size>
        </voxelSize>
      </ViewSetup>
    </ViewSetups>
    <Timepoints type="pattern">
      <integerpattern>0</integerpattern>
    </Timepoints>
  </SequenceDescription>
  <ViewRegistrations>
    <ViewRegistration timepoint="0" setup="0">
      <ViewTransform type="affine">
        <affine>{affine_s}</affine>
      </ViewTransform>
    </ViewRegistration>
  </ViewRegistrations>
</SpimData>
"""
    with open(xml_path, "w") as f:
        f.write(xml)


class WriteDownscalingMetadataTask(SimpleTask):
    """Multiscale metadata for a completed pyramid
    (reference downscaling_workflow.py:17-99)."""

    task_name = "write_downscaling_metadata"

    def __init__(
        self,
        tmp_folder: str,
        config_dir=None,
        max_jobs=None,
        dependencies=(),
        output_path: str = None,
        scale_factors: Sequence = (),
        metadata_format: str = "paintera",
        metadata_dict: Optional[Dict[str, Any]] = None,
        output_key_prefix: str = "",
        scale_offset: int = 0,
        prefix: str = "downscaling",
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        self.output_path = output_path
        self.scale_factors = list(scale_factors)
        self.metadata_format = metadata_format
        self.metadata_dict = metadata_dict or {}
        self.output_key_prefix = output_key_prefix
        self.scale_offset = scale_offset
        self.prefix = prefix

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_{self.prefix}"

    def _base_factor(self, f) -> List[int]:
        """Cumulative factor of the existing level s{scale_offset} relative to
        s0 (identity when starting from scratch)."""
        if self.scale_offset == 0:
            return [1, 1, 1]
        key = (
            os.path.join(self.output_key_prefix, f"s{self.scale_offset}")
            if self.metadata_format == "paintera"
            else bdv_scale_key(self.scale_offset)
        )
        prior = f[key].attrs.get("downsamplingFactors")
        return list(prior[::-1]) if prior else [1, 1, 1]

    def _paintera_metadata(self) -> None:
        f = store.file_reader(self.output_path, "a")
        g = f.require_group(self.output_key_prefix)
        base = self._base_factor(f)
        effective = [
            [b * e for b, e in zip(base, eff)]
            for eff in _accumulate_scales(self.scale_factors)
        ]
        for scale, eff in enumerate(effective, 1):
            # java (xyz) axis order: reverse
            g[f"s{scale + self.scale_offset}"].attrs["downsamplingFactors"] = (
                eff[::-1]
            )
        resolution = self.metadata_dict.get("resolution", [1.0] * 3)
        offsets = self.metadata_dict.get("offsets", [0.0] * 3)
        g.attrs["multiScale"] = True
        g.attrs["resolution"] = resolution[::-1]
        g.attrs["offset"] = offsets[::-1]
        s0 = g[f"s{self.scale_offset}"]
        if "maxId" in s0.attrs:
            g.attrs["maxId"] = s0.attrs["maxId"]

    def _bdv_metadata(self) -> None:
        f = store.file_reader(self.output_path, "a")
        resolution = self.metadata_dict.get("resolution", [1.0] * 3)
        unit = self.metadata_dict.get("unit", "pixel")
        base = self._base_factor(f)
        new = [
            [b * e for b, e in zip(base, eff)]
            for eff in _accumulate_scales(self.scale_factors)
        ]
        # existing levels 0..scale_offset keep their factors; read them back
        # so the setup-level list covers the full pyramid
        existing = []
        for scale in range(self.scale_offset + 1):
            prior = f[bdv_scale_key(scale)].attrs.get("downsamplingFactors")
            existing.append(
                list(prior) if prior else [1, 1, 1]
            )
        factors = existing + [e[::-1] for e in new]
        for scale, eff in enumerate(factors):
            f[bdv_scale_key(scale)].attrs["downsamplingFactors"] = eff
        s_ref = f[bdv_scale_key(0)]
        setup = f["setup0"]
        setup.attrs["downsamplingFactors"] = factors
        setup.attrs["dataType"] = str(s_ref.dtype)
        xml_path = os.path.splitext(self.output_path)[0] + ".xml"
        write_bdv_xml(xml_path, self.output_path, s_ref.shape, resolution, unit)

    def _bdv_h5_metadata(self) -> None:
        """Classic bdv.hdf5 metadata (reference via pybdv.write_h5_metadata):
        ``s00/resolutions`` — absolute per-scale downsampling factors — and
        ``s00/subdivisions`` — per-scale chunk shapes — both xyz-ordered
        tables at the file root, plus the XML sidecar."""
        import numpy as np

        f = store.file_reader(self.output_path, "a")
        resolution = self.metadata_dict.get("resolution", [1.0] * 3)
        unit = self.metadata_dict.get("unit", "pixel")
        # existing levels 0..scale_offset keep their factor rows (read back
        # from a prior s00/resolutions, like the n5 writer's _base_factor
        # path); new levels accumulate on top of the last existing row
        existing = []
        if self.scale_offset > 0 and "s00/resolutions" in f:
            prior = np.asarray(f["s00/resolutions"][:])
            existing = [
                list(map(float, row)) for row in prior[: self.scale_offset + 1]
            ]
        while len(existing) < self.scale_offset + 1:
            existing.append([1.0, 1.0, 1.0])
        base = existing[-1][::-1]  # xyz row → zyx for accumulation
        new = [
            [b * e for b, e in zip(base, eff)][::-1]
            for eff in _accumulate_scales(self.scale_factors)
        ]
        factors = existing + new  # xyz rows covering the whole pyramid
        res_rows, sub_rows = [], []
        for scale, eff in enumerate(factors):
            key = bdv_scale_key(scale, h5=True)
            if key not in f:
                break
            ds = f[key]
            chunks = ds.chunks or ds.shape
            res_rows.append(list(map(float, eff)))
            sub_rows.append(list(map(int, chunks))[::-1])
        g = f.require_group("s00")
        for name, rows, dt in (
            ("resolutions", res_rows, "float64"),
            ("subdivisions", sub_rows, "int32"),
        ):
            if name in g:
                del g[name]
            g.create_dataset(name, data=np.asarray(rows, dtype=dt))
        s_ref = f[bdv_scale_key(0, h5=True)]
        xml_path = os.path.splitext(self.output_path)[0] + ".xml"
        write_bdv_xml(
            xml_path, self.output_path, s_ref.shape, resolution, unit, h5=True
        )

    def run_impl(self) -> None:
        if self.metadata_format == "paintera":
            self._paintera_metadata()
        elif self.metadata_format == "bdv.n5":
            self._bdv_metadata()
        elif self.metadata_format in ("bdv", "bdv.hdf5"):
            self._bdv_h5_metadata()
        else:
            raise ValueError(
                f"metadata format {self.metadata_format!r} is not supported "
                "(paintera, bdv.n5, bdv/bdv.hdf5 are)"
            )


class DownscalingWorkflow(WorkflowBase):
    """Full pyramid build (reference downscaling_workflow.py:102-270)."""

    task_name = "downscaling_workflow"

    def __init__(
        self,
        tmp_folder: str,
        config_dir=None,
        max_jobs=None,
        target=None,
        input_path: str = None,
        input_key: str = None,
        scale_factors: Sequence = (2,),
        halos: Optional[Sequence] = None,
        metadata_format: str = "paintera",
        metadata_dict: Optional[Dict[str, Any]] = None,
        output_path: str = "",
        output_key_prefix: str = "",
        force_copy: bool = False,
        scale_offset: int = 0,
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        self.scale_factors = list(scale_factors)
        self.halos = list(halos) if halos is not None else [[]] * len(
            self.scale_factors
        )
        if len(self.halos) != len(self.scale_factors):
            raise ValueError("need one halo per scale factor")
        self.metadata_format = metadata_format
        self.metadata_dict = metadata_dict or {}
        self.output_path = output_path or input_path
        self.output_key_prefix = output_key_prefix
        self.force_copy = force_copy
        self.scale_offset = scale_offset
        if metadata_format not in ("paintera", "bdv", "bdv.hdf5", "bdv.n5"):
            raise ValueError(f"unknown metadata format {metadata_format!r}")
        if metadata_format == "paintera" and not output_key_prefix:
            raise ValueError("paintera format needs output_key_prefix")
        # extension/format pairing (reference validate_format,
        # downscaling_workflow.py:143-158)
        if metadata_format in ("bdv", "bdv.hdf5") and not is_h5_path(
            self.output_path
        ):
            raise ValueError(f"{metadata_format} needs an .h5/.hdf5 output")
        if metadata_format in ("paintera", "bdv.n5") and is_h5_path(
            self.output_path
        ):
            raise ValueError(f"{metadata_format} needs an n5/zarr output")

    def get_scale_key(self, scale: int) -> str:
        if self.metadata_format == "paintera":
            return os.path.join(self.output_key_prefix, f"s{scale}")
        return bdv_scale_key(
            scale, h5=self.metadata_format in ("bdv", "bdv.hdf5")
        )

    def _have_initial_scale(self, in_key: str) -> bool:
        try:
            return in_key in store.file_reader(self.output_path, "r")
        except FileNotFoundError:
            return False

    def requires(self):
        in_key = self.get_scale_key(self.scale_offset)
        tasks = []
        # initial scale: copy into the pyramid group unless it is already
        # there (reference links instead when input==output; a copy is the
        # store-agnostic equivalent and force_copy always re-copies)
        if self.force_copy or not self._have_initial_scale(in_key):
            dep = CopyVolumeTask(
                self.tmp_folder,
                self.config_dir,
                self.max_jobs,
                input_path=self.input_path,
                input_key=self.input_key,
                output_path=self.output_path,
                output_key=in_key,
                prefix="initial_scale",
            )
            tasks.append(dep)
        else:
            dep = None
        effective = _accumulate_scales(self.scale_factors)
        for i, (sf, halo) in enumerate(zip(self.scale_factors, self.halos)):
            scale = self.scale_offset + 1 + i
            out_key = self.get_scale_key(scale)
            dep = DownscalingTask(
                self.tmp_folder,
                self.config_dir,
                self.max_jobs,
                dependencies=[dep] if dep is not None else [],
                input_path=self.output_path,
                input_key=in_key,
                output_path=self.output_path,
                output_key=out_key,
                scale_factor=sf,
                scale_prefix=f"s{scale}",
                halo=halo,
                effective_scale_factor=effective[i],
            )
            tasks.append(dep)
            in_key = out_key
        meta = WriteDownscalingMetadataTask(
            self.tmp_folder,
            self.config_dir,
            dependencies=[dep],
            output_path=self.output_path,
            scale_factors=self.scale_factors,
            metadata_format=self.metadata_format,
            metadata_dict=self.metadata_dict,
            output_key_prefix=self.output_key_prefix,
            scale_offset=self.scale_offset,
        )
        tasks.append(meta)
        return tasks

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["downscaling"] = DownscalingTask.default_task_config()
        conf["copy_volume"] = CopyVolumeTask.default_task_config()
        return conf


class PainteraToBdvWorkflow(WorkflowBase):  # ctt: noqa[CTT105] DAG shape depends on the input container's scale metadata (requires() enumerates s<i> levels), so it cannot be built against sentinel paths
    """Convert an existing paintera multiscale group to a bdv container
    (reference downscaling_workflow.py:272-330): copy every ``s<i>`` scale
    dataset into the bdv key layout, derive the relative scale factors from
    the paintera ``downsamplingFactors`` attributes, inherit
    ``resolution``/``offset`` group attributes into the metadata, and write
    the bdv metadata + XML sidecar.  The output flavor follows the output
    extension: .h5/.hdf5 → classic bdv.hdf5, else bdv.n5 (the reference
    supports only the h5 flavor here)."""

    task_name = "paintera_to_bdv"

    def __init__(
        self,
        tmp_folder: str,
        config_dir=None,
        max_jobs=None,
        target=None,
        input_path: str = None,
        input_key_prefix: str = None,
        output_path: str = None,
        dtype: Optional[str] = None,
        metadata_dict: Optional[Dict[str, Any]] = None,
        skip_existing_levels: bool = True,
        dependencies=(),
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key_prefix = input_key_prefix
        self.output_path = output_path
        self.dtype = dtype
        self.metadata_dict = metadata_dict or {}
        self.skip_existing_levels = skip_existing_levels

    def _scales(self) -> List[int]:
        try:
            g = store.file_reader(self.input_path, "r")[self.input_key_prefix]
        except (OSError, KeyError) as e:
            # requires() builds the task graph EAGERLY (as the reference's
            # luigi requires() does), so the paintera group must already
            # exist — a dependency that would create it cannot gate this
            raise ValueError(
                f"PainteraToBdvWorkflow needs the paintera group "
                f"{self.input_key_prefix!r} in {self.input_path!r} to exist "
                "when the workflow is constructed — build the pyramid first"
            ) from e
        return sorted(int(name[1:]) for name in g.keys())

    def requires(self):
        h5 = is_h5_path(self.output_path)
        fin = store.file_reader(self.input_path, "r")
        scales = self._scales()
        tasks: List = []
        dep = None
        prev = None
        rel_factors = []
        for scale in scales:
            in_key = os.path.join(self.input_key_prefix, f"s{scale}")
            out_key = bdv_scale_key(scale, h5=h5)
            # paintera attrs are xyz (java) order; internal convention is
            # python zyx — reverse on read (the metadata writers reverse
            # again on their way out)
            eff = fin[in_key].attrs.get("downsamplingFactors", [1, 1, 1])
            eff = (
                [eff] * 3 if isinstance(eff, (int, float)) else list(eff)[::-1]
            )
            if scale > 0 and prev is not None:
                rel_factors.append([e / p for e, p in zip(eff, prev)])
            prev = list(eff)
            if self.skip_existing_levels and os.path.exists(self.output_path):
                try:
                    if out_key in store.file_reader(self.output_path, "r"):
                        continue
                except (OSError, KeyError):
                    pass
            dep = CopyVolumeTask(
                self.tmp_folder,
                self.config_dir,
                self.max_jobs,
                dependencies=[dep] if dep is not None else self.dependencies,
                input_path=self.input_path,
                input_key=in_key,
                output_path=self.output_path,
                output_key=out_key,
                prefix=f"paintera_to_bdv_s{scale}",
                dtype=self.dtype,
                effective_scale_factor=eff,
            )
            tasks.append(dep)

        metadata_dict = {**self.metadata_dict}
        attrs = fin[self.input_key_prefix].attrs
        for src, dst in (("offset", "offsets"), ("resolution", "resolution")):
            val = attrs.get(src)
            if dst not in metadata_dict and val is not None:
                metadata_dict[dst] = list(val)[::-1]  # java xyz → python zyx
        meta = WriteDownscalingMetadataTask(
            self.tmp_folder,
            self.config_dir,
            dependencies=[dep] if dep is not None else list(self.dependencies),
            output_path=self.output_path,
            scale_factors=rel_factors,
            metadata_format="bdv.hdf5" if h5 else "bdv.n5",
            metadata_dict=metadata_dict,
            prefix="paintera_to_bdv",
        )
        tasks.append(meta)
        return tasks

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["copy_volume"] = CopyVolumeTask.default_task_config()
        return conf
