"""The "Problem" pipeline and multicut segmentation workflows.

Reference workflows.py:28-235 and multicut/multicut_workflow.py:11-61:

  GraphWorkflow:        initial_sub_graphs → merge_sub_graphs → map_edge_ids
  EdgeFeaturesWorkflow: block_edge_features → merge_edge_features
  EdgeCostsWorkflow:    probs_to_costs
  MulticutWorkflow:     [solve_subproblems(s) → reduce_problem(s)] × n_scales
                        → solve_global
  MulticutSegmentationWorkflow: watershed → problem → multicut → write
"""

from __future__ import annotations

import os
from typing import Optional

from ..runtime.workflow import WorkflowBase
from ..tasks.costs import ProbsToCostsTask
from ..tasks.features import BlockEdgeFeaturesTask, MergeEdgeFeaturesTask
from ..tasks.graph import (
    InitialSubGraphsTask,
    MapEdgeIdsTask,
    MergeScaleSubGraphsTask,
    MergeSubGraphsTask,
)
from ..tasks.multicut import (
    ASSIGNMENTS_NAME,
    ReducedAssignmentsTask,
    ReduceProblemTask,
    SolveGlobalTask,
    SolveSubproblemsTask,
    SubSolutionsTask,
    reduced_assignments_name,
)
from ..tasks.watershed import WatershedTask
from ..tasks.write import WriteTask


class GraphWorkflow(WorkflowBase):
    """Distributed RAG extraction (reference graph_workflow.py:9).

    ``n_scales > 1`` merges the per-block sub-graphs through a scale pyramid
    (each level dedups 2³ children, reference graph_workflow.py:36-66) before
    the final global merge, bounding the chunk count the single-node merge
    reads at production block counts."""

    task_name = "graph_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path=None, input_key=None, n_scales: int = 1,
                 dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        if int(n_scales) < 1:
            raise ValueError(f"n_scales must be >= 1, got {n_scales}")
        self.n_scales = int(n_scales)

    def requires(self):
        dep = InitialSubGraphsTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=list(self.dependencies),
            input_path=self.input_path, input_key=self.input_key,
        )
        for scale in range(1, self.n_scales):
            dep = MergeScaleSubGraphsTask(
                self.tmp_folder, self.config_dir, self.max_jobs,
                dependencies=[dep],
                input_path=self.input_path, input_key=self.input_key,
                scale=scale,
            )
        merge = MergeSubGraphsTask(
            self.tmp_folder, self.config_dir, dependencies=[dep],
            input_path=self.input_path, input_key=self.input_key,
            scale=self.n_scales - 1,
        )
        map_ids = MapEdgeIdsTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[merge],
            input_path=self.input_path, input_key=self.input_key,
        )
        return [map_ids]


class EdgeFeaturesWorkflow(WorkflowBase):
    """reference features_workflow.py:12."""

    task_name = "edge_features_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path=None, input_key=None, labels_path=None,
                 labels_key=None, dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.labels_path = labels_path
        self.labels_key = labels_key

    def requires(self):
        block = BlockEdgeFeaturesTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=list(self.dependencies),
            input_path=self.input_path, input_key=self.input_key,
            labels_path=self.labels_path, labels_key=self.labels_key,
        )
        merge = MergeEdgeFeaturesTask(
            self.tmp_folder, self.config_dir, dependencies=[block],
            labels_path=self.labels_path, labels_key=self.labels_key,
        )
        return [merge]


def _check_sharded_ws_flags(sharded_ws: bool, sharded_problem: bool) -> None:
    """One definition of the flag contract, raised by BOTH workflow entry
    points (construction-time in MulticutSegmentationWorkflow.requires,
    build-time in ProblemWorkflow.requires)."""
    if sharded_ws and not sharded_problem:
        raise ValueError(
            "sharded_ws=True requires sharded_problem=True (the fused "
            "task produces the collective problem layout)"
        )


class ProblemWorkflow(WorkflowBase):
    """Graph extraction → (optional sanity checks) → edge features →
    (optional) costs: the standalone "problem" pipeline
    (reference workflows.py:28-107).

    ``sanity_checks`` inserts the per-block subgraph validation between graph
    extraction and feature accumulation (reference workflows.py:61-72);
    ``compute_costs=False`` stops after the features (for learning
    pipelines that predict their own probabilities).
    """

    task_name = "problem_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path=None, input_key=None,       # boundary/affinity map
                 ws_path=None, ws_key=None,             # fragment labels
                 n_scales: int = 1,
                 sanity_checks: bool = False,
                 compute_costs: bool = True,
                 probs_path=None,                       # RF edge probabilities
                 node_label_dict=None,
                 sharded_problem: bool = False,
                 sharded_ws: bool = False,
                 dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.n_scales = n_scales
        self.sanity_checks = sanity_checks
        self.compute_costs = compute_costs
        self.probs_path = probs_path
        self.node_label_dict = dict(node_label_dict or {})
        self.sharded_problem = sharded_problem
        self.sharded_ws = sharded_ws

    def requires(self):
        dep = list(self.dependencies)
        _check_sharded_ws_flags(self.sharded_ws, self.sharded_problem)
        if self.sharded_problem:
            if self.sanity_checks:
                # the collective path has no per-block subgraph
                # serialization to verify — refusing beats silently
                # skipping validation the user asked for
                raise ValueError(
                    "sanity_checks=True is not available with "
                    "sharded_problem=True: the collective problem "
                    "extraction has no per-block subgraphs to check"
                )
            if self.sharded_ws:
                # device-resident front: watershed + RAG share one
                # collective session (and the ws dataset is ITS output)
                from ..tasks.features import ShardedWsProblemTask

                problem = ShardedWsProblemTask(
                    self.tmp_folder, self.config_dir, self.max_jobs,
                    dependencies=dep,
                    input_path=self.input_path, input_key=self.input_key,
                    output_path=self.ws_path, output_key=self.ws_key,
                )
            else:
                from ..tasks.features import ShardedProblemTask

                problem = ShardedProblemTask(
                    self.tmp_folder, self.config_dir, self.max_jobs,
                    dependencies=dep,
                    input_path=self.input_path, input_key=self.input_key,
                    labels_path=self.ws_path, labels_key=self.ws_key,
                )
            dep = [problem]
        else:
            graph = GraphWorkflow(
                self.tmp_folder, self.config_dir, self.max_jobs,
                input_path=self.ws_path, input_key=self.ws_key,
                n_scales=self.n_scales, dependencies=dep,
            )
            dep = [graph]
            if self.sanity_checks:
                from ..tasks.debugging import CheckSubGraphsTask

                check = CheckSubGraphsTask(
                    self.tmp_folder, self.config_dir, self.max_jobs,
                    dependencies=dep,
                    input_path=self.ws_path, input_key=self.ws_key,
                )
                dep = [check]
            feats = EdgeFeaturesWorkflow(
                self.tmp_folder, self.config_dir, self.max_jobs,
                input_path=self.input_path, input_key=self.input_key,
                labels_path=self.ws_path, labels_key=self.ws_key,
                dependencies=dep,
            )
            dep = [feats]
        if self.compute_costs:
            costs = ProbsToCostsTask(
                self.tmp_folder, self.config_dir, dependencies=dep,
                probs_path=self.probs_path,
                node_label_dict=self.node_label_dict,
            )
            dep = [costs]
        return dep

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["block_edge_features"] = BlockEdgeFeaturesTask.default_task_config()
        conf["probs_to_costs"] = ProbsToCostsTask.default_task_config()
        from ..tasks.features import ShardedProblemTask

        conf["sharded_problem"] = ShardedProblemTask.default_task_config()
        return conf


def _hierarchical_solve_tasks(
    wf, n_scales: int, dep: list, ws_path: str, ws_key: str
) -> list:
    """solve_subproblems(s) → reduce_problem(s) chains for scales
    0..n_scales-1, so the scale-``n_scales`` problem exists afterwards."""
    for scale in range(n_scales):
        solve = SolveSubproblemsTask(
            wf.tmp_folder, wf.config_dir, wf.max_jobs,
            dependencies=dep, scale=scale,
            input_path=ws_path, input_key=ws_key,
        )
        reduce_ = ReduceProblemTask(
            wf.tmp_folder, wf.config_dir,
            dependencies=[solve], scale=scale,
            input_path=ws_path, input_key=ws_key,
        )
        dep = [reduce_]
    return dep


class MulticutWorkflow(WorkflowBase):
    """Hierarchical multicut solve (reference multicut_workflow.py:45)."""

    task_name = "multicut_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path=None, input_key=None, n_scales: int = 1,
                 dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.n_scales = n_scales

    def requires(self):
        dep = _hierarchical_solve_tasks(
            self, self.n_scales, list(self.dependencies),
            self.input_path, self.input_key,
        )
        solve_global = SolveGlobalTask(
            self.tmp_folder, self.config_dir, dependencies=dep,
            scale=self.n_scales,
        )
        return [solve_global]


class MulticutSegmentationWorkflow(WorkflowBase):
    """watershed → graph → features → costs → multicut → write
    (reference workflows.py:203-233)."""

    task_name = "multicut_segmentation_workflow"

    def __init__(
        self,
        tmp_folder,
        config_dir=None,
        max_jobs=None,
        target=None,
        input_path: str = None,       # boundary / affinity map
        input_key: str = None,
        ws_path: str = None,          # watershed volume (created if missing)
        ws_key: str = None,
        output_path: str = None,      # final segmentation
        output_key: str = None,
        mask_path: str = None,
        mask_key: str = None,
        n_scales: int = 1,
        skip_ws: bool = False,
        sharded_problem: bool = False,
        sharded_ws: bool = False,
        sanity_checks: bool = False,
        node_label_dict: Optional[dict] = None,
        dependencies=(),
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.output_path = output_path
        self.output_key = output_key
        self.mask_path = mask_path
        self.mask_key = mask_key
        self.n_scales = n_scales
        self.skip_ws = skip_ws
        self.sharded_problem = sharded_problem
        self.sharded_ws = sharded_ws
        self.sanity_checks = sanity_checks
        self.node_label_dict = dict(node_label_dict or {})

    def requires(self):
        _check_sharded_ws_flags(self.sharded_ws, self.sharded_problem)
        if self.sharded_ws and self.mask_path:
            raise ValueError(
                "sharded_ws does not support masked volumes — use the "
                "block watershed (sharded_ws=False)"
            )
        if self.sharded_ws and self.skip_ws:
            raise ValueError(
                "skip_ws=True contradicts sharded_ws=True: the fused task "
                "computes the watershed and would overwrite the "
                "precomputed ws dataset — use sharded_ws=False to reuse it"
            )
        dep = list(self.dependencies)
        if not self.skip_ws and not self.sharded_ws:
            ws = WatershedTask(
                self.tmp_folder, self.config_dir, self.max_jobs,
                dependencies=dep,
                input_path=self.input_path, input_key=self.input_key,
                output_path=self.ws_path, output_key=self.ws_key,
                mask_path=self.mask_path, mask_key=self.mask_key,
            )
            dep = [ws]
        problem = ProblemWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs,
            input_path=self.input_path, input_key=self.input_key,
            ws_path=self.ws_path, ws_key=self.ws_key,
            sanity_checks=self.sanity_checks,
            node_label_dict=self.node_label_dict,
            sharded_problem=self.sharded_problem,
            sharded_ws=self.sharded_ws,
            dependencies=dep,
        )
        # the collective problem path has no block edge-id maps, so the solve
        # is the global one (n_scales=0) — consistent with fits-in-HBM
        n_scales = 0 if self.sharded_problem else self.n_scales
        mc = MulticutWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs,
            input_path=self.ws_path, input_key=self.ws_key,
            n_scales=n_scales, dependencies=[problem],
        )
        write = WriteTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[mc],
            input_path=self.ws_path, input_key=self.ws_key,
            output_path=self.output_path, output_key=self.output_key,
            assignment_path=os.path.join(self.tmp_folder, ASSIGNMENTS_NAME),
            identifier="multicut",
        )
        return [write]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["watershed"] = WatershedTask.default_task_config()
        conf["block_edge_features"] = BlockEdgeFeaturesTask.default_task_config()
        conf["probs_to_costs"] = ProbsToCostsTask.default_task_config()
        from ..tasks.features import ShardedProblemTask, ShardedWsProblemTask

        conf["sharded_problem"] = ShardedProblemTask.default_task_config()
        conf["sharded_ws_problem"] = ShardedWsProblemTask.default_task_config()
        return conf


class SubSolutionsWorkflow(WorkflowBase):
    """Hierarchical solve to scale ``n_scales``, then write each block's
    standalone sub-solution for inspection (reference
    multicut_workflow.py:70-100)."""

    task_name = "sub_solutions_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 ws_path=None, ws_key=None,
                 output_path=None, output_key=None,
                 n_scales: int = 0, dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.output_path = output_path
        self.output_key = output_key
        self.n_scales = n_scales

    def requires(self):
        dep = _hierarchical_solve_tasks(
            self, self.n_scales, list(self.dependencies),
            self.ws_path, self.ws_key,
        )
        sub = SubSolutionsTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=dep, scale=self.n_scales,
            input_path=self.ws_path, input_key=self.ws_key,
            output_path=self.output_path, output_key=self.output_key,
        )
        return [sub]


class ReducedSolutionWorkflow(WorkflowBase):
    """Hierarchical solve to scale ``n_scales``, then write the *reduced*
    labeling — merged through the reduces but not globally solved — as a
    segmentation (reference multicut_workflow.py:103-128).  At
    ``n_scales=0`` this reproduces the fragments."""

    task_name = "reduced_solution_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 ws_path=None, ws_key=None,
                 output_path=None, output_key=None,
                 n_scales: int = 0, dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.output_path = output_path
        self.output_key = output_key
        self.n_scales = n_scales

    def requires(self):
        dep = _hierarchical_solve_tasks(
            self, self.n_scales, list(self.dependencies),
            self.ws_path, self.ws_key,
        )
        assign = ReducedAssignmentsTask(
            self.tmp_folder, self.config_dir,
            dependencies=dep, scale=self.n_scales,
        )
        write = WriteTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[assign],
            input_path=self.ws_path, input_key=self.ws_key,
            output_path=self.output_path, output_key=self.output_key,
            assignment_path=os.path.join(
                self.tmp_folder, reduced_assignments_name(self.n_scales)
            ),
            identifier=f"reduced_s{self.n_scales}",
        )
        return [write]
