"""Postprocessing workflow composites
(reference postprocess/postprocess_workflow.py:24-412).

Each composite chains the postprocess tasks the reference wires through
luigi: derive WHICH segments to change (size/intensity/orphan/graph
criteria) → an assignment or discard table → apply block-wise (zero out,
re-flood, or rewrite with the table).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from ..runtime.task import SimpleTask
from ..runtime.workflow import WorkflowBase
from ..tasks.postprocess import (
    GRAPH_CC_NAME,
    GRAPH_WS_NAME,
    ORPHANS_NAME,
    SIZE_FILTER_DISCARD_NAME,
    BackgroundSizeFilterTask,
    FillingSizeFilterTask,
    FilterBlocksTask,
    GraphConnectedComponentsTask,
    GraphWatershedAssignmentsTask,
    OrphanAssignmentsTask,
    SizeFilterTask,
)
from ..tasks.region_features import (
    FEATURE_COLUMNS,
    REGION_FEATURES_NAME,
    MergeRegionFeaturesTask,
    RegionFeaturesTask,
)
from ..tasks.write import WriteTask
from .morphology import MorphologyWorkflow
from .multicut import GraphWorkflow
from .relabel import RelabelWorkflow


class SizeFilterWorkflow(WorkflowBase):
    """Remove segments outside [min_size, max_size]
    (reference SizeFilterWorkflow, postprocess_workflow.py:24-105).

    Without a height map the discarded segments map to background
    (``background_size_filter``); with ``hmap_path/key`` their voxels
    re-flood from the surviving neighbors (``filling_size_filter``).
    ``relabel`` appends a consecutive relabeling of the output.
    """

    task_name = "size_filter_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path: str = None, input_key: str = None,
                 output_path: str = None, output_key: str = None,
                 min_size: int = 0, max_size: Optional[int] = None,
                 hmap_path: str = None, hmap_key: str = None,
                 relabel: bool = False):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.min_size = min_size
        self.max_size = max_size
        self.hmap_path = hmap_path
        self.hmap_key = hmap_key
        self.relabel = relabel

    def requires(self):
        morpho = MorphologyWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs, self.target,
            input_path=self.input_path, input_key=self.input_key,
        )
        size_filter = SizeFilterTask(
            self.tmp_folder, self.config_dir, dependencies=[morpho],
            min_size=self.min_size, max_size=self.max_size, relabel=False,
        )
        discard_path = os.path.join(self.tmp_folder, SIZE_FILTER_DISCARD_NAME)
        apply_key = (
            self.output_key + "_unrelabeled" if self.relabel else self.output_key
        )
        if self.hmap_path:
            apply = FillingSizeFilterTask(
                self.tmp_folder, self.config_dir, self.max_jobs,
                dependencies=[size_filter],
                input_path=self.input_path, input_key=self.input_key,
                output_path=self.output_path, output_key=apply_key,
                hmap_path=self.hmap_path, hmap_key=self.hmap_key,
                res_path=discard_path,
            )
        else:
            apply = BackgroundSizeFilterTask(
                self.tmp_folder, self.config_dir, self.max_jobs,
                dependencies=[size_filter],
                input_path=self.input_path, input_key=self.input_key,
                output_path=self.output_path, output_key=apply_key,
                filter_path=discard_path,
            )
        if not self.relabel:
            return [apply]
        return [
            RelabelWorkflow(
                self.tmp_folder, self.config_dir, self.max_jobs, self.target,
                input_path=self.output_path, input_key=apply_key,
                output_path=self.output_path, output_key=self.output_key,
                dependencies=[apply],
            )
        ]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf.update(MorphologyWorkflow.get_config())
        conf.update(RelabelWorkflow.get_config())
        conf["size_filter"] = SizeFilterTask.default_task_config()
        # both apply variants (hmap selects filling at run time)
        conf["background_size_filter"] = (
            BackgroundSizeFilterTask.default_task_config()
        )
        conf["filling_size_filter"] = FillingSizeFilterTask.default_task_config()
        return conf


class FilterLabelsWorkflow(WorkflowBase):
    """Zero an explicit id list block-wise
    (reference FilterLabelsWorkflow, postprocess_workflow.py:111-158)."""

    task_name = "filter_labels_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path: str = None, input_key: str = None,
                 output_path: str = None, output_key: str = None,
                 filter_labels: Sequence[int] = ()):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.filter_labels = list(filter_labels)

    def requires(self):
        filter_path = os.path.join(self.tmp_folder, "filter_label_ids.npy")
        save_ids = SaveFilterIdsTask(
            self.tmp_folder, self.config_dir,
            filter_labels=self.filter_labels, out_path=filter_path,
        )
        return [
            FilterBlocksTask(
                self.tmp_folder, self.config_dir, self.max_jobs,
                dependencies=[save_ids],
                input_path=self.input_path, input_key=self.input_key,
                output_path=self.output_path, output_key=self.output_key,
                filter_path=filter_path,
            )
        ]


class SaveFilterIdsTask(SimpleTask):
    """Materialize an explicit id list for the block-wise filter (kept out of
    ``requires()`` so DAG inspection never mutates disk)."""

    task_name = "save_filter_ids"

    def __init__(self, *args, filter_labels=(), out_path: str = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.filter_labels = list(filter_labels)
        self.out_path = out_path

    def run_impl(self) -> None:
        np.save(self.out_path, np.asarray(self.filter_labels, dtype="uint64"))


class ApplyFeatureThresholdTask(SimpleTask):
    """Ids whose merged region feature crosses a threshold → discard list
    (reference ApplyThreshold, postprocess_workflow.py:160-191)."""

    task_name = "apply_feature_threshold"

    def __init__(self, *args, threshold: float = 0.5,
                 threshold_mode: str = "less", feature: str = "mean",
                 out_path: str = None, **kwargs):
        super().__init__(*args, **kwargs)
        if threshold_mode not in ("less", "greater", "equal"):
            raise ValueError(f"unsupported threshold_mode {threshold_mode!r}")
        if feature not in FEATURE_COLUMNS:
            raise ValueError(f"unknown feature {feature!r}: {FEATURE_COLUMNS}")
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.feature = feature
        self.out_path = out_path

    def run_impl(self) -> None:
        feats = np.load(os.path.join(self.tmp_folder, REGION_FEATURES_NAME))
        col = feats[:, FEATURE_COLUMNS.index(self.feature)]
        present = feats[:, 0] > 0  # count > 0 = id exists
        if self.threshold_mode == "less":
            sel = col < self.threshold
        elif self.threshold_mode == "greater":
            sel = col > self.threshold
        else:
            sel = col == self.threshold
        ids = np.nonzero(sel & present)[0].astype("uint64")
        ids = ids[ids != 0]
        np.save(self.out_path, ids)
        self.log(
            f"feature threshold ({self.feature} {self.threshold_mode} "
            f"{self.threshold}): {ids.size} ids filtered"
        )


class FilterByThresholdWorkflow(WorkflowBase):
    """Filter segments by a region-feature threshold on an intensity map
    (reference FilterByThresholdWorkflow, postprocess_workflow.py:194-245):
    region features → threshold → filter blocks."""

    task_name = "filter_by_threshold_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path: str = None, input_key: str = None,
                 seg_path: str = None, seg_key: str = None,
                 output_path: str = None, output_key: str = None,
                 threshold: float = 0.5, threshold_mode: str = "less",
                 feature: str = "mean"):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        self.seg_path = seg_path
        self.seg_key = seg_key
        self.output_path = output_path
        self.output_key = output_key
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.feature = feature

    def requires(self):
        feats = RegionFeaturesTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            input_path=self.input_path, input_key=self.input_key,
            labels_path=self.seg_path, labels_key=self.seg_key,
        )
        merge = MergeRegionFeaturesTask(
            self.tmp_folder, self.config_dir, dependencies=[feats],
            input_path=self.seg_path, input_key=self.seg_key,
        )
        filter_path = os.path.join(self.tmp_folder, "feature_filter_ids.npy")
        apply_threshold = ApplyFeatureThresholdTask(
            self.tmp_folder, self.config_dir, dependencies=[merge],
            threshold=self.threshold, threshold_mode=self.threshold_mode,
            feature=self.feature, out_path=filter_path,
        )
        return [
            FilterBlocksTask(
                self.tmp_folder, self.config_dir, self.max_jobs,
                dependencies=[apply_threshold],
                input_path=self.seg_path, input_key=self.seg_key,
                output_path=self.output_path, output_key=self.output_key,
                filter_path=filter_path,
            )
        ]


class FilterOrphansWorkflow(WorkflowBase):
    """Merge orphaned segments (single graph neighbor) into that neighbor
    (reference FilterOrphansWorkflow, postprocess_workflow.py:248-289):
    graph → orphan assignments → write."""

    task_name = "filter_orphans_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path: str = None, input_key: str = None,
                 output_path: str = None, output_key: str = None,
                 assignment_path: str = None, relabel: bool = False):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.assignment_path = assignment_path
        self.relabel = relabel

    def requires(self):
        graph = GraphWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs, self.target,
            input_path=self.input_path, input_key=self.input_key,
        )
        orphans = OrphanAssignmentsTask(
            self.tmp_folder, self.config_dir, dependencies=[graph],
            # None = identity: orphans judged on the raw fragment graph
            assignment_path=self.assignment_path, relabel=self.relabel,
        )
        return [
            WriteTask(
                self.tmp_folder, self.config_dir, self.max_jobs,
                dependencies=[orphans],
                input_path=self.input_path, input_key=self.input_key,
                output_path=self.output_path, output_key=self.output_key,
                assignment_path=os.path.join(self.tmp_folder, ORPHANS_NAME),
                identifier="orphans",
                table_default="identity",
            )
        ]


class ConnectedComponentsWorkflow(WorkflowBase):
    """Connected components over the segment graph
    (reference ConnectedComponentsWorkflow, postprocess_workflow.py:292-336):
    graph → union-find over (optionally cost-thresholded) edges → write.

    ``threshold`` restricts the merge to edges whose COST exceeds it, which
    requires edge costs in this ``tmp_folder``'s scratch store — run the
    problem pipeline (features → probs_to_costs) there first, like
    ``SizeFilterAndGraphWatershedWorkflow``.  ``threshold=None`` (default)
    needs only the graph."""

    task_name = "connected_components_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path: str = None, input_key: str = None,
                 output_path: str = None, output_key: str = None,
                 threshold: Optional[float] = None):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.threshold = threshold

    def requires(self):
        graph = GraphWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs, self.target,
            input_path=self.input_path, input_key=self.input_key,
        )
        cc = GraphConnectedComponentsTask(
            self.tmp_folder, self.config_dir, dependencies=[graph],
            threshold=self.threshold,
        )
        return [
            WriteTask(
                self.tmp_folder, self.config_dir, self.max_jobs,
                dependencies=[cc],
                input_path=self.input_path, input_key=self.input_key,
                output_path=self.output_path, output_key=self.output_key,
                assignment_path=os.path.join(self.tmp_folder, GRAPH_CC_NAME),
                identifier="graph_cc",
                table_default="identity",
            )
        ]


class SizeFilterAndGraphWatershedWorkflow(WorkflowBase):
    """Size filter where discarded fragments re-attach to their
    strongest-connected kept neighbor by edge-weighted graph watershed
    (reference SizeFilterAndGraphWatershedWorkflow,
    postprocess_workflow.py:339-412).

    Must run in the ``tmp_folder`` of a completed problem pipeline (graph +
    edge costs in the scratch store — the reference's ``problem_path``).
    """

    task_name = "size_filter_graph_watershed_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path: str = None, input_key: str = None,
                 output_path: str = None, output_key: str = None,
                 min_size: int = 0, max_size: Optional[int] = None,
                 relabel: bool = False):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.min_size = min_size
        self.max_size = max_size
        self.relabel = relabel

    def requires(self):
        morpho = MorphologyWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs, self.target,
            input_path=self.input_path, input_key=self.input_key,
        )
        size_filter = SizeFilterTask(
            self.tmp_folder, self.config_dir, dependencies=[morpho],
            min_size=self.min_size, max_size=self.max_size, relabel=False,
        )
        graph_ws = GraphWatershedAssignmentsTask(
            self.tmp_folder, self.config_dir, dependencies=[size_filter],
            filter_path=os.path.join(self.tmp_folder, SIZE_FILTER_DISCARD_NAME),
        )
        apply_key = (
            self.output_key + "_unrelabeled" if self.relabel else self.output_key
        )
        write = WriteTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[graph_ws],
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=apply_key,
            assignment_path=os.path.join(self.tmp_folder, GRAPH_WS_NAME),
            identifier="graph_ws_filter",
            table_default="identity",
        )
        if not self.relabel:
            return [write]
        return [
            RelabelWorkflow(
                self.tmp_folder, self.config_dir, self.max_jobs, self.target,
                input_path=self.output_path, input_key=apply_key,
                output_path=self.output_path, output_key=self.output_key,
                dependencies=[write],
            )
        ]
