"""Evaluation workflow (reference evaluation_workflow.py:10-47):
per-block overlaps between segmentation and ground truth → merged contingency
→ Rand/VoI measures JSON."""

from __future__ import annotations

from typing import Optional

from ..runtime.workflow import WorkflowBase
from ..tasks.evaluation import MeasuresTask
from ..tasks.node_labels import BlockNodeLabelsTask, MergeNodeLabelsTask


class EvaluationWorkflow(WorkflowBase):
    task_name = "evaluation_workflow"

    def __init__(
        self,
        tmp_folder,
        config_dir=None,
        max_jobs=None,
        target=None,
        seg_path: str = None,
        seg_key: str = None,
        gt_path: str = None,
        gt_key: str = None,
        dependencies=(),
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.seg_path = seg_path
        self.seg_key = seg_key
        self.gt_path = gt_path
        self.gt_key = gt_key

    def requires(self):
        overlaps = BlockNodeLabelsTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=list(self.dependencies),
            input_path=self.seg_path, input_key=self.seg_key,
            labels_path=self.gt_path, labels_key=self.gt_key,
        )
        merge = MergeNodeLabelsTask(
            self.tmp_folder, self.config_dir,
            dependencies=[overlaps],
            input_path=self.seg_path, input_key=self.seg_key,
        )
        measures = MeasuresTask(
            self.tmp_folder, self.config_dir, dependencies=[merge]
        )
        return [measures]
