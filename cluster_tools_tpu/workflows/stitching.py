"""Stitching workflows for block-wise segmentations
(reference workflows.py:360 SimpleStitchingWorkflow, :388
MulticutStitchingWorkflow, stitching/stitching_workflows.py)."""

from __future__ import annotations

import os

from ..runtime.workflow import WorkflowBase
from ..tasks.stitching import (
    SIMPLE_STITCH_NAME,
    STITCH_MC_NAME,
    SimpleStitchAssignmentsTask,
    SimpleStitchEdgesTask,
    StitchingMulticutTask,
)
from ..tasks.write import WriteTask
from .multicut import EdgeFeaturesWorkflow, GraphWorkflow


class _StitchingBase(WorkflowBase):
    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path=None, input_key=None, labels_path=None,
                 labels_key=None, output_path=None, output_key=None,
                 dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        # input = boundary/affinity map (multicut variant); labels = the
        # block-wise segmentation to stitch
        self.input_path = input_path
        self.input_key = input_key
        self.labels_path = labels_path
        self.labels_key = labels_key
        self.output_path = output_path
        self.output_key = output_key

    def _graph(self):
        return GraphWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs, self.target,
            input_path=self.labels_path, input_key=self.labels_key,
            dependencies=list(self.dependencies),
        )

    def _edges(self, dep):
        return SimpleStitchEdgesTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[dep],
            input_path=self.labels_path, input_key=self.labels_key,
        )

    def _write(self, dep, assignment_name):
        return WriteTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[dep],
            input_path=self.labels_path, input_key=self.labels_key,
            output_path=self.output_path, output_key=self.output_key,
            assignment_path=os.path.join(self.tmp_folder, assignment_name),
            identifier="stitching",
        )


class SimpleStitchingWorkflow(_StitchingBase):
    """Merge every boundary-crossing edge (reference workflows.py:360)."""

    task_name = "simple_stitching_workflow"

    def __init__(self, *args, edge_size_threshold: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self.edge_size_threshold = edge_size_threshold

    def requires(self):
        graph = self._graph()
        edges = self._edges(graph)
        assignments = SimpleStitchAssignmentsTask(
            self.tmp_folder, self.config_dir,
            dependencies=[edges],
            input_path=self.labels_path, input_key=self.labels_key,
            edge_size_threshold=self.edge_size_threshold,
        )
        write = self._write(assignments, SIMPLE_STITCH_NAME)
        return [write]


class MulticutStitchingWorkflow(_StitchingBase):
    """Two-beta multicut over boundary vs inner edges
    (reference workflows.py:388)."""

    task_name = "multicut_stitching_workflow"

    def requires(self):
        graph = self._graph()
        feats = EdgeFeaturesWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs, self.target,
            input_path=self.input_path, input_key=self.input_key,
            labels_path=self.labels_path, labels_key=self.labels_key,
            dependencies=[graph],
        )
        edges = self._edges(feats)
        mc = StitchingMulticutTask(
            self.tmp_folder, self.config_dir,
            dependencies=[edges],
            input_path=self.labels_path, input_key=self.labels_key,
        )
        write = self._write(mc, STITCH_MC_NAME)
        return [write]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["stitching_multicut"] = StitchingMulticutTask.default_task_config()
        return conf
