"""Skeleton / mesh / distance workflows, all gated on the morphology table
(reference skeletons/skeleton_workflow.py:10, distances/distance_workflow.py:35,
meshes are task-only in the reference but get the same morphology chaining)."""

from __future__ import annotations

from ..runtime.workflow import WorkflowBase
from ..tasks.distances import MergeObjectDistancesTask, ObjectDistancesTask
from ..tasks.meshes import ComputeMeshesTask
from ..tasks.morphology import BlockMorphologyTask, MergeMorphologyTask
from ..tasks.skeletons import SkeletonEvaluationTask, SkeletonizeTask


class _MorphologyGated(WorkflowBase):
    """Shared head: compute the morphology table of the segmentation."""

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path=None, input_key=None, **kwargs):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        for k, v in kwargs.items():
            setattr(self, k, v)

    def _morphology_tasks(self):
        block = BlockMorphologyTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            input_path=self.input_path, input_key=self.input_key,
        )
        merge = MergeMorphologyTask(
            self.tmp_folder, self.config_dir, dependencies=[block],
            input_path=self.input_path, input_key=self.input_key,
        )
        return merge


class SkeletonWorkflow(_MorphologyGated):
    task_name = "skeleton_workflow"

    def requires(self):
        morpho = self._morphology_tasks()
        skel = SkeletonizeTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[morpho],
            input_path=self.input_path, input_key=self.input_key,
        )
        return [skel]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["skeletonize"] = SkeletonizeTask.default_task_config()
        return conf


class SkeletonEvaluationWorkflow(_MorphologyGated):
    """Skeletonize + evaluate against a segmentation
    (reference skeleton_workflow.py + skeleton_evaluation.py chain)."""

    task_name = "skeleton_evaluation_workflow"

    def __init__(self, *args, seg_path=None, seg_key=None, **kwargs):
        super().__init__(*args, seg_path=seg_path, seg_key=seg_key, **kwargs)

    def requires(self):
        morpho = self._morphology_tasks()
        skel = SkeletonizeTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[morpho],
            input_path=self.input_path, input_key=self.input_key,
        )
        ev = SkeletonEvaluationTask(
            self.tmp_folder, self.config_dir, dependencies=[skel],
            seg_path=self.seg_path, seg_key=self.seg_key,
        )
        return [ev]


class DistanceWorkflow(_MorphologyGated):
    task_name = "distance_workflow"

    def requires(self):
        morpho = self._morphology_tasks()
        dist = ObjectDistancesTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[morpho],
            input_path=self.input_path, input_key=self.input_key,
        )
        merge = MergeObjectDistancesTask(
            self.tmp_folder, self.config_dir, dependencies=[dist],
        )
        return [merge]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["object_distances"] = ObjectDistancesTask.default_task_config()
        return conf


class MeshWorkflow(_MorphologyGated):
    task_name = "mesh_workflow"

    def __init__(self, *args, output_dir=None, **kwargs):
        super().__init__(*args, output_dir=output_dir, **kwargs)

    def requires(self):
        morpho = self._morphology_tasks()
        meshes = ComputeMeshesTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[morpho],
            input_path=self.input_path, input_key=self.input_key,
            output_dir=self.output_dir,
        )
        return [meshes]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["compute_meshes"] = ComputeMeshesTask.default_task_config()
        return conf
