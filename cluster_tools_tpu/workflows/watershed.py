"""Watershed workflow (reference watershed/watershed_workflow.py:10).

Single-pass (blockwise DT-WS with block-id offsets) or checkerboard two-pass
(boundary-consistent labels), optionally followed by relabeling to consecutive
ids."""

from __future__ import annotations

from typing import Optional

from ..runtime.workflow import WorkflowBase
from ..tasks.watershed import (
    AgglomerateTask,
    ShardedWatershedTask,
    TwoPassWatershedTask,
    WatershedTask,
)


class WatershedWorkflow(WorkflowBase):
    task_name = "watershed_workflow"

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        target: Optional[str] = None,
        input_path: str = None,
        input_key: str = None,
        output_path: str = None,
        output_key: str = None,
        mask_path: str = None,
        mask_key: str = None,
        two_pass: bool = False,
        agglomeration: bool = False,
        sharded: bool = False,
        dependencies=(),
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.mask_path = mask_path
        self.mask_key = mask_key
        self.two_pass = two_pass
        self.agglomeration = agglomeration
        self.sharded = sharded

    def requires(self):
        kwargs = dict(
            input_path=self.input_path,
            input_key=self.input_key,
            output_path=self.output_path,
            output_key=self.output_key,
            mask_path=self.mask_path,
            mask_key=self.mask_key,
        )
        if self.sharded:
            # whole-volume collective DT-watershed over the device mesh: no
            # block offsets, no halos, one globally-consistent fragmentation
            # (volume must fit the mesh's aggregate HBM; 3d mode, no mask)
            if self.mask_path:
                raise ValueError(
                    "sharded watershed does not support masks yet — use the "
                    "block pipeline"
                )
            if self.two_pass or self.agglomeration:
                raise ValueError(
                    "sharded watershed is already globally consistent — "
                    "two_pass/agglomeration do not apply"
                )
            sharded_kwargs = dict(kwargs)
            sharded_kwargs.pop("mask_path")
            sharded_kwargs.pop("mask_key")
            return [
                ShardedWatershedTask(
                    self.tmp_folder,
                    self.config_dir,
                    self.max_jobs,
                    dependencies=list(self.dependencies),
                    **sharded_kwargs,
                )
            ]
        if self.two_pass:
            pass1 = TwoPassWatershedTask(
                self.tmp_folder,
                self.config_dir,
                self.max_jobs,
                dependencies=list(self.dependencies),
                pass_id=0,
                **kwargs,
            )
            pass2 = TwoPassWatershedTask(
                self.tmp_folder,
                self.config_dir,
                self.max_jobs,
                dependencies=[pass1],
                pass_id=1,
                **kwargs,
            )
            return [pass2]
        if self.agglomeration:
            # merge oversegmented fragments per block before any global step
            # (reference watershed_workflow.py agglomeration option).  The
            # fragments live under a separate key so the agglomerate step is
            # idempotent under retry/resume (an in-place read-modify-write
            # would double-agglomerate re-run blocks).
            frag_key = self.output_key + "_frag"
            ws = WatershedTask(
                self.tmp_folder,
                self.config_dir,
                self.max_jobs,
                dependencies=list(self.dependencies),
                **{**kwargs, "output_key": frag_key},
            )
            agglo = AgglomerateTask(
                self.tmp_folder,
                self.config_dir,
                self.max_jobs,
                dependencies=[ws],
                input_path=self.input_path,
                input_key=self.input_key,
                labels_path=self.output_path,
                labels_key=frag_key,
                output_path=self.output_path,
                output_key=self.output_key,
            )
            return [agglo]
        ws = WatershedTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            dependencies=list(self.dependencies),
            **kwargs,
        )
        return [ws]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["watershed"] = WatershedTask.default_task_config()
        conf["two_pass_watershed"] = TwoPassWatershedTask.default_task_config()
        conf["agglomerate"] = AgglomerateTask.default_task_config()
        conf["sharded_watershed"] = ShardedWatershedTask.default_task_config()
        return conf
