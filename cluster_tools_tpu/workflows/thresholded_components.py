"""Distributed thresholded connected components workflow
(reference thresholded_components_workflow.py:17-105)."""

from __future__ import annotations

import os
from typing import Optional, Sequence


from ..runtime.workflow import WorkflowBase
from ..tasks.thresholded_components import (
    ASSIGNMENTS_NAME,
    OFFSETS_NAME,
    BlockComponentsTask,
    BlockFacesTask,
    MergeAssignmentsTask,
    MergeOffsetsTask,
    ShardedComponentsTask,
)
from ..tasks.write import WriteTask




class ThresholdedComponentsWorkflow(WorkflowBase):
    """threshold → block CC → offsets → faces → union-find → write.

    ``sharded=True`` replaces the 5-task block pipeline with ONE collective
    task (``ShardedComponentsTask``): the volume z-shards over the device
    mesh and the cross-block merge rides ICI (ppermute + psum) instead of
    the scratch store — for volumes that fit the mesh's aggregate HBM.
    """

    task_name = "thresholded_components_workflow"

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        target: Optional[str] = None,
        input_path: str = None,
        input_key: str = None,
        output_path: str = None,
        output_key: str = None,
        assignment_path: Optional[str] = None,
        mask_path: str = None,
        mask_key: str = None,
        sharded: bool = False,
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.mask_path = mask_path
        self.mask_key = mask_key
        self.sharded = sharded

    def requires(self):
        if self.sharded:
            return [
                ShardedComponentsTask(
                    self.tmp_folder,
                    self.config_dir,
                    self.max_jobs,
                    input_path=self.input_path,
                    input_key=self.input_key,
                    output_path=self.output_path,
                    output_key=self.output_key,
                    mask_path=self.mask_path,
                    mask_key=self.mask_key,
                )
            ]
        blocks_key = self.output_key + "_blocks"
        components = BlockComponentsTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            input_path=self.input_path,
            input_key=self.input_key,
            output_path=self.output_path,
            output_key=blocks_key,
            mask_path=self.mask_path,
            mask_key=self.mask_key,
        )
        offsets = MergeOffsetsTask(
            self.tmp_folder,
            self.config_dir,
            dependencies=[components],
            input_path=self.input_path,
            input_key=self.input_key,
        )
        faces = BlockFacesTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            dependencies=[offsets],
            input_path=self.output_path,
            input_key=blocks_key,
        )
        assignments = MergeAssignmentsTask(
            self.tmp_folder,
            self.config_dir,
            dependencies=[faces],
            input_path=self.input_path,
            input_key=self.input_key,
        )
        write = WriteTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            dependencies=[assignments],
            input_path=self.output_path,
            input_key=blocks_key,
            output_path=self.output_path,
            output_key=self.output_key,
            assignment_path=os.path.join(self.tmp_folder, ASSIGNMENTS_NAME),
            offsets_path=os.path.join(self.tmp_folder, OFFSETS_NAME),
            identifier="thresholded_components",
        )
        return [write]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["block_components"] = BlockComponentsTask.default_task_config()
        conf["sharded_components"] = ShardedComponentsTask.default_task_config()
        conf["write"] = WriteTask.default_task_config()
        return conf


class ThresholdAndWatershedWorkflow(WorkflowBase):
    """Thresholded components used as global seeds for a watershed over the
    full boundary map (reference thresholded_components_workflow.py:107-137)."""

    task_name = "threshold_and_watershed_workflow"

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        target: Optional[str] = None,
        input_path: str = None,
        input_key: str = None,
        output_path: str = None,
        output_key: str = None,
        mask_path: str = None,
        mask_key: str = None,
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.mask_path = mask_path
        self.mask_key = mask_key

    def requires(self):
        from ..tasks.watershed import WatershedFromSeedsTask

        seeds_key = self.output_key + "_seeds"
        components = ThresholdedComponentsWorkflow(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            self.target,
            input_path=self.input_path,
            input_key=self.input_key,
            output_path=self.output_path,
            output_key=seeds_key,
            mask_path=self.mask_path,
            mask_key=self.mask_key,
        )
        ws = WatershedFromSeedsTask(
            self.tmp_folder,
            self.config_dir,
            self.max_jobs,
            dependencies=[components],
            input_path=self.input_path,
            input_key=self.input_key,
            seeds_path=self.output_path,
            seeds_key=seeds_key,
            output_path=self.output_path,
            output_key=self.output_key,
            mask_path=self.mask_path,
            mask_key=self.mask_key,
        )
        return [ws]

    @classmethod
    def get_config(cls):
        from ..tasks.watershed import WatershedFromSeedsTask

        conf = ThresholdedComponentsWorkflow.get_config()
        conf["watershed_from_seeds"] = WatershedFromSeedsTask.default_task_config()
        return conf
