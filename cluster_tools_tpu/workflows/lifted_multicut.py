"""Lifted multicut workflows.

Reference lifted_features/lifted_feature_workflow.py:80 and
lifted_multicut/lifted_multicut_workflow.py:11, composed into
LiftedMulticutSegmentationWorkflow (reference workflows.py:235-324):
watershed → graph → features → costs → node labels → lifted neighborhood →
lifted costs → [solve_lifted_subproblems(s) → reduce_lifted_problem(s)]×scales
→ solve_lifted_global → write.
"""

from __future__ import annotations

import os
from typing import Optional

from ..runtime.workflow import WorkflowBase
from ..tasks.costs import ProbsToCostsTask
from ..tasks.lifted_features import (
    ClearLiftedEdgesFromLabelsTask,
    LiftedCostsFromNodeLabelsTask,
    SparseLiftedNeighborhoodTask,
)
from ..tasks.lifted_multicut import (
    LIFTED_ASSIGNMENTS_NAME,
    ReduceLiftedProblemTask,
    SolveLiftedGlobalTask,
    SolveLiftedSubproblemsTask,
)
from ..tasks.node_labels import BlockNodeLabelsTask, MergeNodeLabelsTask
from ..tasks.watershed import WatershedTask
from ..tasks.write import WriteTask
from .multicut import EdgeFeaturesWorkflow, GraphWorkflow


class LiftedFeaturesFromNodeLabelsWorkflow(WorkflowBase):
    """Node-label votes over a prior volume → sparse lifted neighborhood →
    ± lifted costs (reference lifted_feature_workflow.py:80).

    ``ws_path/ws_key`` is the fragment volume (graph nodes), ``labels_path/key``
    the semantic prior volume.
    """

    task_name = "lifted_features_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 ws_path=None, ws_key=None, labels_path=None, labels_key=None,
                 prefix: str = "lifted", ignore_label=None,
                 clear_labels=None, dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.labels_path = labels_path
        self.labels_key = labels_key
        self.prefix = prefix
        self.ignore_label = ignore_label
        self.clear_labels = clear_labels

    def requires(self):
        block_labels = BlockNodeLabelsTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=list(self.dependencies),
            input_path=self.ws_path, input_key=self.ws_key,
            labels_path=self.labels_path, labels_key=self.labels_key,
            ignore_label=self.ignore_label,
        )
        merge_labels = MergeNodeLabelsTask(
            self.tmp_folder, self.config_dir, dependencies=[block_labels],
            input_path=self.ws_path, input_key=self.ws_key,
        )
        nh = SparseLiftedNeighborhoodTask(
            self.tmp_folder, self.config_dir, dependencies=[merge_labels],
            prefix=self.prefix,
        )
        costs = LiftedCostsFromNodeLabelsTask(
            self.tmp_folder, self.config_dir, dependencies=[nh],
            prefix=self.prefix,
        )
        if self.clear_labels:
            clear = ClearLiftedEdgesFromLabelsTask(
                self.tmp_folder, self.config_dir, dependencies=[costs],
                prefix=self.prefix, clear_labels=self.clear_labels,
            )
            return [clear]
        return [costs]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["sparse_lifted_neighborhood"] = (
            SparseLiftedNeighborhoodTask.default_task_config()
        )
        conf["costs_from_node_labels"] = (
            LiftedCostsFromNodeLabelsTask.default_task_config()
        )
        return conf


class LiftedMulticutWorkflow(WorkflowBase):
    """Hierarchical lifted multicut solve
    (reference lifted_multicut_workflow.py:11)."""

    task_name = "lifted_multicut_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path=None, input_key=None, n_scales: int = 1,
                 prefix: str = "lifted", dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.n_scales = n_scales
        self.prefix = prefix

    def requires(self):
        dep = list(self.dependencies)
        for scale in range(self.n_scales):
            solve = SolveLiftedSubproblemsTask(
                self.tmp_folder, self.config_dir, self.max_jobs,
                dependencies=dep, scale=scale, prefix=self.prefix,
                input_path=self.input_path, input_key=self.input_key,
            )
            reduce_ = ReduceLiftedProblemTask(
                self.tmp_folder, self.config_dir,
                dependencies=[solve], scale=scale, prefix=self.prefix,
                input_path=self.input_path, input_key=self.input_key,
            )
            dep = [reduce_]
        solve_global = SolveLiftedGlobalTask(
            self.tmp_folder, self.config_dir, dependencies=dep,
            scale=self.n_scales, prefix=self.prefix,
        )
        return [solve_global]


class LiftedMulticutSegmentationWorkflow(WorkflowBase):
    """watershed → problem → lifted features → lifted multicut → write
    (reference workflows.py:235-324)."""

    task_name = "lifted_multicut_segmentation_workflow"

    def __init__(
        self,
        tmp_folder,
        config_dir=None,
        max_jobs=None,
        target=None,
        input_path: str = None,       # boundary / affinity map
        input_key: str = None,
        ws_path: str = None,
        ws_key: str = None,
        labels_path: str = None,      # semantic prior volume for lifted edges
        labels_key: str = None,
        output_path: str = None,
        output_key: str = None,
        mask_path: str = None,
        mask_key: str = None,
        n_scales: int = 1,
        skip_ws: bool = False,
        clear_labels=None,
        dependencies=(),
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.labels_path = labels_path
        self.labels_key = labels_key
        self.output_path = output_path
        self.output_key = output_key
        self.mask_path = mask_path
        self.mask_key = mask_key
        self.n_scales = n_scales
        self.skip_ws = skip_ws
        self.clear_labels = clear_labels

    def requires(self):
        dep = list(self.dependencies)
        if not self.skip_ws:
            ws = WatershedTask(
                self.tmp_folder, self.config_dir, self.max_jobs,
                dependencies=dep,
                input_path=self.input_path, input_key=self.input_key,
                output_path=self.ws_path, output_key=self.ws_key,
                mask_path=self.mask_path, mask_key=self.mask_key,
            )
            dep = [ws]
        graph = GraphWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs,
            input_path=self.ws_path, input_key=self.ws_key,
            dependencies=dep,
        )
        feats = EdgeFeaturesWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs,
            input_path=self.input_path, input_key=self.input_key,
            labels_path=self.ws_path, labels_key=self.ws_key,
            dependencies=[graph],
        )
        costs = ProbsToCostsTask(
            self.tmp_folder, self.config_dir, dependencies=[feats]
        )
        lifted = LiftedFeaturesFromNodeLabelsWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs,
            ws_path=self.ws_path, ws_key=self.ws_key,
            labels_path=self.labels_path, labels_key=self.labels_key,
            clear_labels=self.clear_labels,
            dependencies=[costs],
        )
        lmc = LiftedMulticutWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs,
            input_path=self.ws_path, input_key=self.ws_key,
            n_scales=self.n_scales, dependencies=[lifted],
        )
        write = WriteTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[lmc],
            input_path=self.ws_path, input_key=self.ws_key,
            output_path=self.output_path, output_key=self.output_key,
            assignment_path=os.path.join(self.tmp_folder, LIFTED_ASSIGNMENTS_NAME),
            identifier="lifted_multicut",
        )
        return [write]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["watershed"] = WatershedTask.default_task_config()
        conf["probs_to_costs"] = ProbsToCostsTask.default_task_config()
        conf.update(LiftedFeaturesFromNodeLabelsWorkflow.get_config())
        return conf
