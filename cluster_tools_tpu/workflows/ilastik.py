"""ilastik workflows: block-parallel headless prediction and the carving
project export (reference ilastik/ilastik_workflow.py:16,73)."""

from __future__ import annotations

from typing import Sequence

from ..tasks.ilastik import (
    IlastikPredictionTask,
    MergePredictionsTask,
    WriteCarvingTask,
)
from ..runtime.workflow import WorkflowBase
from .multicut import EdgeFeaturesWorkflow, GraphWorkflow


class IlastikPredictionWorkflow(WorkflowBase):
    """prediction → merge (reference ilastik_workflow.py:16-70)."""

    task_name = "ilastik_prediction_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path=None, input_key=None, output_path=None,
                 output_key=None, ilastik_folder=None, ilastik_project=None,
                 halo: Sequence[int] = (0, 0, 0), n_channels: int = 1,
                 dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.ilastik_folder = ilastik_folder
        self.ilastik_project = ilastik_project
        self.halo = list(halo)
        self.n_channels = int(n_channels)

    def requires(self):
        predict = IlastikPredictionTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=list(self.dependencies),
            input_path=self.input_path, input_key=self.input_key,
            ilastik_folder=self.ilastik_folder,
            ilastik_project=self.ilastik_project, halo=self.halo,
        )
        merge = MergePredictionsTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[predict],
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=self.output_key,
            tmp_prefix=predict.output_prefix, halo=self.halo,
            n_channels=self.n_channels,
        )
        return [merge]


class IlastikCarvingWorkflow(WorkflowBase):
    """watershed RAG + features → carving .ilp
    (reference ilastik_workflow.py:73-142)."""

    task_name = "ilastik_carving_workflow"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None, target=None,
                 input_path=None, input_key=None, watershed_path=None,
                 watershed_key=None, output_path=None, copy_inputs=False,
                 dependencies=()):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.watershed_path = watershed_path
        self.watershed_key = watershed_key
        self.output_path = output_path
        self.copy_inputs = copy_inputs

    def requires(self):
        graph = GraphWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs, self.target,
            input_path=self.watershed_path, input_key=self.watershed_key,
            dependencies=list(self.dependencies),
        )
        feats = EdgeFeaturesWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs, self.target,
            input_path=self.input_path, input_key=self.input_key,
            labels_path=self.watershed_path, labels_key=self.watershed_key,
            dependencies=[graph],
        )
        carving = WriteCarvingTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[feats],
            output_path=self.output_path,
            raw_path=self.input_path, raw_key=self.input_key,
            copy_inputs=self.copy_inputs,
        )
        return [carving]
