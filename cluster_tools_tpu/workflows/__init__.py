from .evaluation import EvaluationWorkflow
from .morphology import MorphologyWorkflow
from .multicut import (
    EdgeFeaturesWorkflow,
    GraphWorkflow,
    MulticutSegmentationWorkflow,
    MulticutWorkflow,
)
from .mws import MwsWorkflow
from .relabel import RelabelWorkflow
from .thresholded_components import ThresholdedComponentsWorkflow
from .watershed import WatershedWorkflow

__all__ = [
    "EvaluationWorkflow",
    "EdgeFeaturesWorkflow",
    "GraphWorkflow",
    "MorphologyWorkflow",
    "MulticutSegmentationWorkflow",
    "MulticutWorkflow",
    "MwsWorkflow",
    "RelabelWorkflow",
    "ThresholdedComponentsWorkflow",
    "WatershedWorkflow",
]
