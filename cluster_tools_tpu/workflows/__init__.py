from .agglomerative_clustering import AgglomerativeClusteringWorkflow
from .downscaling import DownscalingWorkflow, PainteraToBdvWorkflow
from .learning import LearningWorkflow
from .skeletons import (
    DistanceWorkflow,
    MeshWorkflow,
    SkeletonEvaluationWorkflow,
    SkeletonWorkflow,
)
from .paintera import (
    LabelMultisetWorkflow,
    PainteraConversionWorkflow,
)
from .bigcat import BigcatWorkflow
from .debugging import CheckComponentsWorkflow, CheckSubGraphsWorkflow
from .evaluation import EvaluationWorkflow
from .lifted_multicut import (
    LiftedFeaturesFromNodeLabelsWorkflow,
    LiftedMulticutSegmentationWorkflow,
    LiftedMulticutWorkflow,
)
from .morphology import MorphologyWorkflow, RegionCentersWorkflow
from .multicut import (
    EdgeFeaturesWorkflow,
    GraphWorkflow,
    MulticutSegmentationWorkflow,
    MulticutWorkflow,
    ProblemWorkflow,
    ReducedSolutionWorkflow,
    SubSolutionsWorkflow,
)
from .mws import MwsWorkflow, TwoPassMwsWorkflow
from .postprocessing import (
    ConnectedComponentsWorkflow,
    FilterByThresholdWorkflow,
    FilterLabelsWorkflow,
    FilterOrphansWorkflow,
    SizeFilterAndGraphWatershedWorkflow,
    SizeFilterWorkflow,
)
from .events import EventBuildingWorkflow
from .hier import HierarchyWorkflow, ResegmentWorkflow
from .stitching import MulticutStitchingWorkflow, SimpleStitchingWorkflow
from .streaming import StreamingSegmentationWorkflow
from .ilastik import IlastikCarvingWorkflow, IlastikPredictionWorkflow
from .relabel import RelabelWorkflow, UniqueWorkflow
from .transformations import LinearTransformationWorkflow
from .thresholded_components import (
    ThresholdAndWatershedWorkflow,
    ThresholdedComponentsWorkflow,
)
from .watershed import WatershedWorkflow

__all__ = [
    "AgglomerativeClusteringWorkflow",
    "DownscalingWorkflow",
    "PainteraToBdvWorkflow",
    "LearningWorkflow",
    "DistanceWorkflow",
    "MeshWorkflow",
    "SkeletonEvaluationWorkflow",
    "SkeletonWorkflow",
    "LabelMultisetWorkflow",
    "PainteraConversionWorkflow",
    "BigcatWorkflow",
    "CheckComponentsWorkflow",
    "CheckSubGraphsWorkflow",
    "EvaluationWorkflow",
    "EdgeFeaturesWorkflow",
    "GraphWorkflow",
    "LiftedFeaturesFromNodeLabelsWorkflow",
    "LiftedMulticutSegmentationWorkflow",
    "LiftedMulticutWorkflow",
    "MorphologyWorkflow",
    "RegionCentersWorkflow",
    "IlastikCarvingWorkflow",
    "IlastikPredictionWorkflow",
    "MulticutSegmentationWorkflow",
    "MulticutWorkflow",
    "ProblemWorkflow",
    "ReducedSolutionWorkflow",
    "SubSolutionsWorkflow",
    "MwsWorkflow",
    "ConnectedComponentsWorkflow",
    "FilterByThresholdWorkflow",
    "FilterLabelsWorkflow",
    "FilterOrphansWorkflow",
    "SizeFilterAndGraphWatershedWorkflow",
    "SizeFilterWorkflow",
    "TwoPassMwsWorkflow",
    "EventBuildingWorkflow",
    "HierarchyWorkflow",
    "MulticutStitchingWorkflow",
    "ResegmentWorkflow",
    "SimpleStitchingWorkflow",
    "StreamingSegmentationWorkflow",
    "LinearTransformationWorkflow",
    "RelabelWorkflow",
    "UniqueWorkflow",
    "ThresholdAndWatershedWorkflow",
    "ThresholdedComponentsWorkflow",
    "WatershedWorkflow",
]
