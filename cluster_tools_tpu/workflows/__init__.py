from .multicut import (
    EdgeFeaturesWorkflow,
    GraphWorkflow,
    MulticutSegmentationWorkflow,
    MulticutWorkflow,
)
from .relabel import RelabelWorkflow
from .thresholded_components import ThresholdedComponentsWorkflow
from .watershed import WatershedWorkflow

__all__ = [
    "EdgeFeaturesWorkflow",
    "GraphWorkflow",
    "MulticutSegmentationWorkflow",
    "MulticutWorkflow",
    "RelabelWorkflow",
    "ThresholdedComponentsWorkflow",
    "WatershedWorkflow",
]
