from .thresholded_components import ThresholdedComponentsWorkflow
from .relabel import RelabelWorkflow

__all__ = [
    "ThresholdedComponentsWorkflow",
    "RelabelWorkflow",
]
