"""Random-forest learning workflow (reference learning/learning_workflow.py:13).

Per training dataset: RAG extraction → edge features → GT node overlap votes →
edge labels; then one RF trained over all datasets' (features, labels)."""

from __future__ import annotations

import os
from typing import Dict, Sequence, Tuple

from ..runtime.workflow import WorkflowBase
from ..tasks.learning import EdgeLabelsTask, LearnRFTask
from ..tasks.node_labels import BlockNodeLabelsTask, MergeNodeLabelsTask
from .multicut import EdgeFeaturesWorkflow, GraphWorkflow


class LearningWorkflow(WorkflowBase):
    task_name = "learning_workflow"

    def __init__(
        self,
        tmp_folder,
        config_dir=None,
        max_jobs=None,
        target=None,
        input_dict: Dict[str, Tuple[str, str]] = None,
        labels_dict: Dict[str, Tuple[str, str]] = None,
        groundtruth_dict: Dict[str, Tuple[str, str]] = None,
        output_path: str = None,
        ignore_label_gt: bool = False,
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target)
        self.input_dict = dict(input_dict or {})        # boundary maps
        self.labels_dict = dict(labels_dict or {})      # watershed labels
        self.groundtruth_dict = dict(groundtruth_dict or {})
        if not (
            self.input_dict.keys()
            == self.labels_dict.keys()
            == self.groundtruth_dict.keys()
        ):
            raise ValueError("input/labels/groundtruth keys must match")
        self.output_path = output_path
        self.ignore_label_gt = ignore_label_gt

    def requires(self):
        tasks = []
        folders = []
        for key, (input_path, input_key) in self.input_dict.items():
            labels_path, labels_key = self.labels_dict[key]
            gt_path, gt_key = self.groundtruth_dict[key]
            tmp_folder = os.path.join(self.tmp_folder, key)
            folders.append(tmp_folder)

            graph = GraphWorkflow(
                tmp_folder, self.config_dir, self.max_jobs, self.target,
                input_path=labels_path, input_key=labels_key,
            )
            feats = EdgeFeaturesWorkflow(
                tmp_folder, self.config_dir, self.max_jobs, self.target,
                input_path=input_path, input_key=input_key,
                labels_path=labels_path, labels_key=labels_key,
                dependencies=[graph],
            )
            overlaps = BlockNodeLabelsTask(
                tmp_folder, self.config_dir, self.max_jobs,
                dependencies=[graph],
                input_path=labels_path, input_key=labels_key,
                labels_path=gt_path, labels_key=gt_key,
            )
            merge_labels = MergeNodeLabelsTask(
                tmp_folder, self.config_dir,
                dependencies=[overlaps],
                input_path=labels_path, input_key=labels_key,
            )
            edge_labels = EdgeLabelsTask(
                tmp_folder, self.config_dir,
                dependencies=[feats, merge_labels],
                ignore_label_gt=self.ignore_label_gt,
            )
            tasks.append(edge_labels)
        learn = LearnRFTask(
            self.tmp_folder, self.config_dir,
            dependencies=tasks,
            tmp_folders=folders,
            output_path=self.output_path,
        )
        return [learn]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["learn_rf"] = LearnRFTask.default_task_config()
        conf["edge_labels"] = EdgeLabelsTask.default_task_config()
        return conf
