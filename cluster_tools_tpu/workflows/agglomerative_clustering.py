"""Agglomerative clustering segmentation workflow
(reference workflows.py:326-358, AgglomerativeClusteringWorkflow):
watershed → graph → edge features → global threshold clustering → write.
"""

from __future__ import annotations

import os
from typing import Optional

from ..runtime.workflow import WorkflowBase
from ..tasks.agglomerative_clustering import (
    AGGLO_ASSIGNMENTS_NAME,
    AgglomerativeClusteringTask,
)
from ..tasks.write import WriteTask
from .multicut import EdgeFeaturesWorkflow, GraphWorkflow


class AgglomerativeClusteringWorkflow(WorkflowBase):
    task_name = "agglomerative_clustering_workflow"

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        target: Optional[str] = None,
        input_path: str = None,       # boundary / affinity map
        input_key: str = None,
        ws_path: str = None,          # existing watershed / fragment volume
        ws_key: str = None,
        output_path: str = None,
        output_key: str = None,
        dependencies=(),
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, target, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.output_path = output_path
        self.output_key = output_key

    def requires(self):
        graph = GraphWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs,
            input_path=self.ws_path, input_key=self.ws_key,
            dependencies=list(self.dependencies),
        )
        feats = EdgeFeaturesWorkflow(
            self.tmp_folder, self.config_dir, self.max_jobs,
            input_path=self.input_path, input_key=self.input_key,
            labels_path=self.ws_path, labels_key=self.ws_key,
            dependencies=[graph],
        )
        cluster = AgglomerativeClusteringTask(
            self.tmp_folder, self.config_dir, dependencies=[feats]
        )
        write = WriteTask(
            self.tmp_folder, self.config_dir, self.max_jobs,
            dependencies=[cluster],
            input_path=self.ws_path, input_key=self.ws_key,
            output_path=self.output_path, output_key=self.output_key,
            assignment_path=os.path.join(self.tmp_folder, AGGLO_ASSIGNMENTS_NAME),
            identifier="agglomerative_clustering",
        )
        return [write]

    @classmethod
    def get_config(cls):
        conf = super().get_config()
        conf["agglomerative_clustering"] = (
            AgglomerativeClusteringTask.default_task_config()
        )
        return conf
