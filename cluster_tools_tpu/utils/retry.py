"""Shared exponential-backoff-with-jitter retry for transient store IO.

The chunked store is the control AND data plane of the whole runtime: a
transient filesystem error (NFS hiccup, overloaded object-store gateway, a
torn chunk mid-rewrite by a crashed peer) on one chunk read must not fail a
block — and a failed block must not fail the run (that is what block retry
is for).  This helper is the ONE sanctioned retry loop for such errors; lint
rule CTT009 flags ad-hoc ``time.sleep`` retry loops elsewhere.

Classification contract (utils/store.py):

  * transient ``OSError`` (EIO and friends)          → retryable;
  * decode of a torn/truncated chunk → ``CorruptChunk`` (an OSError
    subclass) → retryable — a concurrent writer's rewrite lands between
    attempts; if it never does, the error propagates, the *block* fails,
    and the task retry loop rewrites the chunk;
  * ``FileNotFoundError``                            → NOT retryable
    (unwritten chunks are normal: they mean fill_value, not failure).

Knobs (read per call so tests and chaos runs can tune them):

  ``CTT_IO_RETRIES``        max retry count after the first attempt (default 3)
  ``CTT_IO_BACKOFF_BASE_S`` first backoff delay (default 0.01)
  ``CTT_IO_BACKOFF_MAX_S``  backoff cap (default 1.0)

Each retry sleeps ``min(base * 2**attempt, max) * uniform(0.5, 1.0)`` —
full-jitter-style decorrelation so a fleet of workers hitting the same
flaky mount does not resynchronize into retry storms.  Every sleep
increments the caller's obs counter (default ``store.io_retries``) so
recovered transients stay visible in ``obs diff``.
"""

from __future__ import annotations

import os
import random
import time
from typing import Callable, Tuple, Type, TypeVar

from ..obs import metrics as obs_metrics

__all__ = ["backoff_delay_s", "io_retry", "retry_attempts"]

T = TypeVar("T")

_DEF_RETRIES = 3
_DEF_BASE_S = 0.01
_DEF_MAX_S = 1.0


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    try:
        val = float(raw) if raw is not None else default
    except (TypeError, ValueError):
        val = default  # malformed degrades to default, the CTT_* convention
    return max(val, 0.0)


def retry_attempts() -> int:
    return int(_env_float("CTT_IO_RETRIES", _DEF_RETRIES))


def backoff_delay_s(attempt: int) -> float:
    """The deterministic (un-jittered) backoff delay for retry number
    ``attempt`` (0-based) under the same env knobs as :func:`io_retry`.
    Exposed for retry policies that gate on *elapsed time* rather than
    sleeping — e.g. the serve fleet's between-generation backoff, where a
    job lease may not be reclaimed at generation g+1 until the previous
    generation's expiry is at least this much in the past (a poison job
    burns its retry budget at a decelerating rate instead of instantly)."""
    base_s = _env_float("CTT_IO_BACKOFF_BASE_S", _DEF_BASE_S)
    max_s = _env_float("CTT_IO_BACKOFF_MAX_S", _DEF_MAX_S)
    return min(base_s * (2.0 ** max(int(attempt), 0)), max_s)


def io_retry(
    fn: Callable[[], T],
    what: str = "store io",
    retryable: Tuple[Type[BaseException], ...] = (OSError,),
    non_retryable: Tuple[Type[BaseException], ...] = (FileNotFoundError,),
    counter: str = "store.io_retries",
) -> T:
    """Run ``fn`` with exponential-backoff retries on transient errors.

    The first attempt is a plain call — the success path adds one function
    call and zero allocations over calling ``fn()`` directly."""
    retries = retry_attempts()
    base_s = _env_float("CTT_IO_BACKOFF_BASE_S", _DEF_BASE_S)
    max_s = _env_float("CTT_IO_BACKOFF_MAX_S", _DEF_MAX_S)
    attempt = 0
    while True:
        try:
            return fn()
        except non_retryable:
            raise
        except retryable:
            if attempt >= retries:
                raise
            delay = min(base_s * (2.0 ** attempt), max_s)
            delay *= 0.5 + random.random() * 0.5
            obs_metrics.inc(counter)
            time.sleep(delay)
            attempt += 1
