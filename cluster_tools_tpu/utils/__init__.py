from .blocking import (
    Blocking,
    blocks_in_volume,
    block_to_bb,
    make_checkerboard_block_lists,
)
from . import store
from .store import file_reader

__all__ = [
    "Blocking",
    "blocks_in_volume",
    "block_to_bb",
    "make_checkerboard_block_lists",
    "store",
    "file_reader",
]
