"""Block decomposition geometry: blocks, halos, faces, checkerboard colorings.

Pure host-side Python/numpy.  This is the layer the reference delegates to
``nifty.tools.blocking`` (C++) — see SURVEY.md §1 L2 and
reference cluster_tools/utils/volume_utils.py:31-236.  Re-designed here as a small
self-contained module: the TPU build needs the *same geometry semantics* (identical
block ids and bounding boxes give identical label offsets and therefore comparable
segmentations), but none of the C++.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from itertools import product
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

Coord = Tuple[int, ...]


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


@dataclass(frozen=True)
class Block:
    """Half-open bounding box ``[begin, end)`` of one block."""

    begin: Coord
    end: Coord

    @property
    def shape(self) -> Coord:
        return tuple(e - b for b, e in zip(self.begin, self.end))

    @property
    def slicing(self) -> Tuple[slice, ...]:
        return tuple(slice(b, e) for b, e in zip(self.begin, self.end))


@dataclass(frozen=True)
class BlockWithHalo:
    """A block enlarged by a halo.

    ``outer``        — the halo'd box, clipped to the volume,
    ``inner``        — the original block,
    ``inner_local``  — ``inner`` in coordinates relative to ``outer``.

    Mirrors the outer/inner/innerLocal triple of the reference
    (cluster_tools/watershed/watershed.py:253-265).
    """

    outer: Block
    inner: Block
    inner_local: Block


class Blocking:
    """Regular grid decomposition of an nd volume into blocks.

    Blocks are indexed C-order over the grid; the last block along each axis may be
    smaller than ``block_shape``.
    """

    def __init__(self, shape: Sequence[int], block_shape: Sequence[int]):
        if len(shape) != len(block_shape):
            raise ValueError(f"rank mismatch: {shape} vs {block_shape}")
        if any(bs <= 0 for bs in block_shape):
            raise ValueError(f"invalid block shape {block_shape}")
        self.shape = tuple(int(s) for s in shape)
        self.block_shape = tuple(int(b) for b in block_shape)
        self.grid_shape = tuple(
            _ceil_div(s, b) for s, b in zip(self.shape, self.block_shape)
        )
        self.n_blocks = int(np.prod(self.grid_shape))

    @property
    def ndim(self) -> int:
        return len(self.shape)

    # -- id <-> grid position ------------------------------------------------

    def block_grid_position(self, block_id: int) -> Coord:
        if not 0 <= block_id < self.n_blocks:
            raise ValueError(f"block id {block_id} out of range [0, {self.n_blocks})")
        return tuple(int(c) for c in np.unravel_index(block_id, self.grid_shape))

    def block_id_from_grid_position(self, pos: Sequence[int]) -> int:
        return int(np.ravel_multi_index(tuple(pos), self.grid_shape))

    # -- geometry ------------------------------------------------------------

    def block(self, block_id: int) -> Block:
        pos = self.block_grid_position(block_id)
        begin = tuple(p * b for p, b in zip(pos, self.block_shape))
        end = tuple(
            min(p * b + b, s) for p, b, s in zip(pos, self.block_shape, self.shape)
        )
        return Block(begin, end)

    def block_with_halo(self, block_id: int, halo: Sequence[int]) -> BlockWithHalo:
        inner = self.block(block_id)
        outer_begin = tuple(max(b - h, 0) for b, h in zip(inner.begin, halo))
        outer_end = tuple(min(e + h, s) for e, h, s in zip(inner.end, halo, self.shape))
        outer = Block(outer_begin, outer_end)
        local = Block(
            tuple(ib - ob for ib, ob in zip(inner.begin, outer_begin)),
            tuple(ie - ob for ie, ob in zip(inner.end, outer_begin)),
        )
        return BlockWithHalo(outer, inner, local)

    def neighbor_id(self, block_id: int, axis: int, lower: bool) -> Optional[int]:
        """Grid neighbor along ``axis`` (``lower=True`` → towards index 0), or None."""
        pos = list(self.block_grid_position(block_id))
        pos[axis] += -1 if lower else 1
        if not 0 <= pos[axis] < self.grid_shape[axis]:
            return None
        return self.block_id_from_grid_position(pos)

    def blocks_overlapping_roi(
        self, roi_begin: Sequence[int], roi_end: Sequence[int]
    ) -> List[int]:
        lo = tuple(rb // bs for rb, bs in zip(roi_begin, self.block_shape))
        hi = tuple(
            min(_ceil_div(re, bs), gs)
            for re, bs, gs in zip(roi_end, self.block_shape, self.grid_shape)
        )
        ids = [
            self.block_id_from_grid_position(pos)
            for pos in product(*[range(l, h) for l, h in zip(lo, hi)])
        ]
        return sorted(ids)

    # -- faces ---------------------------------------------------------------

    def face(
        self, block_id: int, axis: int, halo: int = 1
    ) -> Optional[Tuple[int, Block]]:
        """The face between ``block_id`` and its *upper* neighbor along ``axis``.

        Returns ``(neighbor_id, face_bb)`` where ``face_bb`` spans
        ``halo`` voxels on each side of the block boundary (global coordinates),
        or None at the volume border.  Mirrors reference ``get_face``
        (volume_utils.py:187-216).
        """
        ngb = self.neighbor_id(block_id, axis, lower=False)
        if ngb is None:
            return None
        this = self.block(block_id)
        other = self.block(ngb)
        begin = list(max(tb, ob) for tb, ob in zip(this.begin, other.begin))
        end = list(min(te, oe) for te, oe in zip(this.end, other.end))
        boundary = this.end[axis]
        begin[axis] = boundary - halo
        end[axis] = boundary + halo
        return ngb, Block(tuple(begin), tuple(end))

    def iterate_faces(
        self, block_id: int, halo: int = 1
    ) -> Iterator[Tuple[int, int, Block]]:
        """Yield ``(axis, neighbor_id, face_bb)`` for all upper faces of a block."""
        for axis in range(self.ndim):
            got = self.face(block_id, axis, halo)
            if got is not None:
                ngb, bb = got
                yield axis, ngb, bb


# -- module level helpers (the reference's volume_utils surface) ----------------


def block_to_bb(block: Block) -> Tuple[slice, ...]:
    return block.slicing


def blocks_in_volume(
    shape: Sequence[int],
    block_shape: Sequence[int],
    roi_begin: Optional[Sequence[int]] = None,
    roi_end: Optional[Sequence[int]] = None,
    block_list_path: Optional[str] = None,
) -> List[int]:
    """Ids of blocks to process: full grid, restricted by ROI and/or a saved list.

    Reference: volume_utils.py:31-73.
    """
    if (roi_begin is None) != (roi_end is None):
        raise ValueError("either both or none of roi_begin / roi_end must be given")
    blocking = Blocking(shape, block_shape)
    if roi_begin is None:
        ids = list(range(blocking.n_blocks))
    else:
        roi_end = [s if re is None else re for re, s in zip(roi_end, shape)]
        ids = blocking.blocks_overlapping_roi(roi_begin, roi_end)
    if block_list_path is not None:
        # a missing list must not silently widen the block set to the full grid
        # (reference asserts existence too, volume_utils.py:39-40)
        if not os.path.exists(block_list_path):
            raise FileNotFoundError(f"block_list_path does not exist: {block_list_path}")
        with open(block_list_path) as f:
            saved = set(json.load(f))
        ids = [b for b in ids if b in saved]
    return ids


def make_checkerboard_block_lists(
    blocking: Blocking, block_ids: Optional[Sequence[int]] = None
) -> Tuple[List[int], List[int]]:
    """2-color the block grid so no two same-color blocks touch on a face.

    Pass-2 blocks of two-pass workflows read pass-1 neighbors' results; the coloring
    makes that dependency safe (reference volume_utils.py:108-171).
    """
    if block_ids is None:
        block_ids = range(blocking.n_blocks)
    white: List[int] = []
    black: List[int] = []
    for bid in block_ids:
        pos = blocking.block_grid_position(bid)
        (white if sum(pos) % 2 == 0 else black).append(bid)
    return white, black


def grid_neighbor_offsets(ndim: int) -> np.ndarray:
    """The 2*ndim face-neighbor offsets (6-connectivity in 3d)."""
    offs = []
    for axis in range(ndim):
        for sign in (-1, 1):
            o = [0] * ndim
            o[axis] = sign
            offs.append(o)
    return np.array(offs, dtype=np.int64)
