"""ctt-diskless: AWS Signature Version 4 request signing (stdlib only).

The object-store backend (``utils/store_backend.py``) signs every HTTP
request with SigV4 so the serve fleet can live on a real S3-compatible
store instead of the unauthenticated stub.  This module owns the pure
signing math and the credential resolution; the backend owns *when* to
sign (``s3://`` paths always, ``http(s)://`` origins when
``CTT_S3_SIGN`` opts in).

Credential resolution order (:func:`resolve_credentials`):

  1. environment — ``AWS_ACCESS_KEY_ID`` + ``AWS_SECRET_ACCESS_KEY``
     (+ optional ``AWS_SESSION_TOKEN``);
  2. shared credentials file — ``AWS_SHARED_CREDENTIALS_FILE`` (default
     ``~/.aws/credentials``), profile ``AWS_PROFILE`` (default
     ``default``), the standard ini layout.

Returns None when neither yields a key pair: the backend then sends
unsigned requests, and a signing store rejects them with 403 — which the
backend surfaces as a *retryable* auth error (``store.remote_auth_retries``),
never as a silent fallback.

Canonicalization notes (kept bit-compatible with the verifying twin in
``tests/objstub.py``, which re-derives the signature from the raw
request):

  * the canonical URI is the percent-encoded request path exactly as
    sent (the backend's ``_key`` quoting IS the encoding);
  * query params are normalized ``k=v`` pairs (a bare ``uploads`` flag
    becomes ``uploads=``) sorted lexicographically;
  * signed headers are ``host``, ``x-amz-content-sha256``,
    ``x-amz-date`` (+ ``x-amz-security-token`` with session creds).
"""

from __future__ import annotations

import configparser
import hashlib
import hmac
import os
import time
from dataclasses import dataclass
from typing import Dict, Optional

__all__ = [
    "Credentials",
    "SigV4Signer",
    "canonical_query",
    "default_region",
    "resolve_credentials",
]

_ALGORITHM = "AWS4-HMAC-SHA256"


@dataclass(frozen=True)
class Credentials:
    access_key: str
    secret_key: str
    session_token: Optional[str] = None


def default_region() -> str:
    return (
        os.environ.get("AWS_REGION")
        or os.environ.get("AWS_DEFAULT_REGION")
        or "us-east-1"
    )


def resolve_credentials() -> Optional[Credentials]:
    """Env first, then the shared credentials file; None when absent."""
    access = os.environ.get("AWS_ACCESS_KEY_ID")
    secret = os.environ.get("AWS_SECRET_ACCESS_KEY")
    if access and secret:
        return Credentials(
            access, secret, os.environ.get("AWS_SESSION_TOKEN") or None
        )
    path = os.environ.get("AWS_SHARED_CREDENTIALS_FILE") or os.path.join(
        os.path.expanduser("~"), ".aws", "credentials"
    )
    if not os.path.exists(path):
        return None
    profile = os.environ.get("AWS_PROFILE") or "default"
    parser = configparser.ConfigParser()
    try:
        parser.read(path)
        section = parser[profile]
        access = section.get("aws_access_key_id")
        secret = section.get("aws_secret_access_key")
        token = section.get("aws_session_token")
    except (configparser.Error, KeyError):
        return None
    if not access or not secret:
        return None
    return Credentials(access, secret, token or None)


def canonical_query(query: Optional[str]) -> str:
    """Normalized, sorted query string for the canonical request.  Our
    queries are pre-encoded (``_key`` quoting / literal params), so
    canonicalization is normalize-bare-flags + sort — applied identically
    by the signer and the stub's verifier."""
    if not query:
        return ""
    params = []
    for param in query.split("&"):
        if not param:
            continue
        params.append(param if "=" in param else param + "=")
    return "&".join(sorted(params))


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret_key: str, datestamp: str, region: str,
                service: str) -> bytes:
    """The SigV4 derived key chain (exposed for the stub's verifier)."""
    k = _hmac(("AWS4" + secret_key).encode(), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


class SigV4Signer:
    def __init__(self, creds: Credentials, region: Optional[str] = None,
                 service: str = "s3"):
        self.creds = creds
        self.region = region or default_region()
        self.service = service

    def sign_headers(
        self,
        method: str,
        key: str,
        query: Optional[str],
        payload: Optional[bytes],
        host: str,
        amz_date: Optional[str] = None,
    ) -> Dict[str, str]:
        """Headers to attach to one request: ``host``, ``x-amz-date``,
        ``x-amz-content-sha256``, ``authorization`` (+ session token).
        ``key`` is the percent-encoded request path as sent on the wire;
        ``query`` the raw (pre-encoded) query string or None."""
        if amz_date is None:
            amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        datestamp = amz_date[:8]
        payload_hash = hashlib.sha256(payload or b"").hexdigest()
        headers = {
            "host": host,
            "x-amz-content-sha256": payload_hash,
            "x-amz-date": amz_date,
        }
        if self.creds.session_token:
            headers["x-amz-security-token"] = self.creds.session_token
        signed_names = ";".join(sorted(headers))
        canonical = "\n".join([
            method.upper(),
            key,
            canonical_query(query),
            "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
            signed_names,
            payload_hash,
        ])
        scope = f"{datestamp}/{self.region}/{self.service}/aws4_request"
        string_to_sign = "\n".join([
            _ALGORITHM,
            amz_date,
            scope,
            hashlib.sha256(canonical.encode()).hexdigest(),
        ])
        key_bytes = signing_key(
            self.creds.secret_key, datestamp, self.region, self.service
        )
        signature = hmac.new(
            key_bytes, string_to_sign.encode(), hashlib.sha256
        ).hexdigest()
        out = dict(headers)
        out["authorization"] = (
            f"{_ALGORITHM} "
            f"Credential={self.creds.access_key}/{scope}, "
            f"SignedHeaders={signed_names}, "
            f"Signature={signature}"
        )
        return out
