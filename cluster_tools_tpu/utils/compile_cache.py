"""Persistent XLA compilation cache for the whole framework.

The e2e pipelines concentrate their cold wall in a handful of large jit
programs (measured on the CPU fallback: the fused watershed program 8.3 s,
the collective RAG 4.7 s; on the tunneled TPU the remote AOT compiles
dominated a 141 s cold sharded run vs 11.9 s warm).  jax ships a
persistent on-disk executable cache but leaves it OFF by default — so
every fresh process (each driver bench subprocess, every production
worker) recompiles everything.  Enabling it makes cold starts converge to
warm across processes and rounds: the reference's deployment model spawns
many short-lived jobs (cluster_tasks.py job scripts), where this matters
most.

``enable_compile_cache()`` is called from ``runtime.build`` and bench
entry points; set ``CTT_COMPILE_CACHE=0`` to disable or
``CTT_COMPILE_CACHE=<dir>`` to relocate (default
``~/.cache/cluster_tools_tpu/xla``).  Idempotent; safe on backends whose
executables cannot be serialized (the cache just never hits).
"""

from __future__ import annotations

import os

# the directory jax is actually caching to (None until first enable)
_ACTIVE_DIR: str | None = None


def active_dir() -> str | None:
    """The directory jax is caching executables to, or None when the cache
    was never enabled / is disabled (introspection for the serve daemon's
    /healthz and the ExecutionContext describe())."""
    return _ACTIVE_DIR


def enable_compile_cache(path: str | None = None) -> str | None:
    """Turn on jax's persistent compilation cache (idempotent).

    Returns the directory jax is actually caching to — once enabled, later
    calls return the ORIGINAL directory regardless of their arguments
    (re-pointing a live cache mid-process is not supported).  Returns None
    when disabled via ``CTT_COMPILE_CACHE=0`` or when the cache directory
    cannot be created (the cache is an optimization; never fail the
    caller's workload for it)."""
    global _ACTIVE_DIR
    if _ACTIVE_DIR is not None:
        return _ACTIVE_DIR
    env = os.environ.get("CTT_COMPILE_CACHE")
    if env is not None and env.strip() in ("0", "false", "off", ""):
        return None
    if path is None:
        path = (
            env
            if env
            else os.path.join(
                os.path.expanduser("~"), ".cache", "cluster_tools_tpu", "xla"
            )
        )
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # only cache programs with a substantial compile — tiny ones are
        # cheaper to recompile than to hash+load (and each cached-load
        # prints a cosmetic machine-feature notice on XLA:CPU)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except OSError as e:
        print(f"[compile_cache] disabled ({e})", flush=True)
        return None
    _ACTIVE_DIR = path
    # ctt-obs: count cache hits/misses via jax.monitoring (no-op when
    # tracing is off) and record how warm the cache was at enable time
    from ..obs import metrics as obs_metrics

    obs_metrics.install_compile_cache_listener()
    try:
        n_entries = sum(1 for n in os.listdir(path) if not n.startswith("."))
    except OSError:  # pragma: no cover - dir vanished between calls
        n_entries = 0
    obs_metrics.set_gauge("compile_cache.entries_at_enable", n_entries)
    return path
