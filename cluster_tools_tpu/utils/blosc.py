"""ctypes binding to the system c-blosc (v1) — the zarr ecosystem's default
chunk codec.

The reference stack reads blosc-compressed zarr/n5 through z5py's bundled
c-blosc (reference cluster_tools/utils/volume_utils.py:21-22); this image has
no zarr-python/z5py, but ships ``libblosc.so.1`` (1.21) — binding it keeps us
bit-compatible with every chunk the ecosystem writes (all cnames: blosclz,
lz4, lz4hc, zlib, zstd; byte- and bit-shuffle) without vendoring a codec.

Context-variant API only (``*_ctx``): no global init, thread-safe, so the
store's threaded chunk readers can decompress concurrently.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import threading
from typing import Optional

MAX_OVERHEAD = 16  # BLOSC_MAX_OVERHEAD: container header bytes

# blosc shuffle constants (blosc.h)
NOSHUFFLE = 0
SHUFFLE = 1
BITSHUFFLE = 2

_lib = None
_lib_checked = False
_load_lock = threading.Lock()


def _bind(lib: ctypes.CDLL) -> bool:
    """Declare the prototypes we call; returns False if the core symbols
    are missing (not a c-blosc1)."""
    try:
        lib.blosc_compress_ctx.restype = ctypes.c_int
        lib.blosc_compress_ctx.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
        ]
        lib.blosc_decompress_ctx.restype = ctypes.c_int
        lib.blosc_decompress_ctx.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int,
        ]
        lib.blosc_cbuffer_sizes.restype = None
        lib.blosc_cbuffer_sizes.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_size_t),
        ]
    except AttributeError:
        return False
    try:
        # >= 1.16 only; decompress() falls back to cbuffer_sizes without it
        lib.blosc_cbuffer_validate.restype = ctypes.c_int
        lib.blosc_cbuffer_validate.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
    except AttributeError:
        pass
    return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_checked
    if _lib_checked:
        return _lib
    with _load_lock:
        if _lib_checked:
            return _lib
        candidates = ["libblosc.so.1", "libblosc.so", "libblosc.dylib"]
        found = ctypes.util.find_library("blosc")
        if found:
            candidates.insert(0, found)
        lib_found = None
        for name in candidates:
            try:
                lib = ctypes.CDLL(name)
            except OSError:
                continue
            if _bind(lib):
                lib_found = lib
                break
        # publish the lib BEFORE the checked flag: a concurrent reader that
        # sees _lib_checked must also see the final _lib
        _lib = lib_found
        _lib_checked = True
    return _lib


def available() -> bool:
    """True when a usable system libblosc was found."""
    return _load() is not None


def decompress(payload: bytes, expected_nbytes: Optional[int] = None) -> bytes:
    """Decompress one blosc frame (any cname/shuffle the lib supports).

    ``expected_nbytes`` bounds the output allocation: chunk callers know the
    decoded size a frame may legitimately claim (chunk_shape × itemsize), and
    a corrupt/hostile chunk from an externally-produced store must fail
    loudly instead of triggering a multi-GB allocation from a forged header
    (ADVICE r5 — the pre-1.16 fallback path read the header-claimed nbytes
    unbounded; the clamp applies to the validate path too, since
    ``blosc_cbuffer_validate`` checks consistency, not plausibility)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(
            "blosc-compressed chunk but no system libblosc available"
        )
    nbytes = ctypes.c_size_t(0)
    if hasattr(lib, "blosc_cbuffer_validate"):
        # validate reads the header defensively (truncated/corrupt frames
        # fail here instead of over-reading) and yields the decompressed size
        rc = lib.blosc_cbuffer_validate(
            payload, len(payload), ctypes.byref(nbytes)
        )
        if rc < 0:
            raise ValueError("corrupt blosc chunk (header validation failed)")
    else:
        # pre-1.16 libs: read the sizes from the header; decompress_ctx
        # still bounds-checks against destsize below
        if len(payload) < MAX_OVERHEAD:
            raise ValueError("truncated blosc chunk")
        cbytes = ctypes.c_size_t(0)
        blocksize = ctypes.c_size_t(0)
        lib.blosc_cbuffer_sizes(
            payload, ctypes.byref(nbytes), ctypes.byref(cbytes),
            ctypes.byref(blocksize),
        )
        if cbytes.value != len(payload):
            raise ValueError("corrupt blosc chunk (size header mismatch)")
    if expected_nbytes is not None and nbytes.value > int(expected_nbytes):
        raise ValueError(
            f"corrupt blosc chunk: header claims {nbytes.value} decompressed "
            f"bytes, expected at most {int(expected_nbytes)}"
        )
    out = ctypes.create_string_buffer(max(nbytes.value, 1))
    n = lib.blosc_decompress_ctx(payload, out, nbytes.value, 1)
    if n < 0 or n != nbytes.value:
        raise ValueError(f"blosc decompression failed (rc={n})")
    return out.raw[: nbytes.value]


def compress(
    raw: bytes,
    typesize: int,
    cname: str = "lz4",
    clevel: int = 5,
    shuffle: int = SHUFFLE,
    blocksize: int = 0,
) -> bytes:
    """Compress ``raw`` into one blosc frame (zarr-python default settings:
    lz4, clevel 5, byte shuffle, automatic block size)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("blosc compression requested but libblosc missing")
    typesize = max(int(typesize), 1)
    dest_len = len(raw) + MAX_OVERHEAD
    out = ctypes.create_string_buffer(dest_len)
    n = lib.blosc_compress_ctx(
        int(clevel), int(shuffle), typesize, len(raw), raw, out, dest_len,
        str(cname).encode(), int(blocksize), 1,
    )
    if n <= 0:
        raise ValueError(f"blosc compression failed (rc={n}, cname={cname!r})")
    return out.raw[:n]
