"""ctt-cloud: the storage-backend seam under the chunked store.

``utils/store.py`` historically spoke straight to the filesystem
(``open``/``os.stat``/``os.replace``) from ``Dataset``/``Group`` and the
format adapters.  Production EM volumes live in S3/GCS-style object
stores — zarr's native habitat — so the byte-level operations now go
through a :class:`StoreBackend`:

  * :class:`PosixBackend` — the original behavior, byte for byte (atomic
    tmp+rename writes, ``(inode, mtime_ns, size)`` freshness signatures);
  * :class:`HttpBackend` — ``http://`` / ``https://`` object stores
    speaking plain GET/PUT/HEAD/DELETE with ``Range`` reads.

URL scheme
----------

A dataset path is simply a URL whose last path component carries the
container extension, e.g.::

    http://objstore:9000/bucket/volume.n5        (container root)
    http://objstore:9000/bucket/volume.zarr/raw  (dataset inside it)

``file_reader`` routes any ``http(s)://`` path here; everything after the
origin is the object key namespace.  The wire protocol is the small
object-store subset the local stub server (``tests/objstub.py``) and any
S3/GCS HTTP gateway can serve:

  ``GET <key>``      → 200 + object bytes; honors ``Range: bytes=a-b``
                       (206 + ``Content-Range``); 404 when absent.
                       A *directory* key returns a JSON array of child
                       names with the ``X-CTT-Dir: 1`` header (the
                       listing analog of ``os.listdir`` — object stores
                       express this as a delimiter list query; the stub
                       keeps it a plain GET).
  ``PUT <key>``      → store bytes atomically, create parents; 200/201.
                       With ``If-None-Match: *`` the PUT is *create-only*:
                       412 Precondition Failed when the key already
                       exists — the object-store analog of the
                       ``publish_once`` exclusive link (ctt-fleet lifts
                       the work-queue lease/result claims onto this).
  ``HEAD <key>``     → existence + freshness headers (``ETag``,
                       ``Last-Modified``, ``Content-Length``,
                       ``X-CTT-Dir`` for directories).
  ``DELETE <key>``   → remove the object (or prefix/directory tree); 204.

Freshness: the decoded-chunk LRU keys remote entries by the
``(ETag, Last-Modified, Content-Length)`` signature — the object
store's analog of the POSIX ``(inode, mtime_ns, size)`` triple — so a
rewrite by any process anywhere is a cache miss, never stale data.
Revalidation happens ON the read itself (``read_bytes_versioned``): a
single GET with ``If-None-Match`` on the cached ETag answers 304 for a
warm entry (one round trip, zero body bytes — the HEAD probe that used
to precede every chunk GET is folded in) or delivers the fresh payload
together with its new signature (the LRU is the latency shield that
makes high-RTT stores usable).

Resilience: every request checks the ``store.remote_read`` (GET/HEAD) or
``store.remote_write`` (PUT/DELETE) fault site, and transient failures
(connection errors, 5xx, truncated multipart ranges) surface as
``OSError`` so the shared backoff helper (``utils/retry.py``) absorbs
them — chunk IO retries at the Dataset layer under the
``store.remote_retries`` counter, metadata helpers retry internally.  A
*truncated* single-object body (the server promised more bytes than it
sent) is returned short on purpose: the chunk decode classifies it as
:class:`CorruptChunk`, exactly like a torn POSIX write, so the same
retry/heal machinery applies.

Authentication (ctt-diskless): requests carry an AWS Signature V4
``Authorization`` header (``utils/sigv4.py``) when the origin demands it
— always for ``s3://bucket/key`` paths (mapped path-style onto
``CTT_S3_ENDPOINT``), and for plain ``http(s)://`` origins when
``CTT_S3_SIGN=1`` opts in.  Credentials come from the environment
(``AWS_ACCESS_KEY_ID``/``AWS_SECRET_ACCESS_KEY``) or the shared
credentials file; with none resolvable the request goes out unsigned and
a signing store answers 401/403, which surfaces as a *retryable*
``OSError`` under the ``store.remote_auth_retries`` counter — loud after
the backoff gives up, never a silent downgrade.  The signing step has
its own fault site (``store.remote_auth``) so chaos runs can exercise
credential trouble separately from wire trouble.

Large PUTs (ctt-diskless): payloads above ``CTT_REMOTE_MULTIPART_MB``
ride the S3 multipart protocol — initiate (``POST ?uploads``), parallel
part PUTs on the range pool with per-part retry, complete (``POST
?uploadId=``), abort on failure — counted by
``store.remote_multipart_uploads``.  ``publish_once`` stays a single
create-only PUT (the claim must be atomic).

Knobs (env, read once per process):

  ``CTT_REMOTE_THREADS``      chunk fan-out + multipart pool width (default 16)
  ``CTT_REMOTE_TIMEOUT_S``    per-request socket timeout (default 30)
  ``CTT_REMOTE_RANGE_MB``     objects larger than this split into parallel
                              range GETs (default 8; 0 = never split)
  ``CTT_REMOTE_MULTIPART_MB`` PUT payloads above this upload multipart
                              (default 8; 0 = never)
  ``CTT_S3_SIGN``             =1: SigV4-sign plain http(s) origins too
  ``CTT_S3_ENDPOINT``         gateway origin for ``s3://`` paths (default
                              ``https://s3.<region>.amazonaws.com``)
"""

from __future__ import annotations

import errno
import http.client
import json
import os
import re
import shutil
import threading
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from .. import faults
from ..obs import metrics as obs_metrics
from . import sigv4

__all__ = [
    "StoreBackend", "PosixBackend", "HttpBackend", "CorruptChunk",
    "backend_for", "is_remote_path", "atomic_write_bytes",
]


class CorruptChunk(OSError):
    """A chunk read back but failed to decode — truncated or garbled
    payload, i.e. a torn write (or a truncated object-store response).
    OSError subclass so the shared IO retry treats it as transient (a
    concurrent rewrite may land between attempts); if it never heals it
    fails the reading block cleanly and block retry repairs the store by
    rerunning the writer."""


# fsync before rename is the durability half of atomicity: without it a
# power failure can surface the renamed file EMPTY (metadata reached the
# journal, data didn't).  Chunk scratch on tmpfs doesn't care; status/meta
# JSON does.  CTT_STORE_FSYNC=0 opts out for throwaway stores.
_FSYNC = os.environ.get("CTT_STORE_FSYNC", "1").lower() not in (
    "0", "false", "off", ""
)


def atomic_write_bytes(path: str, payload: bytes) -> None:
    # tmp name must be unique per pid AND thread: concurrent block threads
    # writing the same meta file (e.g. two workers group-initializing the
    # shared scratch store) would otherwise replace each other's tmp away
    tmp = path + f".tmp{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "wb") as f:
            f.write(payload)
            if _FSYNC:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # failed writes must not litter .tmpPID.TID files in shared stores
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _env_pos_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    try:
        val = float(raw) if raw is not None else default
    except (TypeError, ValueError):
        val = default  # malformed degrades to default, the CTT_* convention
    return max(val, 0.0)


class StoreBackend:
    """Byte-level operations of one storage namespace.

    Paths are whatever the owning :func:`backend_for` resolution hands
    out: filesystem paths for :class:`PosixBackend`, full URLs for
    :class:`HttpBackend`.  Chunk payload calls (``read_bytes`` /
    ``write_bytes`` / ``signature``) raise ``FileNotFoundError`` for
    absent objects and ``OSError`` for transient trouble — the caller
    (``Dataset``) wraps them in the shared backoff retry under this
    backend's ``retry_counter``.  Metadata helpers (json/list/exists)
    absorb their own transients."""

    name = "posix"
    is_remote = False
    retry_counter = "store.io_retries"
    default_threads = 1  # Dataset.n_threads starting point

    # -- path algebra --------------------------------------------------------

    def join(self, *parts: str) -> str:
        return os.path.join(*parts)

    def dirname(self, path: str) -> str:
        return os.path.dirname(path)

    # -- payload bytes -------------------------------------------------------

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write_bytes(self, path: str, payload: bytes) -> None:
        atomic_write_bytes(path, payload)

    def signature(self, path: str):
        """Freshness signature for the decoded-chunk LRU; raises
        ``FileNotFoundError`` when the object is absent.  POSIX:
        ``(inode, mtime_ns, size)`` — ``os.replace`` changes the inode, so
        any rewrite (in- or cross-process) is a miss."""
        st = os.stat(path)
        return (st.st_ino, st.st_mtime_ns, st.st_size)

    def publish_once(self, path: str, payload: bytes) -> bool:
        """Atomically publish ``payload`` at ``path`` iff nothing is there
        yet — the lease/result claim arbiter (ctt-steal, ctt-serve).
        POSIX stages to a pid+thread-unique tmp file and ``os.link``s it
        into place: the link either creates the name with the full payload
        visible or fails with EEXIST.  Returns True when this caller won
        the slot."""
        tmp = path + f".tmp{os.getpid()}.{threading.get_ident()}"
        atomic_write_bytes(tmp, payload)
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def mtime(self, path: str) -> Optional[float]:
        """Last-modified wall stamp, or None when absent/unknown — the
        torn-lease ageing fallback (a lease whose JSON never parses still
        expires, from its storage timestamp)."""
        try:
            return os.path.getmtime(path)
        except OSError:
            return None

    def remove(self, path: str) -> None:
        os.unlink(path)

    # -- namespace / metadata ------------------------------------------------

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def isdir(self, path: str) -> bool:
        return os.path.isdir(path)

    def listdir(self, path: str) -> List[str]:
        if not os.path.isdir(path):
            return []
        return sorted(os.listdir(path))

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def rmtree(self, path: str) -> None:
        shutil.rmtree(path)

    def read_json(self, path: str) -> Any:
        with open(path) as f:
            return json.load(f)

    def write_json(self, path: str, obj: Any) -> None:
        self.write_bytes(path, json.dumps(obj, indent=2).encode())

    # -- fan-out -------------------------------------------------------------

    def map(self, fn, items, n_threads: int) -> list:
        """Apply ``fn`` over ``items`` with up to ``n_threads`` workers —
        the chunk fan-out seam.  POSIX spins an ephemeral pool (thread
        startup is noise next to codec work); the HTTP backend overrides
        with a persistent pool so worker threads keep their keep-alive
        connections across calls."""
        items = list(items)
        n = min(max(int(n_threads), 1), len(items))
        if n <= 1:
            return [fn(it) for it in items]
        with ThreadPoolExecutor(n) as pool:
            return list(pool.map(fn, items))


# -- remote inflight gauge (module-level: one series across backends) -------

_INFLIGHT_LOCK = threading.Lock()
_INFLIGHT = 0


def _note_inflight(delta: int) -> None:
    global _INFLIGHT
    with _INFLIGHT_LOCK:
        _INFLIGHT += delta
        value = _INFLIGHT
    obs_metrics.set_gauge("store.remote_inflight", value)


class HttpBackend(StoreBackend):
    """``http(s)://`` object-store namespace over plain range-read HTTP.

    One instance per origin (scheme + host + port), with one keep-alive
    connection per thread and a shared fetch pool for multipart range
    reads — "parallel multipart-style" IO rides chunk-level fan-out
    (``Dataset.n_threads`` defaults to ``CTT_REMOTE_THREADS`` on remote
    datasets) plus intra-object range splitting for oversized objects."""

    name = "http"
    is_remote = True
    retry_counter = "store.remote_retries"

    def __init__(self, origin: str, alias: Optional[str] = None,
                 alias_prefix: str = ""):
        parsed = urllib.parse.urlsplit(origin)
        if parsed.scheme not in ("http", "https"):
            raise ValueError(f"unsupported remote scheme in {origin!r}")
        self.origin = f"{parsed.scheme}://{parsed.netloc}"
        self._scheme = parsed.scheme
        self._netloc = parsed.netloc
        # ``s3://bucket`` paths ride a plain HTTP gateway path-style:
        # alias is the virtual origin ("s3://bucket"), alias_prefix the
        # key prefix it maps to ("/bucket")
        self._alias = alias
        self._alias_prefix = alias_prefix
        # signing is ARMED per origin (s3:// always; http(s) by opt-in);
        # a signer only exists when credentials resolve — armed-but-
        # credential-less sends unsigned and lets the store say 403
        self._sign = alias is not None or (
            os.environ.get("CTT_S3_SIGN", "").lower()
            in ("1", "true", "on", "yes")
        )
        self._signer: Optional[sigv4.SigV4Signer] = None
        if self._sign:
            creds = sigv4.resolve_credentials()
            if creds is not None:
                self._signer = sigv4.SigV4Signer(creds)
        self._tls = threading.local()
        self._pool_lock = threading.Lock()
        # two PERSISTENT pools (threads keep their keep-alive connections
        # across calls — ephemeral pools pay connect churn per region):
        # "fan" runs chunk-level operations, "range" runs multipart part
        # fetches.  Separate so a fan task issuing a multipart read can
        # never deadlock waiting on its own pool.
        self._pools: Dict[str, ThreadPoolExecutor] = {}
        self.default_threads = max(
            int(_env_pos_float("CTT_REMOTE_THREADS", 16)), 1
        )
        self.timeout_s = _env_pos_float("CTT_REMOTE_TIMEOUT_S", 30.0) or 30.0
        self.range_bytes = int(
            _env_pos_float("CTT_REMOTE_RANGE_MB", 8.0) * 1024 * 1024
        )
        self.multipart_bytes = int(
            _env_pos_float("CTT_REMOTE_MULTIPART_MB", 8.0) * 1024 * 1024
        )

    # -- connection plumbing -------------------------------------------------

    def join(self, *parts: str) -> str:
        out = parts[0].rstrip("/")
        for part in parts[1:]:
            out = out + "/" + str(part).strip("/")
        return out

    def dirname(self, path: str) -> str:
        return path.rsplit("/", 1)[0]

    def _key(self, path: str) -> str:
        """The request target for a full URL of this origin."""
        if self._alias and path.startswith(self._alias):
            key = self._alias_prefix + path[len(self._alias):]
        elif path.startswith(self.origin):
            key = path[len(self.origin):]
        else:
            key = urllib.parse.urlsplit(path).path
        if not key.startswith("/"):
            key = "/" + key
        return urllib.parse.quote(key)

    def _connection(self):
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            cls = (
                http.client.HTTPSConnection
                if self._scheme == "https"
                else http.client.HTTPConnection
            )
            conn = cls(self._netloc, timeout=self.timeout_s)
            self._tls.conn = conn
        return conn

    def _drop_connection(self) -> None:
        conn = getattr(self._tls, "conn", None)
        self._tls.conn = None
        if conn is not None:
            try:
                conn.close()
            except Exception:  # ctt: noqa[CTT009] socket teardown of a failed connection cannot be allowed to mask the request error
                pass

    def _pool(self, kind: str) -> ThreadPoolExecutor:
        with self._pool_lock:
            pool = self._pools.get(kind)
            if pool is None:
                pool = ThreadPoolExecutor(
                    self.default_threads,
                    thread_name_prefix=f"ctt-remote-{kind}",
                )
                self._pools[kind] = pool
            return pool

    def map(self, fn, items, n_threads: int) -> list:
        items = list(items)
        if len(items) <= 1 or int(n_threads) <= 1:
            return [fn(it) for it in items]
        return list(self._pool("fan").map(fn, items))

    def _request(
        self, method: str, path: str,
        body: Optional[bytes] = None,
        headers: Optional[Dict[str, str]] = None,
        query: Optional[str] = None,
        site: Optional[str] = None,
    ) -> Tuple[int, Any, bytes, bool]:
        """One HTTP round trip: ``(status, headers, body, truncated)``.

        Network-level trouble (refused/reset/timeout, garbled response)
        raises ``OSError(EIO)`` — retryable.  A body shorter than the
        promised ``Content-Length`` (server hiccup mid-stream) comes back
        with ``truncated=True`` and the partial bytes so callers can
        classify it (chunk decode → ``CorruptChunk``) instead of hiding
        it behind a generic error.

        ``query`` is a pre-encoded query string appended AFTER key
        quoting (``_key`` percent-escapes ``?``/``=``, so it cannot ride
        in ``path``); ``site`` overrides the fault-injection site for
        request kinds with their own chaos semantics (listing GETs)."""
        if site is None:
            site = (
                "store.remote_write" if method in ("PUT", "DELETE", "POST")
                else "store.remote_read"
            )
        faults.check(site, path=path)
        obs_metrics.inc(
            "store.remote_writes" if site == "store.remote_write"
            else "store.remote_reads"
        )
        key = self._key(path)
        send_headers = dict(headers or {})
        if self._sign:
            # chaos seam for credential trouble, distinct from wire chaos
            faults.check("store.remote_auth", path=path)
            if self._signer is not None:
                send_headers.update(self._signer.sign_headers(
                    method, key, query, body, host=self._netloc,
                ))
        _note_inflight(1)
        try:
            conn = self._connection()
            try:
                target = key + (f"?{query}" if query else "")
                conn.request(
                    method, target, body=body,
                    headers=send_headers,
                )
                resp = conn.getresponse()
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                self._drop_connection()
                raise OSError(
                    errno.EIO,
                    f"{method} {path} failed: {type(e).__name__}: {e}",
                ) from e
            truncated = False
            try:
                # http.client returns b"" for HEAD (length pinned to 0),
                # so reading unconditionally keeps keep-alive hygiene
                data = resp.read()
            except http.client.IncompleteRead as e:
                # the server promised Content-Length and closed early: a
                # truncated object read — deliver the partial payload for
                # torn-write-style classification by the decoder
                data = e.partial
                truncated = True
                self._drop_connection()
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                self._drop_connection()
                raise OSError(
                    errno.EIO,
                    f"{method} {path} body read failed: {e}",
                ) from e
            if body is not None:
                obs_metrics.inc("store.remote_bytes_written", len(body))
            if data:
                obs_metrics.inc("store.remote_bytes_read", len(data))
            return resp.status, resp.headers, data, truncated
        finally:
            _note_inflight(-1)


    def _raise_for(self, status: int, method: str, path: str) -> None:
        if status == 404:
            raise FileNotFoundError(f"{path} (HTTP 404)")
        # a 5xx may have left the server mid-request (e.g. an unread PUT
        # body on a keep-alive socket): reconnect rather than risk the
        # next request landing on poisoned connection state
        self._drop_connection()
        if status in (401, 403):
            # auth rejection is RETRYABLE (plain OSError, never
            # FileNotFoundError): expiring session tokens and clock-skewed
            # signatures heal across the backoff, and a genuinely unsigned
            # request fails loudly once the retries are spent
            obs_metrics.inc("store.remote_auth_retries")
            raise OSError(
                errno.EACCES,
                f"HTTP {status} on {method} {path}: auth rejected "
                f"(unsigned request or bad signature/credentials)",
            )
        # everything unexpected is transient until the backoff gives up:
        # object-store gateways surface overload as 429/500/503, and a
        # hard 4xx failing loudly after 3 retries is still loud
        raise OSError(errno.EIO, f"HTTP {status} on {method} {path}")

    # -- payload bytes -------------------------------------------------------

    def read_bytes(self, path: str) -> bytes:
        from .retry import io_retry

        split = self.range_bytes

        def _first_window() -> Tuple[Optional[int], bytes]:
            if split <= 0:
                status, _, data, _ = self._request("GET", path)
                if status != 200:
                    self._raise_for(status, "GET", path)
                return None, data
            status, hdrs, data, truncated = self._request(
                "GET", path, headers={"Range": f"bytes=0-{split - 1}"}
            )
            if status == 200:
                return None, data  # server ignored the range; whole object
            if status != 206:
                self._raise_for(status, "GET", path)
            total = _content_range_total(hdrs.get("Content-Range"))
            if truncated or total is None or total <= len(data):
                # short first window: decode classifies (CorruptChunk) and
                # the shared retry re-fetches — the torn-POSIX-chunk contract
                return None, data
            return total, data

        total, data = io_retry(
            _first_window, what=f"read {path}", counter=self.retry_counter
        )
        if total is None:
            return data
        # parallel multipart-style range reads for the tail
        offsets = list(range(len(data), total, split))
        parts = list(
            self._pool("range").map(
                lambda off: self._range_part(path, off, split, total), offsets
            )
        )
        return data + b"".join(parts)

    def read_bytes_versioned(
        self, path: str, etag: Optional[str] = None,
    ) -> Tuple[Optional[bytes], tuple]:
        """One conditional GET folding the freshness HEAD into the read
        (the ctt-cloud follow-up): returns ``(None, sig)`` on 304 — the
        caller's cached bytes are still current, zero body crossed the
        wire — or ``(payload, sig)`` where ``sig`` is the
        ``(ETag, Last-Modified, Content-Length)`` triple taken from the
        GET response itself, byte-compatible with :meth:`signature`.
        Large objects keep the multipart range-read tail of
        :meth:`read_bytes` (continuation ranges are never conditional)."""
        from .retry import io_retry

        split = self.range_bytes
        headers: Dict[str, str] = {}
        if etag:
            headers["If-None-Match"] = etag
        if split > 0:
            headers["Range"] = f"bytes=0-{split - 1}"

        def _first_window():
            status, hdrs, data, truncated = self._request(
                "GET", path, headers=headers
            )
            if status == 304:
                return None, None, (
                    hdrs.get("ETag") or etag,
                    hdrs.get("Last-Modified"),
                    hdrs.get("Content-Length"),
                )
            if status not in (200, 206):
                self._raise_for(status, "GET", path)
            total = (
                _content_range_total(hdrs.get("Content-Range"))
                if status == 206 else None
            )
            sig = (
                hdrs.get("ETag"),
                hdrs.get("Last-Modified"),
                str(total) if total is not None
                else hdrs.get("Content-Length"),
            )
            if (status == 200 or truncated or total is None
                    or total <= len(data)):
                # whole object (or short first window: decode classifies
                # and the shared retry re-fetches, the torn-chunk contract)
                return None, data, sig
            return total, data, sig

        total, data, sig = io_retry(
            _first_window, what=f"read {path}", counter=self.retry_counter
        )
        if total is None:
            return data, sig
        offsets = list(range(len(data), total, split))
        parts = list(
            self._pool("range").map(
                lambda off: self._range_part(path, off, split, total), offsets
            )
        )
        return data + b"".join(parts), sig

    def _range_part(self, path: str, offset: int, split: int,
                    total: int) -> bytes:
        from .retry import io_retry

        end = min(offset + split, total) - 1

        def _fetch() -> bytes:
            st, _, part, part_trunc = self._request(
                "GET", path, headers={"Range": f"bytes={offset}-{end}"}
            )
            if st not in (200, 206):
                self._raise_for(st, "GET", path)
            if part_trunc or len(part) != end - offset + 1:
                raise OSError(
                    errno.EIO,
                    f"truncated range response for {path} "
                    f"[{offset}, {end}]: got {len(part)} bytes",
                )
            return part

        return io_retry(
            _fetch, what=f"range read {path}@{offset}",
            counter=self.retry_counter,
        )

    def write_bytes(self, path: str, payload: bytes) -> None:
        if 0 < self.multipart_bytes < len(payload):
            return self._write_multipart(path, payload)
        from .retry import io_retry

        def _put() -> None:
            status, _, _, _ = self._request("PUT", path, body=payload)
            if status not in (200, 201, 204):
                self._raise_for(status, "PUT", path)

        io_retry(_put, what=f"write {path}", counter=self.retry_counter)

    def _write_multipart(self, path: str, payload: bytes) -> None:
        """S3 multipart upload for oversized payloads (ragged ``.npy``
        scratch chunks included): initiate → parallel part PUTs (each
        with its own retry, riding the range pool) → complete.  A failure
        past initiate best-effort-aborts so the store can reap parts."""
        from .retry import io_retry

        part_size = max(self.multipart_bytes, 1)

        def _initiate() -> str:
            status, _, data, _ = self._request("POST", path, query="uploads")
            if status not in (200, 201):
                self._raise_for(status, "POST", path)
            m = re.search(rb"<UploadId>([^<]+)</UploadId>", data)
            if m is None:
                raise OSError(
                    errno.EIO,
                    f"multipart initiate {path}: no UploadId in response",
                )
            return m.group(1).decode()

        upload_id = io_retry(
            _initiate, what=f"multipart initiate {path}",
            counter=self.retry_counter,
        )
        uid_query = "uploadId=" + urllib.parse.quote(upload_id, safe="")

        def _put_part(numbered: Tuple[int, int]) -> Tuple[int, str]:
            number, offset = numbered
            chunk = payload[offset:offset + part_size]

            def _put() -> Tuple[int, str]:
                status, hdrs, _, _ = self._request(
                    "PUT", path, body=chunk,
                    query=f"partNumber={number}&{uid_query}",
                )
                if status not in (200, 201, 204):
                    self._raise_for(status, "PUT", path)
                return number, hdrs.get("ETag") or f'"{number}"'

            return io_retry(
                _put, what=f"multipart part {number} {path}",
                counter=self.retry_counter,
            )

        try:
            numbered = list(enumerate(range(0, len(payload), part_size), 1))
            etags = list(self._pool("range").map(_put_part, numbered))
            manifest = "".join(
                f"<Part><PartNumber>{n}</PartNumber><ETag>{etag}</ETag></Part>"
                for n, etag in etags
            )
            xml = (
                "<CompleteMultipartUpload>"
                + manifest
                + "</CompleteMultipartUpload>"
            ).encode()

            def _complete() -> None:
                status, _, _, _ = self._request(
                    "POST", path, body=xml, query=uid_query
                )
                if status not in (200, 201, 204):
                    self._raise_for(status, "POST", path)

            io_retry(
                _complete, what=f"multipart complete {path}",
                counter=self.retry_counter,
            )
        except BaseException:
            try:
                self._request("DELETE", path, query=uid_query)
            except OSError:
                pass  # abort is advisory; the store reaps stale uploads
            raise
        obs_metrics.inc("store.remote_multipart_uploads")

    def publish_once(self, path: str, payload: bytes) -> bool:
        """Create-only PUT: ``If-None-Match: *`` makes the object store
        the claim arbiter — 412 Precondition Failed means the slot was
        already taken (the remote analog of the POSIX ``os.link``
        EEXIST).  Transient trouble retries internally; a retry that
        lands after its own first attempt actually stored the object
        reads as a lost race, which costs at worst a spurious
        requeue-later, never two owners."""
        from .retry import io_retry

        def _put() -> bool:
            status, _, _, _ = self._request(
                "PUT", path, body=payload,
                headers={"If-None-Match": "*"},
            )
            if status == 412:
                return False
            if status not in (200, 201, 204):
                self._raise_for(status, "PUT", path)
            return True

        return io_retry(
            _put, what=f"publish {path}", counter=self.retry_counter
        )

    def mtime(self, path: str) -> Optional[float]:
        """Wall stamp from the ``Last-Modified`` header (HEAD), or None —
        the torn-lease ageing fallback over an object store."""
        try:
            status, hdrs = self._head(path)
        except OSError:
            return None
        if status != 200:
            return None
        value = hdrs.get("Last-Modified")
        if not value:
            return None
        try:
            import email.utils

            return email.utils.parsedate_to_datetime(value).timestamp()
        except (TypeError, ValueError):
            return None

    def signature(self, path: str):
        """``(ETag, Last-Modified, Content-Length)`` from a HEAD — the
        remote analog of the POSIX inode triple (any rewrite changes the
        ETag/mtime, so stale LRU entries can only miss)."""
        status, hdrs, _, _ = self._request("HEAD", path)
        if status != 200:
            self._raise_for(status, "HEAD", path)
        return (
            hdrs.get("ETag"),
            hdrs.get("Last-Modified"),
            hdrs.get("Content-Length"),
        )

    def remove(self, path: str) -> None:
        from .retry import io_retry

        def _delete() -> None:
            status, _, _, _ = self._request("DELETE", path)
            if status not in (200, 202, 204, 404):
                self._raise_for(status, "DELETE", path)

        io_retry(_delete, what=f"delete {path}", counter=self.retry_counter)

    # -- namespace / metadata ------------------------------------------------
    # metadata helpers absorb their own transients (the callers are not
    # under the Dataset-level chunk retry)

    def _head(self, path: str) -> Tuple[int, Any]:
        from .retry import io_retry

        def _probe():
            status, hdrs, _, _ = self._request("HEAD", path)
            # 401/403 must be LOUD here too: an existence probe answering
            # False on an auth rejection would read as "no lease/no peer"
            # and corrupt scheduling decisions downstream
            if status >= 500 or status in (429, 401, 403):
                self._raise_for(status, "HEAD", path)
            return status, hdrs

        return io_retry(
            _probe, what=f"head {path}", counter=self.retry_counter
        )

    def exists(self, path: str) -> bool:
        status, _ = self._head(path)
        return status == 200

    def isdir(self, path: str) -> bool:
        status, hdrs = self._head(path)
        return status == 200 and hdrs.get("X-CTT-Dir") == "1"

    # listing page size (``?limit=&marker=`` continuation; tests shrink it
    # to exercise multi-page listings against the stub store).  A server
    # that ignores the parameters returns everything in one page and the
    # loop still terminates — pagination is an upper bound, not a contract.
    list_page = 1000

    def listdir(self, path: str) -> List[str]:
        from .retry import io_retry

        def _page(marker: Optional[str]):
            query = f"limit={int(self.list_page)}"
            if marker is not None:
                query += "&marker=" + urllib.parse.quote(marker, safe="")
            status, hdrs, data, truncated = self._request(
                "GET", path, query=query, site="store.remote_list"
            )
            if status == 404:
                return [], None
            if status != 200 or truncated:
                self._raise_for(status if status != 200 else 500,
                                "GET", path)
            if hdrs.get("X-CTT-Dir") != "1":
                return [], None
            names = [str(n) for n in json.loads(data.decode())]
            return names, hdrs.get("X-CTT-List-Next")

        # each page retries independently against the same marker (listing
        # pages are idempotent) — an injected/transient listing failure
        # mid-continuation never restarts the whole scan
        names: List[str] = []
        marker: Optional[str] = None
        while True:
            page, nxt = io_retry(
                lambda m=marker: _page(m),
                what=f"list {path}", counter=self.retry_counter,
            )
            names.extend(page)
            if nxt is None or not page:
                break
            marker = nxt
        return sorted(names)

    def makedirs(self, path: str) -> None:
        return None  # object namespaces have no directories to create

    def rmtree(self, path: str) -> None:
        self.remove(path)

    def read_json(self, path: str) -> Any:
        from .retry import io_retry

        def _load() -> Any:
            payload = self.read_bytes(path)
            try:
                return json.loads(payload.decode())
            except ValueError as e:
                # truncated/garbled metadata responses heal like torn
                # chunks: retryable, loud if persistent
                raise CorruptChunk(
                    f"metadata {path} failed to parse "
                    f"({len(payload)} bytes): {e}"
                ) from e

        return io_retry(
            _load, what=f"read meta {path}", counter=self.retry_counter
        )

    def write_json(self, path: str, obj: Any) -> None:
        from .retry import io_retry

        payload = json.dumps(obj, indent=2).encode()
        io_retry(
            lambda: self.write_bytes(path, payload),
            what=f"write meta {path}", counter=self.retry_counter,
        )


def _content_range_total(value: Optional[str]) -> Optional[int]:
    """Total object size from a ``Content-Range: bytes a-b/total`` header."""
    if not value or "/" not in value:
        return None
    total = value.rsplit("/", 1)[1].strip()
    try:
        return int(total)
    except ValueError:
        return None  # "*" (unknown) or garbage: treat as unsplittable


PosixBackend = StoreBackend  # posix IS the base behavior
_POSIX = StoreBackend()
_REMOTE_LOCK = threading.Lock()
_REMOTE: Dict[str, HttpBackend] = {}


def is_remote_path(path: str) -> bool:
    return isinstance(path, str) and path.startswith(
        ("http://", "https://", "s3://")
    )


def backend_for(path: str) -> StoreBackend:
    """The backend owning ``path``: the process-wide POSIX singleton, or
    one cached :class:`HttpBackend` per remote origin (so every dataset
    of one store shares connections, pool, and counters).  ``s3://bucket``
    paths get an always-signing backend aimed path-style at the
    ``CTT_S3_ENDPOINT`` gateway (default: the region's public endpoint)."""
    if not is_remote_path(path):
        return _POSIX
    parsed = urllib.parse.urlsplit(path)
    origin = f"{parsed.scheme}://{parsed.netloc}"
    with _REMOTE_LOCK:
        backend = _REMOTE.get(origin)
        if backend is None:
            if parsed.scheme == "s3":
                endpoint = os.environ.get("CTT_S3_ENDPOINT") or (
                    f"https://s3.{sigv4.default_region()}.amazonaws.com"
                )
                backend = HttpBackend(
                    endpoint, alias=origin,
                    alias_prefix=f"/{parsed.netloc}",
                )
            else:
                backend = HttpBackend(origin)
            _REMOTE[origin] = backend
        return backend
