"""Chunked-array storage: zarr v2 + n5 directory stores and an hdf5 passthrough.

The reference keeps all inter-process data in chunked n5/zarr/hdf5 volumes through the
``z5py`` C++ codec (SURVEY.md §1 L0; reference utils/volume_utils.py:21-22).  This
module provides the same ``file_reader(path, mode)`` façade as a small self-contained
implementation:

  * ``.zarr`` → zarr v2 directory store (``.zarray`` metadata, ``i.j.k`` chunk files,
    raw/zlib/blosc compression — blosc via the system libblosc, all cnames +
    byte/bit shuffle, the zarr-python default codec) — readable by standard zarr
    implementations;
  * ``.n5``   → n5 directory store (``attributes.json``, reversed dimension order,
    big-endian chunks with the mode-0 header, raw/gzip/blosc) — readable by
    z5py/n5 java;
  * ``.h5`` / ``.hdf5`` → h5py.

A ``RaggedDataset`` covers the reference's variable-length chunks (per-block graph /
feature / overlap serializations, e.g. reference graph/initial_sub_graphs.py:129).

Datasets support numpy-style region read/write (``ds[bb]`` / ``ds[bb] = x``) with
read-modify-write on partially covered chunks.  Parallel writers must write disjoint
chunk-aligned regions — the same contract the reference relies on (SURVEY.md §5
"race detection": disjoint inner-block writes by construction).

Host hot-path fast paths (ctt-io):

  * region writes that exactly cover a chunk encode straight from the region
    view (no intermediate chunk buffer, no RMW read+decode);
  * region reads AND writes fan their per-chunk work over ``ds.n_threads``
    (the z5py idiom, ``set_read_threads``) — codec work releases the GIL;
  * a process-global decoded-chunk LRU (``CTT_CHUNK_CACHE_MB``, default 64,
    0 disables) so overlapping halo'd reads of neighboring blocks decode
    each shared chunk once.  Entries are validated against the chunk file's
    ``(inode, mtime_ns, size)`` and invalidated by in-process writes, so
    cross-process writers are picked up on the next read.

Transient-failure resilience (ctt-fault):

  * chunk reads/writes run under the shared backoff helper
    (``utils/retry.py``): transient ``OSError`` retries with exponential
    backoff + jitter (``store.io_retries`` obs counter) instead of failing
    the block outright; ``FileNotFoundError`` stays non-retryable (an
    unwritten chunk means fill_value, not failure);
  * a chunk that reads but fails to *decode* (truncated/garbled payload —
    a torn write by a crashed peer) raises :class:`CorruptChunk`, an
    OSError subclass: retryable at the IO level (a concurrent rewrite may
    land between attempts) and, if it never heals, a clean block failure
    that the task retry loop repairs by rerunning the writing block;
  * atomic writes fsync the tmp file before ``os.replace`` (an unsynced
    rename can surface as an empty/truncated file after power loss —
    ``CTT_STORE_FSYNC=0`` opts out for throwaway scratch) and unlink the
    tmp file when the write fails, so failed writes don't litter
    ``.tmpPID.TID`` files in shared stores;
  * fault-injection sites ``store.read`` / ``store.write`` /
    ``store.decode`` (see ``cluster_tools_tpu/faults``) exercise all of the
    above deterministically, including torn-write simulation.

Object-store backend (ctt-cloud):

  * every byte-level operation goes through a :class:`StoreBackend`
    (``utils/store_backend.py``): POSIX keeps the exact behavior above,
    and ``http(s)://`` paths speak GET/PUT/HEAD/DELETE with ``Range``
    reads against an object store (the URL scheme, wire schema, and the
    local stub server contract are documented in that module);
  * remote datasets key the decoded-chunk LRU by the
    ``(ETag, Last-Modified, Content-Length)`` signature instead of the
    POSIX inode triple, and revalidate it ON the read: one conditional
    GET (``If-None-Match``) answers 304 for a warm entry — zero body
    bytes, one round trip, no separate HEAD — making the LRU the latency
    shield for high-RTT stores;
  * remote chunk IO retries under ``store.remote_retries`` through the
    same backoff helper, with request-level fault sites
    ``store.remote_read`` / ``store.remote_write``;
  * :meth:`Dataset.prefetch` warms the LRU for a region with fetches
    fanned over a pool — the async-prefetch primitive the executor read
    stage issues ahead of compute (``runtime/executor.py``).
"""

from __future__ import annotations

import gzip
import io
import os
import struct
import threading
import urllib.parse
import zlib
from collections import OrderedDict
from itertools import product
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from .. import faults
from ..obs import metrics as obs_metrics
from .blocking import _ceil_div
from .retry import io_retry
from .store_backend import (  # noqa: F401  (re-exported API)
    CorruptChunk,
    HttpBackend,
    PosixBackend,
    StoreBackend,
    atomic_write_bytes,
    backend_for,
    is_remote_path,
)

try:  # h5py is available in the image, but keep it optional
    import h5py
except ImportError:  # pragma: no cover
    h5py = None

__all__ = [
    "file_reader", "File", "Dataset", "RaggedDataset", "CorruptChunk",
    "atomic_write_bytes", "backend_for", "is_remote_path",
]


# fsync opt-out mirrors store_backend (RaggedDataset writes .npy directly)
_FSYNC = os.environ.get("CTT_STORE_FSYNC", "1").lower() not in (
    "0", "false", "off", ""
)


# original (pre-ctt-fault) internal name, kept for callers/tests
_atomic_write_bytes = atomic_write_bytes


def _write_json(path: str, obj: Any) -> None:
    backend_for(path).write_json(path, obj)


def _exists(path: str) -> bool:
    return backend_for(path).exists(path)


def _gzip_compress(raw: bytes) -> bytes:
    """Deterministic gzip (level 1, mtime pinned to 0): by default
    ``gzip.compress`` stamps the wall clock into every member header, so
    two runs writing identical arrays produce different chunk *bytes* —
    which breaks byte-identity checks (chaos-vs-clean runs, content-
    addressed dedup) for no benefit.  Readers ignore the field."""
    return gzip.compress(raw, 1, mtime=0)


def _read_json(path: str) -> Any:
    return backend_for(path).read_json(path)


class _DecodedChunkCache:
    """Process-global LRU of decoded (uncompressed, full-shape) chunks.

    Halo'd block reads decode every shared chunk up to 2^ndim times per
    batch; the cache makes each decode happen once.  Entries are keyed by
    the chunk file path and carry the file's ``(inode, mtime_ns, size)``
    signature: a mismatch (another process rewrote the chunk — os.replace
    changes the inode) is a miss, so cross-process freshness degrades to a
    re-decode, never to stale data.  In-process writers invalidate
    explicitly (``write_chunk``).  Cached arrays are read-only views shared
    across readers; callers that hand out writable data copy on exit
    (``Dataset.read_chunk``).
    """

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Tuple[Any, np.ndarray]]" = OrderedDict()
        self._bytes = 0

    def get(self, path: str, sig) -> Optional[np.ndarray]:
        with self._lock:
            entry = self._entries.get(path)
            if entry is None or entry[0] != sig:
                return None
            self._entries.move_to_end(path)
            return entry[1]

    def put(self, path: str, sig, arr: np.ndarray) -> None:
        if arr.nbytes > self.max_bytes:
            return
        with self._lock:
            old = self._entries.pop(path, None)
            if old is not None:
                self._bytes -= old[1].nbytes
            self._entries[path] = (sig, arr)
            self._bytes += arr.nbytes
            while self._bytes > self.max_bytes and self._entries:
                _, (_, evicted) = self._entries.popitem(last=False)
                self._bytes -= evicted.nbytes

    def invalidate(self, path: str) -> None:
        with self._lock:
            old = self._entries.pop(path, None)
            if old is not None:
                self._bytes -= old[1].nbytes

    def peek(self, path: str) -> Optional[Tuple[Any, np.ndarray]]:
        """The ``(signature, array)`` entry regardless of freshness — the
        conditional-GET path (ctt-cloud) revalidates the signature on the
        wire (``If-None-Match``) instead of against a local probe."""
        with self._lock:
            return self._entries.get(path)

    def touch(self, path: str) -> None:
        with self._lock:
            if path in self._entries:
                self._entries.move_to_end(path)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0


def _chunk_cache_budget_bytes() -> int:
    """CTT_CHUNK_CACHE_MB (default 64, 0 disables); malformed values degrade
    to the default like every other CTT_* switch.  Read once at import."""
    raw = os.environ.get("CTT_CHUNK_CACHE_MB")
    try:
        mb = float(raw) if raw is not None else 64.0
    except (TypeError, ValueError):
        mb = 64.0
    return max(int(mb * 1024 * 1024), 0)


_CHUNK_CACHE = _DecodedChunkCache(_chunk_cache_budget_bytes())


def set_chunk_cache_budget(max_bytes: Optional[int]) -> int:
    """Override the decoded-chunk LRU budget in-process; returns the
    previous budget.  ``None`` restores the ``CTT_CHUNK_CACHE_MB``
    resolution; any change clears cached entries.  Store-traffic
    measurements (the ctt-stream bench/smoke) set 0 so ``store.bytes_read``
    reflects actual codec-boundary traffic instead of LRU luck."""
    prev = _CHUNK_CACHE.max_bytes
    _CHUNK_CACHE.max_bytes = (
        _chunk_cache_budget_bytes() if max_bytes is None else max(int(max_bytes), 0)
    )
    _CHUNK_CACHE.clear()
    return prev


def chunk_cache_budget() -> int:
    """The decoded-chunk LRU's current byte budget (introspection for the
    serve daemon / ExecutionContext; 0 means the cache is disabled)."""
    return _CHUNK_CACHE.max_bytes


class Attributes:
    """JSON-file-backed attribute mapping (``.zattrs`` / n5 ``attributes.json``)."""

    # n5 keeps array metadata and user attributes in the same file; these keys are
    # reserved by the format and hidden from the user view.
    _N5_RESERVED = ("dimensions", "blockSize", "dataType", "compression", "n5")

    def __init__(self, path: str, reserved: Sequence[str] = ()):
        self._path = path
        self._reserved = tuple(reserved)

    def _load(self) -> Dict[str, Any]:
        try:
            return _read_json(self._path)
        except FileNotFoundError:
            return {}

    def _store(self, obj: Dict[str, Any]) -> None:
        _write_json(self._path, obj)

    def __getitem__(self, key: str) -> Any:
        return self._load()[key]

    def __setitem__(self, key: str, value: Any) -> None:
        if key in self._reserved:
            raise KeyError(f"attribute key {key!r} is reserved")
        obj = self._load()
        obj[key] = value
        self._store(obj)

    def __contains__(self, key: str) -> bool:
        return key in self._load() and key not in self._reserved

    def get(self, key: str, default: Any = None) -> Any:
        return self._load().get(key, default)

    def update(self, other: Dict[str, Any]) -> None:
        obj = self._load()
        for k in other:
            if k in self._reserved:
                raise KeyError(f"attribute key {k!r} is reserved")
        obj.update(other)
        self._store(obj)

    def keys(self):
        return [k for k in self._load().keys() if k not in self._reserved]

    def asdict(self) -> Dict[str, Any]:
        return {k: v for k, v in self._load().items() if k not in self._reserved}


# ---------------------------------------------------------------------------
# format adapters
# ---------------------------------------------------------------------------


def _blosc_mod():
    from . import blosc

    return blosc


# internal compression spec: None | "zlib" | "gzip" | blosc dict
def _is_blosc(compression) -> bool:
    return isinstance(compression, dict) and compression.get("id") == "blosc"


def _clamp_chunks(chunks, shape):
    """Chunk dims never exceed the shape; zero-size dims keep the chunk
    (h5py/zarr both reject zero chunks) — the one clamp both the directory
    stores and the h5 façade apply."""
    return tuple(min(c, s) if s > 0 else c for c, s in zip(chunks, shape))


def default_compression():
    """The house codec for datasets the framework creates: blosc-lz4 when
    the system libblosc is present (6-30x faster than gzip-1 per chunk at
    equal-or-better ratios on label/boundary data — SURVEY.md §7 hard-part
    5 'blosc intermediates'), else gzip.  Explicit ``compression=`` values
    always win; the sentinel string ``"default"`` resolves here.

    ``CTT_DEFAULT_COMPRESSION`` pins the resolution (``gzip``/``blosc``)
    for deployments where codec availability varies across nodes — the
    scratch store's meta records whatever the CREATING node resolved, and
    a reading node without libblosc would fail loudly; on such mixed
    installs pin gzip."""
    pinned = os.environ.get("CTT_DEFAULT_COMPRESSION")
    if pinned in ("gzip", "blosc"):
        return pinned
    return "blosc" if _blosc_mod().available() else "gzip"


def _normalize_blosc(spec, itemsize: Optional[int] = None) -> dict:
    """Blosc spec with the ecosystem defaults (zarr-python: lz4, clevel 5,
    byte shuffle, auto blocksize) filled in; ``spec`` may be the string
    'blosc', a zarr compressor dict, or an n5 compression dict.

    ``shuffle`` from external metadata is validated into {0, 1, 2} here —
    at ``read_meta`` time — because numcodecs writes −1 (AUTOSHUFFLE),
    which READS fine (the frame header governs decompression) but would
    make any later write into such a dataset fail inside
    ``blosc_compress_ctx`` with a generic rc error (ADVICE r5).  −1 maps
    to what numcodecs' auto resolves to: byte shuffle for ``itemsize`` > 1,
    no shuffle for single-byte types."""
    src = spec if isinstance(spec, dict) else {}
    shuffle = int(src.get("shuffle", 1))
    if shuffle == -1:
        shuffle = 1 if (itemsize or 0) > 1 else 0
    if shuffle not in (0, 1, 2):
        raise ValueError(
            f"unsupported blosc shuffle {src.get('shuffle')!r} "
            "(supported: 0=none, 1=byte, 2=bit, -1=auto)"
        )
    return {
        "id": "blosc",
        "cname": src.get("cname", "lz4"),
        "clevel": int(src.get("clevel", 5)),
        "shuffle": shuffle,
        "blocksize": int(src.get("blocksize", 0)),
    }


class _ZarrFormat:
    """zarr v2 directory layout."""

    array_meta = ".zarray"
    group_meta = ".zgroup"
    attrs_file = ".zattrs"
    attrs_reserved: Tuple[str, ...] = ()

    @staticmethod
    def chunk_key(grid_pos: Sequence[int], separator: str = ".") -> str:
        return separator.join(str(p) for p in grid_pos)

    @staticmethod
    def write_meta(path: str, shape, chunks, dtype: np.dtype, compression) -> None:
        if compression is None:
            compressor = None
        elif _is_blosc(compression):
            compressor = {
                "id": "blosc",
                "cname": compression["cname"],
                "clevel": compression["clevel"],
                "shuffle": compression["shuffle"],
                "blocksize": compression["blocksize"],
            }
        else:
            compressor = {"id": "zlib", "level": 1}
        meta = {
            "zarr_format": 2,
            "shape": list(shape),
            "chunks": list(chunks),
            "dtype": dtype.str,
            "compressor": compressor,
            "fill_value": 0,
            "order": "C",
            "filters": None,
            "dimension_separator": ".",
        }
        _write_json(os.path.join(path, _ZarrFormat.array_meta), meta)

    @staticmethod
    def read_meta(path: str):
        meta = _read_json(os.path.join(path, _ZarrFormat.array_meta))
        comp = meta.get("compressor")
        if comp is None:
            compression = None
        elif comp.get("id") in ("zlib", "gzip"):
            compression = comp["id"]
        elif comp.get("id") == "blosc":
            compression = _normalize_blosc(
                comp, itemsize=np.dtype(meta["dtype"]).itemsize
            )
        else:
            raise ValueError(
                f"unsupported zarr compressor {comp.get('id')!r} in {path} "
                "(supported: null, zlib, gzip, blosc)"
            )
        if meta.get("filters"):
            raise ValueError(f"zarr filters are not supported ({path})")
        if meta.get("order", "C") != "C":
            raise ValueError(f"only C-order zarr arrays are supported ({path})")
        fill = meta.get("fill_value", 0)
        fill = 0 if fill is None else fill
        return {
            "shape": tuple(meta["shape"]),
            "chunks": tuple(meta["chunks"]),
            "dtype": np.dtype(meta["dtype"]),
            "compression": compression,
            "separator": meta.get("dimension_separator", "."),
            "fill_value": fill,
        }

    @staticmethod
    def encode_chunk(data: np.ndarray, chunks, compression) -> bytes:
        # zarr v2 stores edge chunks at full chunk shape, padded with fill_value
        if tuple(data.shape) != tuple(chunks):
            full = np.zeros(chunks, dtype=data.dtype)
            full[tuple(slice(0, s) for s in data.shape)] = data
            data = full
        raw = np.ascontiguousarray(data).tobytes()
        if _is_blosc(compression):
            return _blosc_mod().compress(
                raw, data.dtype.itemsize, cname=compression["cname"],
                clevel=compression["clevel"], shuffle=compression["shuffle"],
                blocksize=compression["blocksize"],
            )
        if compression == "gzip":
            return _gzip_compress(raw)
        return zlib.compress(raw, 1) if compression else raw

    @staticmethod
    def decode_chunk(payload: bytes, chunk_shape, dtype: np.dtype, compression):
        if _is_blosc(compression):
            # bound the decode allocation by what the chunk may legitimately
            # hold — a forged header cannot trigger a multi-GB buffer
            payload = _blosc_mod().decompress(
                payload,
                expected_nbytes=int(np.prod(chunk_shape)) * dtype.itemsize,
            )
        elif compression == "gzip":
            payload = gzip.decompress(payload)
        elif compression:
            payload = zlib.decompress(payload)
        full = np.frombuffer(payload, dtype=dtype).reshape(-1)
        # stored shape is always the full chunk shape; caller crops edge chunks
        return full

    @staticmethod
    def is_array(path: str) -> bool:
        return _exists(
            backend_for(path).join(path, _ZarrFormat.array_meta)
        )

    @staticmethod
    def init_group(path: str) -> None:
        _write_json(
            backend_for(path).join(path, _ZarrFormat.group_meta),
            {"zarr_format": 2},
        )


class _N5Format:
    """n5 directory layout: reversed dims, big-endian mode-0 chunks, ``i/j/k`` keys."""

    array_meta = "attributes.json"
    group_meta = "attributes.json"
    attrs_file = "attributes.json"
    attrs_reserved = Attributes._N5_RESERVED

    _DTYPES = {
        "uint8": "|u1", "uint16": ">u2", "uint32": ">u4", "uint64": ">u8",
        "int8": "|i1", "int16": ">i2", "int32": ">i4", "int64": ">i8",
        "float32": ">f4", "float64": ">f8",
    }

    @staticmethod
    def chunk_key(grid_pos: Sequence[int], separator: str = "/") -> str:
        return os.path.join(*[str(p) for p in reversed(tuple(grid_pos))])

    @staticmethod
    def write_meta(path: str, shape, chunks, dtype: np.dtype, compression) -> None:
        meta_path = backend_for(path).join(path, _N5Format.array_meta)
        meta = _read_json(meta_path) if _exists(meta_path) else {}
        if compression is None:
            n5_comp = {"type": "raw"}
        elif _is_blosc(compression):
            n5_comp = {
                "type": "blosc",
                "cname": compression["cname"],
                "clevel": compression["clevel"],
                "shuffle": compression["shuffle"],
                "blocksize": compression["blocksize"],
                "nthreads": 1,
            }
        else:
            n5_comp = {"type": "gzip", "level": 1}
        meta.update(
            {
                "dimensions": list(reversed(shape)),
                "blockSize": list(reversed(chunks)),
                "dataType": dtype.name,
                "compression": n5_comp,
            }
        )
        _write_json(meta_path, meta)

    @staticmethod
    def read_meta(path: str):
        meta = _read_json(os.path.join(path, _N5Format.array_meta))
        n5_comp = meta.get("compression", {"type": "raw"})
        ctype = n5_comp["type"]
        if ctype not in ("raw", "gzip", "blosc"):
            raise ValueError(f"unsupported n5 compression {ctype!r} in {path}")
        if ctype == "raw":
            compression = None
        elif ctype == "blosc":
            compression = _normalize_blosc(
                n5_comp, itemsize=np.dtype(meta["dataType"]).itemsize
            )
        else:
            compression = "gzip"
        return {
            "shape": tuple(reversed(meta["dimensions"])),
            "chunks": tuple(reversed(meta["blockSize"])),
            "dtype": np.dtype(meta["dataType"]),
            "compression": compression,
            "separator": "/",
            "fill_value": 0,
        }

    @staticmethod
    def pack_chunk(data: np.ndarray, dims, compression, n_varlen=None) -> bytes:
        """Shared chunk wire format: mode-0 (default) or mode-1 (varlength,
        ``n_varlen`` = element count) header + big-endian payload."""
        be = data.astype(_N5Format._DTYPES[data.dtype.name], copy=False)
        mode = 0 if n_varlen is None else 1
        header = struct.pack(">HH", mode, len(dims)) + struct.pack(
            f">{len(dims)}I", *reversed(tuple(dims))
        )
        if n_varlen is not None:
            header += struct.pack(">I", n_varlen)
        raw = np.ascontiguousarray(be).tobytes()
        if _is_blosc(compression):
            raw = _blosc_mod().compress(
                raw, be.dtype.itemsize, cname=compression["cname"],
                clevel=compression["clevel"], shuffle=compression["shuffle"],
                blocksize=compression["blocksize"],
            )
        elif compression:
            raw = _gzip_compress(raw)
        return header + raw

    @staticmethod
    def encode_chunk(data: np.ndarray, chunks, compression) -> bytes:
        # header: mode(0), ndim, then per-dim sizes in n5 (reversed) order, all BE.
        # numpy C-order bytes are already "first n5 dim fastest".
        return _N5Format.pack_chunk(data, data.shape, compression)

    @staticmethod
    def decode_chunk(payload: bytes, chunk_shape, dtype: np.dtype, compression):
        mode, ndim = struct.unpack(">HH", payload[:4])
        dims = struct.unpack(f">{ndim}I", payload[4 : 4 + 4 * ndim])
        offset = 4 + 4 * ndim
        if mode == 1:  # varlength mode carries an extra element count
            offset += 4
        raw = payload[offset:]
        if _is_blosc(compression):
            # n5 stores clipped edge chunks, so the full chunk size is an
            # upper bound on any legitimate decode (see _ZarrFormat)
            raw = _blosc_mod().decompress(
                raw,
                expected_nbytes=int(np.prod(chunk_shape)) * dtype.itemsize,
            )
        elif compression:
            raw = gzip.decompress(raw)
        be_dtype = np.dtype(_N5Format._DTYPES[dtype.name])
        arr = np.frombuffer(raw, dtype=be_dtype).astype(dtype)
        shape = tuple(reversed(dims))
        full = np.zeros(chunk_shape, dtype=dtype).reshape(-1)
        if shape == tuple(chunk_shape):
            full = arr
        else:  # n5 stores clipped edge chunks; pad to full chunk for the caller
            tmp = np.zeros(chunk_shape, dtype=dtype)
            tmp[tuple(slice(0, s) for s in shape)] = arr.reshape(shape)
            full = tmp.reshape(-1)
        return full

    @staticmethod
    def is_array(path: str) -> bool:
        meta_path = backend_for(path).join(path, _N5Format.array_meta)
        if not _exists(meta_path):
            return False
        return "dimensions" in _read_json(meta_path)

    @staticmethod
    def init_group(path: str) -> None:
        meta_path = backend_for(path).join(path, _N5Format.group_meta)
        if not _exists(meta_path):
            _write_json(meta_path, {"n5": "2.0.0"})


def _format_for(path: str):
    # a remote path is a URL: the container extension lives on the URL
    # path component (query/fragment stripped)
    name = path
    if is_remote_path(path):
        name = urllib.parse.urlsplit(path).path
    ext = os.path.splitext(name.rstrip("/"))[1].lower()
    if ext in (".zarr", ".zr"):
        return _ZarrFormat
    if ext == ".n5":
        return _N5Format
    raise ValueError(f"unsupported container extension: {path}")


# ---------------------------------------------------------------------------
# dataset / group / file
# ---------------------------------------------------------------------------


class Dataset:
    def __init__(self, path: str, fmt, readonly: bool = False):
        self.path = path
        self._fmt = fmt
        self._readonly = readonly
        self._backend = backend_for(path)
        spec = fmt.read_meta(path)
        self.shape = spec["shape"]
        self.chunks = spec["chunks"]
        self.dtype = spec["dtype"]
        self.compression = spec["compression"]
        self.fill_value = spec["fill_value"]
        self._separator = spec["separator"]
        # remote datasets default to the wide fan-out (high-RTT range
        # reads want request overlap); posix keeps the serial default —
        # ``set_read_threads`` / ``ds.n_threads = n`` override either way
        self.n_threads = self._backend.default_threads
        self.attrs = Attributes(
            self._backend.join(path, fmt.attrs_file),
            reserved=fmt.attrs_reserved,
        )

    # -- basic properties ----------------------------------------------------

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape))

    @property
    def chunk_grid(self) -> Tuple[int, ...]:
        return tuple(_ceil_div(s, c) for s, c in zip(self.shape, self.chunks))

    # -- chunk level ---------------------------------------------------------

    def _chunk_path(self, grid_pos: Sequence[int]) -> str:
        return self._backend.join(
            self.path, self._fmt.chunk_key(grid_pos, self._separator)
        )

    def _chunk_extent(self, grid_pos: Sequence[int]) -> Tuple[Tuple[int, int], ...]:
        return tuple(
            (g * c, min(g * c + c, s))
            for g, c, s in zip(grid_pos, self.chunks, self.shape)
        )

    def _decode_classified(self, p: str, payload: bytes) -> np.ndarray:
        """Decode one chunk payload at full chunk shape, classifying every
        decode failure as :class:`CorruptChunk` (retryable torn-write
        evidence) — codec errors on bytes that DID read are corruption,
        not programming errors."""
        try:
            faults.check("store.decode", path=p)
            flat = self._fmt.decode_chunk(
                payload, self.chunks, self.dtype, self.compression
            )
            return flat.reshape(self.chunks)
        except FileNotFoundError:
            raise
        except (ValueError, struct.error, zlib.error, EOFError,
                RuntimeError, OSError) as e:
            raise CorruptChunk(
                f"chunk {p} failed to decode "
                f"({len(payload)} payload bytes): {e}"
            ) from e

    def _decoded_chunk(self, grid_pos: Sequence[int]) -> Optional[np.ndarray]:
        """One chunk decoded at FULL chunk shape (edge chunks zero-padded),
        read-only, through the process-global decoded-chunk LRU.  Returns
        None if the chunk is unwritten.  The signature → read window is
        benign: a concurrent rewrite can at worst cache fresh content under
        the old signature, which the next reader's probe turns into a miss.
        Remote datasets revalidate over the wire instead of a separate
        HEAD probe: ONE conditional GET (``If-None-Match`` on the cached
        ETag) either returns 304 — the warm hit, zero body bytes — or the
        fresh payload plus its new signature, so both the warm and the
        cold path cost exactly one round trip (the HEAD that used to
        precede every GET is folded in — ctt-cloud follow-up)."""
        p = self._chunk_path(grid_pos)
        backend = self._backend
        if backend.is_remote and _CHUNK_CACHE.max_bytes > 0:
            return self._decoded_chunk_remote(p, backend)
        sig = None
        if _CHUNK_CACHE.max_bytes > 0:
            try:
                sig = io_retry(
                    lambda: backend.signature(p),
                    what=f"stat chunk {p}", counter=backend.retry_counter,
                )
            except FileNotFoundError:
                return None
            hit = _CHUNK_CACHE.get(p, sig)
            if hit is not None:
                obs_metrics.inc("store.chunk_cache_hits")
                return hit
        def _load() -> np.ndarray:
            faults.check("store.read", path=p)
            payload = backend.read_bytes(p)
            # obs counters at the codec boundary: what actually crossed the
            # filesystem (compressed payload bytes), not the decoded size
            obs_metrics.inc("store.chunks_read")
            obs_metrics.inc("store.bytes_read", len(payload))
            return self._decode_classified(p, payload)

        try:
            # transient OSError / torn-chunk decode retries with backoff;
            # a missing chunk (FileNotFoundError) is normal and final
            full = io_retry(
                _load, what=f"read chunk {p}", counter=backend.retry_counter
            )
        except FileNotFoundError:
            return None
        full.setflags(write=False)  # shared across cache readers
        if sig is not None:
            obs_metrics.inc("store.chunk_cache_misses")
            _CHUNK_CACHE.put(p, sig, full)
        return full

    def _decoded_chunk_remote(self, p: str, backend) -> Optional[np.ndarray]:
        """Remote chunk read through the LRU with wire revalidation: the
        cached entry's ETag rides an ``If-None-Match`` conditional GET —
        304 is the warm hit (one round trip, no body), anything else is
        the fresh payload WITH its signature (no separate HEAD even on
        the cold path)."""
        entry = _CHUNK_CACHE.peek(p)
        etag = entry[0][0] if entry is not None and entry[0] else None

        def _load():
            faults.check("store.read", path=p)
            payload, sig = backend.read_bytes_versioned(p, etag)
            if payload is None:
                return None, sig  # 304: cached bytes still current
            obs_metrics.inc("store.chunks_read")
            obs_metrics.inc("store.bytes_read", len(payload))
            return self._decode_classified(p, payload), sig

        try:
            full, sig = io_retry(
                _load, what=f"read chunk {p}", counter=backend.retry_counter
            )
        except FileNotFoundError:
            _CHUNK_CACHE.invalidate(p)
            return None
        if full is None:
            obs_metrics.inc("store.chunk_cache_hits")
            _CHUNK_CACHE.touch(p)
            return entry[1]
        full.setflags(write=False)
        obs_metrics.inc("store.chunk_cache_misses")
        _CHUNK_CACHE.put(p, sig, full)
        return full

    def region_signature(self, bb) -> Optional[tuple]:
        """Per-chunk freshness signatures of every chunk overlapping
        ``bb`` — the device-buffer cache's (ctt-hbm) invalidation key,
        riding the exact signatures the decoded-chunk LRU uses (POSIX
        ``(inode, mtime_ns, size)``, remote ``(ETag, Last-Modified,
        Content-Length)``).  Unwritten chunks sign as None (they read as
        fill_value — also content); a transient probe error returns None
        for the whole region, which callers treat as "uncacheable this
        round", never as a match."""
        bb, _ = self._normalize_bb(bb)
        positions = list(self._chunks_overlapping(bb))

        def _one(grid_pos):
            p = self._chunk_path(grid_pos)
            try:
                return self._backend.signature(p)
            except FileNotFoundError:
                return None

        try:
            sigs = self._backend.map(
                _one, positions, getattr(self, "n_threads", 1)
            )
        except OSError:
            return None
        return tuple(sigs)

    def prefetch(self, bb, n_threads: Optional[int] = None) -> int:
        """Warm the decoded-chunk LRU with every chunk overlapping ``bb``,
        fetches fanned over a thread pool — the async-prefetch primitive
        (ctt-cloud): the executor read stage issues these AHEAD of the
        in-order compute stage, so high-latency range reads overlap device
        programs instead of blocking one read thread per slice.  Advisory
        by contract: per-chunk failures are swallowed (the real read
        re-raises and classifies), and nothing happens when the LRU is
        disabled (nothing could be retained).  Returns the chunk count
        submitted."""
        if _CHUNK_CACHE.max_bytes <= 0:
            return 0
        bb, _ = self._normalize_bb(bb)
        positions = list(self._chunks_overlapping(bb))
        if not positions:
            return 0

        def _warm(grid_pos) -> None:
            try:
                self._decoded_chunk(grid_pos)
            except Exception:  # ctt: noqa[CTT009] prefetch is advisory — the real read retries and classifies this chunk's failure loudly
                pass

        n = int(n_threads or getattr(self, "n_threads", 1) or 1)
        self._backend.map(_warm, positions, n)
        return len(positions)

    def read_chunk(self, grid_pos: Sequence[int]) -> Optional[np.ndarray]:
        """Read one chunk (cropped to the volume at edges), or None if unwritten."""
        full = self._decoded_chunk(grid_pos)
        if full is None:
            return None
        extent = self._chunk_extent(grid_pos)
        crop = tuple(slice(0, e - b) for b, e in extent)
        return full[crop].copy()  # cached/frombuffer arrays are read-only

    def write_chunk(self, grid_pos: Sequence[int], data: np.ndarray) -> None:
        if self._readonly:
            raise PermissionError(f"dataset opened read-only: {self.path}")
        extent = self._chunk_extent(grid_pos)
        expected = tuple(e - b for b, e in extent)
        if tuple(data.shape) != expected:
            raise ValueError(
                f"chunk {tuple(grid_pos)} expects shape {expected}, got {data.shape}"
            )
        p = self._chunk_path(grid_pos)
        self._backend.makedirs(self._backend.dirname(p))
        payload = self._fmt.encode_chunk(
            np.asarray(data, dtype=self.dtype), self.chunks, self.compression
        )
        self._commit_chunk_payload(p, payload)

    def _commit_chunk_payload(self, p: str, payload: bytes) -> None:
        """Write one encoded chunk payload under the shared IO retry.
        The ``store.write`` fault site raises transient errors here; the
        ``torn`` action truncates the payload on disk and raises
        CorruptChunk, so the retry (or, once exhausted, block retry)
        rewrites the full payload — a tear heals instead of poisoning
        later reads."""

        def _commit() -> None:
            faults.check("store.write", path=p)
            torn = faults.mangle("store.write", payload, path=p)
            obs_metrics.inc("store.chunks_written")
            obs_metrics.inc("store.bytes_written", len(payload))
            self._backend.write_bytes(p, payload if torn is None else torn)
            if torn is not None:
                raise CorruptChunk(
                    f"torn write injected for {p} "
                    f"({len(torn)}/{len(payload)} bytes)"
                )

        try:
            io_retry(
                _commit, what=f"write chunk {p}",
                counter=self._backend.retry_counter,
            )
        finally:
            _CHUNK_CACHE.invalidate(p)

    def write_chunk_varlen(self, grid_pos: Sequence[int], data: np.ndarray) -> None:
        """Write an arbitrary-length 1d payload as an n5 mode-1 (varlength)
        chunk — the reference's ``write_chunk(..., varlen=True)`` used for
        label multisets and graph serializations."""
        if self._readonly:
            raise PermissionError(f"dataset opened read-only: {self.path}")
        if self._fmt is not _N5Format:
            raise NotImplementedError("varlength chunks are n5-only")
        data = np.ascontiguousarray(data, dtype=self.dtype)
        payload = _N5Format.pack_chunk(
            data, self.chunks, self.compression, n_varlen=data.size
        )
        p = self._chunk_path(grid_pos)
        self._backend.makedirs(self._backend.dirname(p))
        self._commit_chunk_payload(p, payload)

    def read_chunk_varlen(self, grid_pos: Sequence[int]) -> Optional[np.ndarray]:
        """Read a mode-1 (varlength) chunk as a flat array, or None."""
        if self._fmt is not _N5Format:
            raise NotImplementedError("varlength chunks are n5-only")
        p = self._chunk_path(grid_pos)

        def _load() -> np.ndarray:
            faults.check("store.read", path=p)
            payload = self._backend.read_bytes(p)
            obs_metrics.inc("store.chunks_read")
            obs_metrics.inc("store.bytes_read", len(payload))
            try:
                faults.check("store.decode", path=p)
                mode, ndim = struct.unpack(">HH", payload[:4])
                if mode != 1:
                    raise ValueError(
                        f"chunk {tuple(grid_pos)} is not varlength"
                    )
                offset = 4 + 4 * ndim
                (n_elements,) = struct.unpack(
                    ">I", payload[offset : offset + 4]
                )
                raw = payload[offset + 4 :]
                if _is_blosc(self.compression):
                    raw = _blosc_mod().decompress(raw)
                elif self.compression:
                    raw = gzip.decompress(raw)
                be_dtype = np.dtype(_N5Format._DTYPES[self.dtype.name])
                out = np.frombuffer(raw, dtype=be_dtype)
                if out.size < n_elements:
                    raise ValueError(
                        f"payload holds {out.size} elements, "
                        f"header promises {n_elements}"
                    )
                return out[:n_elements].astype(self.dtype)
            except FileNotFoundError:
                raise
            except (ValueError, struct.error, zlib.error, EOFError,
                    RuntimeError, OSError) as e:
                raise CorruptChunk(
                    f"varlen chunk {p} failed to decode "
                    f"({len(payload)} payload bytes): {e}"
                ) from e

        try:
            return io_retry(
                _load, what=f"read varlen chunk {p}",
                counter=self._backend.retry_counter,
            )
        except FileNotFoundError:
            return None

    # -- region level --------------------------------------------------------

    def _normalize_bb(self, bb) -> Tuple[Tuple[Tuple[int, int], ...], Tuple[int, ...]]:
        """Returns the per-axis (begin, end) bounds plus the axes indexed by a
        plain int (those are dropped from read results, matching h5py/zarr)."""
        if not isinstance(bb, tuple):
            bb = (bb,)
        if Ellipsis in bb:
            i = bb.index(Ellipsis)
            fill = self.ndim - (len(bb) - 1)
            bb = bb[:i] + (slice(None),) * fill + bb[i + 1 :]
        bb = bb + (slice(None),) * (self.ndim - len(bb))
        out = []
        int_axes = []
        for axis, (sl, s) in enumerate(zip(bb, self.shape)):
            if isinstance(sl, (int, np.integer)):
                idx = int(sl) + s if sl < 0 else int(sl)
                if not 0 <= idx < s:
                    raise IndexError(f"index {sl} out of range for axis {axis} ({s})")
                int_axes.append(axis)
                sl = slice(idx, idx + 1)
            start = 0 if sl.start is None else (sl.start if sl.start >= 0 else s + sl.start)
            stop = s if sl.stop is None else (sl.stop if sl.stop >= 0 else s + sl.stop)
            if sl.step not in (None, 1):
                raise ValueError("strided access is not supported")
            out.append((max(0, start), min(s, stop)))
        return tuple(out), tuple(int_axes)

    def _chunks_overlapping(self, bb):
        ranges = [
            range(b // c, _ceil_div(e, c) if e > b else b // c + 1)
            for (b, e), c in zip(bb, self.chunks)
        ]
        return product(*ranges)

    def __getitem__(self, bb) -> np.ndarray:
        bb, int_axes = self._normalize_bb(bb)
        out_shape = tuple(e - b for b, e in bb)
        out = np.full(out_shape, self.fill_value, dtype=self.dtype)

        def _assemble(grid_pos):
            # full decoded chunk via the LRU: overlapping halo reads of
            # neighboring blocks decode each shared chunk once, and no
            # per-chunk crop copy is made on the assembly path
            chunk = self._decoded_chunk(grid_pos)
            if chunk is None:
                return
            extent = self._chunk_extent(grid_pos)
            # intersection of chunk extent and requested bb, in both frames
            lo = [max(cb, rb) for (cb, _), (rb, _) in zip(extent, bb)]
            hi = [min(ce, re) for (_, ce), (_, re) in zip(extent, bb)]
            if any(l >= h for l, h in zip(lo, hi)):
                return
            src = tuple(
                slice(l - cb, h - cb) for l, h, (cb, _) in zip(lo, hi, extent)
            )
            dst = tuple(slice(l - rb, h - rb) for l, h, (rb, _) in zip(lo, hi, bb))
            out[dst] = chunk[src]  # disjoint regions: thread-safe

        positions = list(self._chunks_overlapping(bb))
        n_threads = int(getattr(self, "n_threads", 1) or 1)
        # the reference's ``ds.n_threads = n`` idiom (z5py datasets): file
        # IO and zlib/gzip decompression release the GIL, so the fan-out
        # overlaps chunk decode even on few cores; remote backends run it
        # on their persistent pool (keep-alive connection reuse)
        self._backend.map(_assemble, positions, n_threads)
        if int_axes:
            out = out.reshape(
                tuple(s for ax, s in enumerate(out_shape) if ax not in int_axes)
            )
        return out

    def __setitem__(self, bb, value) -> None:
        if self._readonly:
            raise PermissionError(f"dataset opened read-only: {self.path}")
        bb, _ = self._normalize_bb(bb)
        region_shape = tuple(e - b for b, e in bb)
        value = np.asarray(value, dtype=self.dtype)
        value = np.broadcast_to(value, region_shape)

        def _write_one(grid_pos):
            extent = self._chunk_extent(grid_pos)
            lo = [max(cb, rb) for (cb, _), (rb, _) in zip(extent, bb)]
            hi = [min(ce, re) for (_, ce), (_, re) in zip(extent, bb)]
            if any(l >= h for l, h in zip(lo, hi)):
                return
            src = tuple(slice(l - rb, h - rb) for l, h, (rb, _) in zip(lo, hi, bb))
            covers_fully = all(
                l == cb and h == ce
                for l, h, (cb, ce) in zip(lo, hi, extent)
            )
            if covers_fully:
                # chunk-aligned fast path: encode straight from the region
                # view — no intermediate chunk buffer and, for partially
                # written datasets, no RMW read+decode
                obs_metrics.inc("store.aligned_chunk_writes")
                self.write_chunk(grid_pos, value[src])
                return
            chunk_shape = tuple(ce - cb for cb, ce in extent)
            # read-modify-write for partially covered chunks
            chunk = self.read_chunk(grid_pos)
            if chunk is None:
                chunk = np.zeros(chunk_shape, dtype=self.dtype)
            dst = tuple(slice(l - cb, h - cb) for l, h, (cb, _) in zip(lo, hi, extent))
            chunk[dst] = value[src]
            self.write_chunk(grid_pos, chunk)

        positions = list(self._chunks_overlapping(bb))
        n_threads = int(getattr(self, "n_threads", 1) or 1)
        # mirror of the read fan-out: each grid position is a distinct
        # chunk file, so the per-chunk encode+replace jobs are disjoint
        # ("parallel multipart-style" chunk PUTs on the remote backend)
        self._backend.map(_write_one, positions, n_threads)

    def __repr__(self) -> str:
        return f"Dataset({self.path!r}, shape={self.shape}, chunks={self.chunks}, dtype={self.dtype})"


class RaggedDataset:
    """Variable-length per-chunk storage over a block grid.

    The TPU-native stand-in for the reference's n5 varlen chunks
    (reference graph/initial_sub_graphs.py:129, multicut/solve_subproblems.py):
    each grid position holds one 1d array of arbitrary length, serialized as ``.npy``.
    """

    META = ".ragged.json"

    def __init__(self, path: str):
        # ctt-diskless: ragged scratch may live on an object-store prefix
        # — chunks serialize through an in-memory .npy buffer and ride
        # backend PUTs/GETs (oversized chunks take the multipart path)
        self._backend = backend_for(path)
        self.path = path
        meta = _read_json(self._backend.join(path, self.META))
        self.grid_shape = tuple(meta["grid_shape"])
        self.dtype = np.dtype(meta["dtype"])
        self.attrs = Attributes(self._backend.join(path, ".zattrs"))

    @classmethod
    def create(cls, path: str, grid_shape: Sequence[int], dtype) -> "RaggedDataset":
        backend = backend_for(path)
        backend.makedirs(path)
        _write_json(
            backend.join(path, cls.META),
            {"grid_shape": list(grid_shape), "dtype": np.dtype(dtype).str},
        )
        return cls(path)

    @classmethod
    def exists(cls, path: str) -> bool:
        backend = backend_for(path)
        return backend.exists(backend.join(path, cls.META))

    def _chunk_path(self, grid_pos) -> str:
        if isinstance(grid_pos, (int, np.integer)):
            grid_pos = np.unravel_index(int(grid_pos), self.grid_shape)
        return self._backend.join(
            self.path, ".".join(str(p) for p in grid_pos) + ".npy"
        )

    def read_chunk(self, grid_pos) -> Optional[np.ndarray]:
        p = self._chunk_path(grid_pos)
        try:
            raw = self._backend.read_bytes(p)
        except FileNotFoundError:
            return None
        return np.load(io.BytesIO(raw), allow_pickle=False)

    def write_chunk(self, grid_pos, data: np.ndarray) -> None:
        p = self._chunk_path(grid_pos)
        buf = io.BytesIO()
        np.save(buf, np.asarray(data, dtype=self.dtype))
        # backend write: atomic tmp+replace on POSIX (fsync per _FSYNC),
        # single PUT — or multipart above the threshold — on a store
        self._backend.write_bytes(p, buf.getvalue())


class Group:
    def __init__(self, root: str, fmt, rel: str = "", readonly: bool = False):
        self._root = root
        self._fmt = fmt
        self._rel = rel
        self._readonly = readonly
        self._backend = backend_for(root)
        self.path = self._backend.join(root, rel) if rel else root
        if not readonly:
            self._backend.makedirs(self.path)
            fmt.init_group(self.path)
        # groups keep the structural keys guarded (writing "dimensions" into a
        # group's attributes.json would make is_array misclassify it) but allow
        # "dataType", which n5 GROUP attrs legitimately carry (bdv setup meta)
        group_reserved = tuple(
            k for k in fmt.attrs_reserved if k != "dataType"
        )
        self.attrs = Attributes(
            self._backend.join(self.path, fmt.attrs_file),
            reserved=group_reserved,
        )

    # -- navigation ----------------------------------------------------------

    def __contains__(self, key: str) -> bool:
        return self._backend.isdir(self._backend.join(self.path, key))

    def __getitem__(self, key: str):
        p = self._backend.join(self.path, key)
        if not self._backend.isdir(p):
            raise KeyError(key)
        if self._fmt.is_array(p):
            return Dataset(p, self._fmt, readonly=self._readonly)
        if RaggedDataset.exists(p):
            return RaggedDataset(p)
        rel = os.path.join(self._rel, key) if self._rel else key
        return Group(self._root, self._fmt, rel, readonly=self._readonly)

    def require_group(self, key: str) -> "Group":
        rel = os.path.join(self._rel, key) if self._rel else key
        if self._readonly and not self._backend.isdir(
            self._backend.join(self.path, key)
        ):
            raise PermissionError(f"container opened read-only: {self.path}")
        return Group(self._root, self._fmt, rel, readonly=self._readonly)

    create_group = require_group

    def keys(self):
        return [
            k
            for k in self._backend.listdir(self.path)
            if self._backend.isdir(self._backend.join(self.path, k))
        ]

    # -- dataset creation ----------------------------------------------------

    def create_dataset(
        self,
        key: str,
        shape: Optional[Sequence[int]] = None,
        dtype=None,
        chunks: Optional[Sequence[int]] = None,
        compression: Optional[str] = "default",
        data: Optional[np.ndarray] = None,
        exist_ok: bool = False,
    ) -> Dataset:
        if self._readonly:
            raise PermissionError(f"container opened read-only: {self.path}")
        if data is not None:
            data = np.asarray(data)
            shape = data.shape if shape is None else shape
            dtype = data.dtype if dtype is None else dtype
        if shape is None or dtype is None:
            raise ValueError("shape and dtype (or data) are required")
        if chunks is None:
            chunks = tuple(min(s, 64) for s in shape)
        chunks = _clamp_chunks(chunks, shape)
        # normalize/validate the compression spec BEFORE any destructive
        # step: the exist_ok overwrite below rmtree's the old array, and a
        # late failure (e.g. missing libblosc) must not have deleted data
        if compression == "default":
            compression = default_compression()
        if compression == "blosc" or _is_blosc(compression):
            compression = _normalize_blosc(
                compression, itemsize=np.dtype(dtype).itemsize
            )
            if not _blosc_mod().available():
                raise RuntimeError(
                    "compression='blosc' requires the system libblosc"
                )
        elif compression not in (None, "raw", "gzip"):
            compression = "gzip"
        if compression == "raw":
            compression = None
        p = self._backend.join(self.path, key)
        if self._fmt.is_array(p):
            if not exist_ok:
                raise ValueError(f"dataset exists: {p}")
            if data is None:
                return Dataset(p, self._fmt)
            # overwrite semantics: a rerun that brings new data must not
            # silently keep the stale array (shape/width may have changed —
            # e.g. merge_edge_features after a quantile_mode switch)
            self._backend.rmtree(p)
        # intermediate groups
        parts = key.split("/")
        grp = self
        for part in parts[:-1]:
            grp = grp.require_group(part)
        dpath = self._backend.join(grp.path, parts[-1])
        self._backend.makedirs(dpath)
        self._fmt.write_meta(dpath, tuple(shape), tuple(chunks), np.dtype(dtype), compression)
        ds = Dataset(dpath, self._fmt)
        if data is not None:
            ds[tuple(slice(0, s) for s in shape)] = data
        return ds

    def require_dataset(self, key: str, shape=None, dtype=None, chunks=None,
                        compression="default") -> Dataset:
        p = self._backend.join(self.path, key)
        if self._fmt.is_array(p):
            ds = Dataset(p, self._fmt)
            if shape is not None and tuple(shape) != ds.shape:
                raise ValueError(f"shape mismatch for {p}: {shape} vs {ds.shape}")
            return ds
        return self.create_dataset(key, shape=shape, dtype=dtype, chunks=chunks,
                                   compression=compression)

    def create_ragged_dataset(
        self, key: str, grid_shape: Sequence[int], dtype
    ) -> RaggedDataset:
        if self._readonly:
            raise PermissionError(f"container opened read-only: {self.path}")
        p = self._backend.join(self.path, key)
        if RaggedDataset.exists(p):
            return RaggedDataset(p)
        return RaggedDataset.create(p, grid_shape, dtype)


class File(Group):
    """Root of a zarr/n5 container.  Context-manager compatible with h5py.File."""

    def __init__(self, path: str, mode: str = "a"):
        fmt = _format_for(path)
        if mode == "r" and not backend_for(path).isdir(path):
            raise FileNotFoundError(path)
        super().__init__(path, fmt, readonly=(mode == "r"))
        self.mode = mode

    def __enter__(self) -> "File":
        return self

    def __exit__(self, *exc) -> None:
        pass

    def close(self) -> None:
        pass


_H5_HANDLES: Dict[str, Any] = {}
# façade open-count per path (ADVICE r3): `with file_reader(...)` really
# closes the cached handle on the LAST façade close, releasing the HDF5
# file lock; handles opened without close() stay cached process-wide
_H5_REFS: Dict[str, int] = {}
# RLock: dataset proxies re-enter via _h5_open when lazily reopening
_H5_LOCK = threading.RLock()


def _h5_cached_handle(key: str):
    """Raw cached read handle for proxy re-resolution — does NOT touch the
    refcount (nobody will close a proxy's implicit reopen)."""
    cached = _H5_HANDLES.get(key)
    if cached is None or not bool(cached):
        cached = h5py.File(key, "r")
        _H5_HANDLES[key] = cached
    return cached


class _H5DatasetProxy:
    """Dataset handle that re-resolves through the process handle cache on
    every access, so a read-only→writable reopen of the owning file cannot
    leave the caller with an invalidated HDF5 id.  Every access happens
    under the cache lock: a concurrent upgrade/release cannot close the
    handle between resolution and use (h5py serializes globally anyway, so
    the lock costs no read parallelism)."""

    _is_hdf5 = True  # read_block_batch keys its single-thread guard on this

    def __init__(self, path: str, name: str):
        self._path = path
        self._name = name

    def _ds(self):
        # the cached handle may have been released (e.g. before worker
        # spawn or by the last façade close): reopen read-only — a proxy
        # is only handed out for reads
        return _h5_cached_handle(self._path)[self._name]

    def __getitem__(self, key):
        with _H5_LOCK:
            return self._ds()[key]

    def __setitem__(self, key, value):
        with _H5_LOCK:
            self._ds()[key] = value

    def __getattr__(self, name):
        with _H5_LOCK:
            return getattr(self._ds(), name)

    def __len__(self):
        with _H5_LOCK:
            return len(self._ds())


class _CachedH5File:
    """Non-closing façade over a process-cached h5py.File.

    HDF5 refuses to open one file twice with different modes in a process,
    so tasks reading their input and writing their output in the SAME .h5
    file would fail with "file is already open".  The cache keeps one real
    handle per path, refcounted per façade: ``close``/``with`` flush, and
    the LAST close for a path really closes the handle (releasing the HDF5
    file lock for other processes).  Handles opened without a matching
    close stay cached for the process; ``release_h5_handles()`` force-closes
    everything (the cluster executor does, before spawning workers, so the
    driver's handle cannot hold the file lock against them).

    Datasets fetched through a *read-only* handle (via ``[]`` or ``get``)
    come back as lazy re-resolving proxies: a later writable open of the
    same path reopens the file underneath, and raw h5py ids from the old
    handle would die.  Writable handles are never reopened (``w``/``w-``/
    ``x`` keep their loud h5py semantics, see ``_h5_open``), so their
    datasets are returned raw.  Objects reached through other h5py APIs
    (group traversal, ``visititems``) are raw and must not be held across a
    writable reopen of a file first opened read-only.
    """

    def __init__(self, f, path: str):
        object.__setattr__(self, "_f", f)
        object.__setattr__(self, "_path", path)

    def __getattr__(self, name):
        return getattr(self._f, name)

    @staticmethod
    def _h5_compression(compression):
        """Map the store's compression vocabulary onto h5py's: the house
        'default'/'blosc'/'zlib' become gzip (h5py has no blosc without a
        plugin), 'raw'/None mean uncompressed."""
        if compression in (None, "raw"):
            return {}
        if compression in ("gzip", "zlib", "default", "blosc") or _is_blosc(
            compression
        ):
            return {"compression": "gzip"}
        return {"compression": compression}

    def create_dataset(self, key, shape=None, dtype=None, chunks=None,
                       compression="default", data=None, **kw):
        if data is not None and not isinstance(data, (str, bytes)):
            # str/bytes stay raw: h5py stores them as vlen strings, and
            # np.asarray would turn str into a U-dtype h5py rejects
            data = np.asarray(data)
            if shape is None:
                shape = data.shape
            elif tuple(shape) != data.shape:
                data = data.reshape(shape)  # h5py semantics: shape wins
        if chunks is not None and shape is not None:
            chunks = _clamp_chunks(chunks, shape)
        scalar = (data is not None and np.ndim(data) == 0) or (
            shape is not None
            and (len(shape) == 0 or any(s == 0 for s in shape))
        )
        if scalar:
            # h5py: scalar/empty datasets take no chunk/filter options
            args = dict(kw)
        else:
            args = dict(kw, **self._h5_compression(compression))
            if chunks is not None:
                args["chunks"] = chunks
        if dtype is not None:
            args["dtype"] = dtype
        if data is not None:
            return self._f.create_dataset(key, data=data, **args)
        return self._f.create_dataset(key, shape=shape, **args)

    def require_dataset(self, key, shape=None, dtype=None, chunks=None,
                        compression="default", **kw):
        if key in self._f:
            ds = self._f[key]
            if shape is not None and tuple(shape) != tuple(ds.shape):
                raise ValueError(
                    f"shape mismatch for {key}: {shape} vs {ds.shape}"
                )
            if dtype is not None and not np.can_cast(
                np.dtype(dtype), ds.dtype, "safe"
            ):
                # keep h5py's loud dtype conformance: silently reusing an
                # incompatible dataset would corrupt later writes
                raise TypeError(
                    f"existing dataset {key} has dtype {ds.dtype}, "
                    f"cannot safely hold {dtype}"
                )
            return ds
        return self.create_dataset(
            key, shape=shape, dtype=dtype, chunks=chunks,
            compression=compression, **kw,
        )

    def __getitem__(self, key):
        obj = self._f[key]
        if self._f.mode == "r" and isinstance(obj, h5py.Dataset):
            return _H5DatasetProxy(self._path, key)
        return obj

    def __setitem__(self, key, value):
        self._f[key] = value

    def __contains__(self, key):
        return key in self._f

    def __iter__(self):
        return iter(self._f)

    def __len__(self):
        return len(self._f)

    def get(self, key, default=None):
        if key not in self._f:
            return default
        return self[key]  # routes datasets through the proxy path

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self):
        """Flush, and really close the cached handle on the LAST façade
        close for this path (refcounted — ADVICE r3: a `with` user expects
        the HDF5 file lock released).  Stale façades over a handle that a
        read→write upgrade already replaced are a no-op."""
        with _H5_LOCK:
            if getattr(self, "_released", False):
                return
            object.__setattr__(self, "_released", True)
            f = self._f
            if f and f.mode != "r":
                f.flush()
            key = self._path
            if _H5_HANDLES.get(key) is not f:
                return  # replaced by an upgrade; its refs were reset there
            n = _H5_REFS.get(key, 1) - 1
            if n > 0:
                _H5_REFS[key] = n
                return
            _H5_REFS.pop(key, None)
            _H5_HANDLES.pop(key, None)
            if f:
                f.close()


def set_read_threads(ds, n: int) -> None:
    """Best-effort ``ds.n_threads = n`` (the reference's z5py idiom).

    Raw h5py datasets refuse attribute assignment — and single-threaded is
    the correct setting there anyway (global h5 lock), so the failure is
    swallowed deliberately."""
    try:
        ds.n_threads = int(n)
    except (AttributeError, TypeError):
        pass


def release_h5_handles() -> None:
    """Close every cached h5 handle (flushing writers).  Call before handing
    a file to another process: a held writable handle would otherwise block
    the peer's open under HDF5 file locking."""
    with _H5_LOCK:
        for f in _H5_HANDLES.values():
            if f:
                f.close()
        _H5_HANDLES.clear()
        _H5_REFS.clear()


def _h5_open(path: str, mode: str):
    key = os.path.abspath(path)
    with _H5_LOCK:
        cached = _H5_HANDLES.get(key)
        if cached is not None and not bool(cached):
            _H5_HANDLES.pop(key, None)
            _H5_REFS.pop(key, None)
            cached = None  # closed underneath us
        if mode in ("w", "w-", "x"):
            # truncate / exclusive-create: never satisfiable from a cached
            # handle — let h5py raise its usual loud errors (truncate of an
            # open file, FileExistsError) rather than silently clobbering
            if cached is not None:
                raise OSError(
                    f"cannot open {path!r} with mode {mode!r}: the file is "
                    "open elsewhere in this process "
                    "(store.release_h5_handles() closes cached handles)"
                )
            f = h5py.File(path, mode)
            _H5_HANDLES[key] = f
            _H5_REFS[key] = _H5_REFS.get(key, 0) + 1
            return _CachedH5File(f, key)
        if cached is not None and mode in ("a", "r+") and cached.mode == "r":
            # upgrade read-only → writable; prior reads were handed out as
            # re-resolving proxies, so nothing is invalidated.  Refs reset:
            # stale façades over the replaced handle must not decrement the
            # new handle's count (they no-op on the identity check)
            cached.close()
            _H5_HANDLES.pop(key, None)
            _H5_REFS.pop(key, None)
            cached = None
            mode = "a"
        if cached is None:
            cached = h5py.File(path, mode)
            _H5_HANDLES[key] = cached
        _H5_REFS[key] = _H5_REFS.get(key, 0) + 1
        return _CachedH5File(cached, key)


def file_reader(path: str, mode: str = "a"):
    """Open a chunked container by extension: .zarr/.zr, .n5, .h5/.hdf5.

    Mirrors the façade the reference builds over elf.io/z5py
    (reference utils/volume_utils.py:21-22).  ``http(s)://`` paths open
    the same zarr/n5 layouts against an object store (ctt-cloud,
    ``utils/store_backend.py``); hdf5 stays a local-file format.
    """
    if is_remote_path(path):
        ext = os.path.splitext(
            urllib.parse.urlsplit(path).path.rstrip("/")
        )[1].lower()
        if ext in (".h5", ".hdf5", ".hdf"):
            raise ValueError(
                "hdf5 containers cannot be served over the object-store "
                "backend (single-file format); use .zarr/.n5"
            )
        return File(path, mode)
    ext = os.path.splitext(path)[1].lower()
    if ext in (".h5", ".hdf5", ".hdf"):
        if h5py is None:
            raise RuntimeError("h5py is not available")
        return _h5_open(path, mode)
    return File(path, mode)
