"""CLI: ``python -m cluster_tools_tpu.analysis``.

Default run = AST lints over the package source + ``tests/``, plus the
workflow-graph validator over ``cluster_tools_tpu/workflows/``.  Exit code
is 0 unless ``--fail-on-findings`` is given and findings exist (then 1);
internal errors exit 2.
"""

from __future__ import annotations

import argparse
import os
import sys


def _force_cpu_backend() -> None:
    """The workflow-graph validator imports jax transitively; on the TPU
    image a wedged device tunnel makes device init hang, and the
    sitecustomize pins JAX_PLATFORMS too early for the env var — force the
    CPU backend via the config, exactly like tests/conftest.py."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # no jax (pure-AST run still works); graph validation will say so


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "conformance":
        # `analysis conformance <dir>`: validate a real state/run dir
        # against the artifact registry (exit 0 clean / 1 empty / 2
        # malformed) — the chaos smokes' post-run protocol gate
        sub = argparse.ArgumentParser(
            prog="python -m cluster_tools_tpu.analysis conformance",
            description="validate a state/run dir against the "
            "analysis/protocols.py artifact registry",
        )
        sub.add_argument("dir", help="state/queue/run directory to validate")
        sub_args = sub.parse_args(argv[1:])
        from .conformance import run_conformance

        return run_conformance(sub_args.dir)

    parser = argparse.ArgumentParser(
        prog="python -m cluster_tools_tpu.analysis",
        description="ctt-lint: AST invariant checks + workflow-graph "
        "validation for the TPU pipeline",
    )
    parser.add_argument(
        "--fail-on-findings", action="store_true",
        help="exit 1 if any finding is reported (CI mode)",
    )
    parser.add_argument(
        "--paths", nargs="*", default=None,
        help="files/directories for the AST lints (default: the package "
        "source dirs + tests/)",
    )
    parser.add_argument(
        "--workflows", default=None,
        help="directory of workflow modules to graph-validate (default: "
        "cluster_tools_tpu/workflows; pass an empty string to skip)",
    )
    parser.add_argument(
        "--no-graph", action="store_true",
        help="skip the workflow-graph validator (pure-AST run, no imports)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print every rule id and exit"
    )
    args = parser.parse_args(argv)

    from .core import REGISTRY

    # make sure every rule family is registered before --list-rules
    from . import ast_rules  # noqa: F401
    from . import graph as graph_rules  # noqa: F401
    from . import proto_rules  # noqa: F401

    if args.list_rules:
        for info in REGISTRY.items():
            print(f"{info.rule_id}  {info.description}")
        return 0

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_root = os.path.dirname(pkg_root)

    if args.paths is None:
        paths = [
            os.path.join(pkg_root, d)
            for d in ("faults", "obs", "ops", "parallel", "runtime", "serve",
                      "tasks", "workflows", "utils")
        ]
        tests_dir = os.path.join(repo_root, "tests")
        if os.path.isdir(tests_dir):
            paths.append(tests_dir)
    else:
        paths = args.paths

    pyproject = os.path.join(repo_root, "pyproject.toml")

    from .ast_rules import lint_paths

    findings = lint_paths(paths, pyproject if os.path.exists(pyproject) else None)

    if args.paths is None:
        # full-tree runs also get the reverse CTT205 check: every
        # faults.KNOWN_SITES entry must keep >= 1 call site in the
        # package source (tests excluded — chaos specs there are data)
        from .proto_rules import check_fault_site_coverage

        pkg_paths = [p for p in paths if not p.endswith("tests")]
        findings.extend(check_fault_site_coverage(pkg_paths))

    if not args.no_graph:
        workflows_dir = args.workflows
        if workflows_dir is None:
            workflows_dir = os.path.join(pkg_root, "workflows")
        if workflows_dir and os.path.isdir(workflows_dir):
            _force_cpu_backend()
            from .graph import validate_workflows_dir

            findings.extend(validate_workflows_dir(workflows_dir))

    findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    for f in findings:
        print(f.format())
    n = len(findings)
    print(f"ctt-lint: {n} finding{'s' if n != 1 else ''}", file=sys.stderr)
    if findings and args.fail_on_findings:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
