"""ctt-lint core: findings, the rule registry, and noqa suppression.

Every rule is a small class with a stable id (``CTT001``...), a one-line
description, and a ``check`` entry point.  Findings are reported as
``path:line: CTTxxx message`` and can be suppressed inline with

    some_code()  # ctt: noqa[CTT003] reason why this is a false positive

A bare ``# ctt: noqa`` (no bracket) suppresses every rule on that line.
Rule ids live in two families:

  * ``CTT0xx`` — AST invariant lints over the accelerator/runtime source
    (see ``ast_rules.py``);
  * ``CTT1xx`` — workflow-graph validation over ``workflows/*.py`` task
    DAGs (see ``graph.py``).

Adding a rule: subclass :class:`AstRule` (or extend the graph validator),
give it a unique ``rule_id`` + ``description``, and register it in the
module-level rule list; ``python -m cluster_tools_tpu.analysis --list-rules``
must show it, and COMPONENTS.md ("Static analysis") documents it.
"""

from __future__ import annotations

import io
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, List, Sequence, Set


@dataclass(frozen=True)
class Finding:
    rule_id: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule_id} {self.message}"


# ``# ctt: noqa`` or ``# ctt: noqa[CTT001, CTT005] optional reason``
_NOQA_RE = re.compile(r"#\s*ctt:\s*noqa(?:\[(?P<ids>[^\]]*)\])?")

# sentinel for "suppress every rule on this line"
_ALL = "*"


def comment_lines(source: str) -> Dict[int, str]:
    """1-based line number -> comment text, via the tokenizer — so noqa
    grammar inside *string literals* (docs, test corpora) never counts.
    Falls back to a raw line scan when the source does not tokenize."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, SyntaxError, IndentationError):
        for lineno, text in enumerate(source.splitlines(), start=1):
            if "#" in text:
                out[lineno] = text[text.index("#"):]
    return out


def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """Map 1-based line number -> set of suppressed rule ids (``*`` = all)."""
    out: Dict[int, Set[str]] = {}
    for lineno, text in comment_lines(source).items():
        m = _NOQA_RE.search(text)
        if m is None:
            continue
        ids = m.group("ids")
        if ids is None:
            out[lineno] = {_ALL}
        else:
            out[lineno] = {t.strip() for t in ids.split(",") if t.strip()}
    return out


def is_suppressed(
    finding: Finding, suppressions: Dict[int, Set[str]]
) -> bool:
    ids = suppressions.get(finding.line)
    if not ids:
        return False
    return _ALL in ids or finding.rule_id in ids


def filter_suppressed(
    findings: Sequence[Finding], source: str
) -> List[Finding]:
    supp = parse_suppressions(source)
    return [f for f in findings if not is_suppressed(f, supp)]


@dataclass
class RuleInfo:
    rule_id: str
    description: str


class Registry:
    """The set of known rule ids — used by the CLI listing and by the
    noqa-hygiene rule (an unknown id in a noqa comment is itself a finding)."""

    def __init__(self) -> None:
        self._rules: Dict[str, RuleInfo] = {}

    def register(self, rule_id: str, description: str) -> None:
        if rule_id in self._rules:
            raise ValueError(f"duplicate rule id {rule_id}")
        self._rules[rule_id] = RuleInfo(rule_id, description)

    def known_ids(self) -> Set[str]:
        return set(self._rules)

    def items(self) -> List[RuleInfo]:
        return [self._rules[k] for k in sorted(self._rules)]


REGISTRY = Registry()


def register_rule(rule_id: str, description: str) -> None:
    REGISTRY.register(rule_id, description)
