"""AST invariant lints (CTT0xx) over the accelerator/runtime source.

The rules encode the invariants the TPU rebuild otherwise only enforces
through runtime tests:

  CTT001  no host-materializing calls inside ``@jax.jit``/``shard_map``
          bodies (``np.*``, ``jax.device_get``, ``.block_until_ready()``,
          ``.item()``, ``.tolist()``) — a host sync inside a traced body
          either crashes on tracers or silently serializes the pipeline.
          Trace-time-constant helpers (``np.iinfo``/``np.finfo``/dtype
          constructors/...) are allowed.
  CTT002  no wall-clock or host randomness inside jitted bodies
          (``time.time()``, ``random.*``, ``np.random.*``) — they burn
          into the compiled program as constants.
  CTT003  collectives (``psum``/``ppermute``/``all_gather``/...) only in
          ``parallel/`` modules, where the mesh context that gives their
          axis names meaning lives.
  CTT004  no wide-dtype drift into device code: ``jnp.float64``/
          ``jnp.int64``/``jnp.uint64`` anywhere, or 64-bit dtype literals
          inside jitted bodies / passed to ``jnp`` calls — without
          ``jax_enable_x64`` these silently demote and mask precision bugs.
  CTT005  no iteration over ``set`` values where the order can leak into
          constructed state (task graphs, pin files, edge lists) — wrap in
          ``sorted()`` or iterate a list.  Order-invariant consumers
          (``sorted``/``min``/``max``/``sum``/``len``/``any``/``all``/set
          algebra) are allowed.
  CTT006  every ``pytest.mark.<name>`` used under ``tests/`` must be
          registered in ``pyproject.toml`` (``[tool.pytest.ini_options]
          markers``) — unregistered markers make ``-m`` selection silently
          select nothing and spam warnings.
  CTT007  noqa hygiene: a ``# ctt: noqa[...]`` referencing an unknown rule
          id (or an empty bracket) suppresses nothing and hides typos.
  CTT008  raw ``time.time()`` used in duration/deadline math (arithmetic
          or comparison) outside ``obs/`` — a host clock jump (NTP step,
          VM migration) fires or stalls such timeouts.  Wall clock is for
          *timestamps* only; durations and deadlines go through the obs
          monotonic helpers (``obs.trace.monotonic()``).
  CTT009  resilience hygiene: (a) ad-hoc retry loops — a ``while``/``for``
          containing both a ``try``/``except`` and a ``time.sleep`` —
          outside the shared backoff helper (``utils/retry.py``): hand-
          rolled retries skip the exponential backoff, the jitter that
          prevents retry storms, and the ``store.io_retries`` counter;
          (b) ``except Exception: pass`` (or a bare except) whose body is
          only ``pass`` — swallowing a block error without recording any
          status hides failures from the retry machinery and the operator.
  CTT010  metric-name hygiene: a string literal passed to
          ``metrics.inc``/``metrics.set_gauge``/``hist.observe`` that is
          not listed in ``obs/registry.py`` (counters, gauges, and
          histograms are checked against their own kind; dynamic
          prefixes like ``faults.injected.<site>`` are allowed) — a typo
          silently creates a fresh series nothing ever reads.
          Non-literal names (f-strings, variables) are the sanctioned
          dynamic path and are skipped.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, List, Optional, Sequence, Set

from .core import Finding, REGISTRY, register_rule

register_rule("CTT001", "host-materializing call inside a jitted body")
register_rule("CTT002", "wall-clock/host randomness inside a jitted body")
register_rule("CTT003", "collective call outside parallel/ mesh context")
register_rule("CTT004", "wide (64-bit) dtype in device code")
register_rule("CTT005", "order-sensitive iteration over a set")
register_rule("CTT006", "pytest marker not registered in pyproject.toml")
register_rule("CTT007", "noqa comment references an unknown rule id")
register_rule("CTT008", "wall-clock time.time() in duration/deadline math")
register_rule(
    "CTT009", "ad-hoc sleep-retry loop / error-swallowing `except: pass`"
)
register_rule(
    "CTT010", "metric name literal not in the obs/registry.py registry"
)


# --------------------------------------------------------------------------
# shared AST helpers


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_SHARD_MAP_NAMES = {"shard_map", "jax.experimental.shard_map.shard_map"}


def _is_jit_decorator(dec: ast.expr) -> bool:
    name = dotted_name(dec)
    if name in _JIT_NAMES | _SHARD_MAP_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        if fname in _JIT_NAMES | _SHARD_MAP_NAMES:
            return True  # @jax.jit(static_argnums=...) / @shard_map(...)
        if fname in {"partial", "functools.partial"} and dec.args:
            inner = dotted_name(dec.args[0])
            if inner in _JIT_NAMES | _SHARD_MAP_NAMES:
                return True
    return False


def jitted_functions(tree: ast.Module) -> List[ast.FunctionDef]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                out.append(node)
    return out


# --------------------------------------------------------------------------
# CTT001 / CTT002 / CTT004-in-jit: walk jitted bodies

# np.* helpers that only produce trace-time constants — legal inside jit
_TRACE_SAFE_NP = {
    "iinfo", "finfo", "dtype", "promote_types", "result_type", "can_cast",
    # scalar dtype constructors (np.float32(x) on a python scalar)
    "float32", "float16", "bfloat16", "int32", "int16", "int8",
    "uint32", "uint16", "uint8", "bool_",
    # trace-time arithmetic on static shapes/sizes (np.prod(x.shape),
    # np.ceil(np.log2(n)) for loop-bound derivation) — the codebase idiom
    "prod", "ceil", "floor", "log2", "sqrt",
}

_HOST_SYNC_METHODS = {"block_until_ready", "item", "tolist"}

_WIDE_DTYPES = {"float64", "int64", "uint64"}

_TIME_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic", "time.process_time",
    "datetime.datetime.now", "datetime.datetime.utcnow",
}


def _check_jit_body(
    fn: ast.FunctionDef, path: str, findings: List[Finding]
) -> None:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            root = name.split(".")[0]
            # -- CTT002: clock / host RNG ---------------------------------
            if (
                name in _TIME_CALLS
                or root == "random"
                or name.startswith(("np.random", "numpy.random"))
            ):
                findings.append(Finding(
                    "CTT002", path, node.lineno,
                    f"`{name}` inside jitted `{fn.name}` bakes host "
                    "state into the compiled program",
                ))
                continue
            # -- CTT001: host materialization -----------------------------
            if name in {"jax.device_get", "device_get"}:
                findings.append(Finding(
                    "CTT001", path, node.lineno,
                    f"`{name}` inside jitted `{fn.name}` forces a device "
                    "sync on a tracer",
                ))
                continue
            if root in {"np", "numpy"}:
                leaf = name.split(".")[-1]
                if leaf not in _TRACE_SAFE_NP:
                    findings.append(Finding(
                        "CTT001", path, node.lineno,
                        f"`{name}` inside jitted `{fn.name}` runs on the "
                        "host — use jnp, or hoist to trace-time constants",
                    ))
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOST_SYNC_METHODS
            ):
                findings.append(Finding(
                    "CTT001", path, node.lineno,
                    f"`.{node.func.attr}()` inside jitted `{fn.name}` "
                    "forces a host sync",
                ))
                continue
        # -- CTT004: wide dtype mentioned inside device code --------------
        if isinstance(node, ast.Attribute):
            name = dotted_name(node) or ""
            if (
                name.split(".")[0] in {"np", "numpy", "jnp"}
                and name.split(".")[-1] in _WIDE_DTYPES
            ):
                findings.append(Finding(
                    "CTT004", path, node.lineno,
                    f"`{name}` inside jitted `{fn.name}` — 64-bit dtypes "
                    "demote silently without jax_enable_x64",
                ))
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if node.value in _WIDE_DTYPES:
                findings.append(Finding(
                    "CTT004", path, node.lineno,
                    f"dtype literal '{node.value}' inside jitted "
                    f"`{fn.name}`",
                ))


# --------------------------------------------------------------------------
# CTT004 outside jit: jnp-wide dtypes anywhere, 64-bit literals fed to jnp


def _check_wide_dtypes_module(
    tree: ast.Module, path: str, jit_fns: Sequence[ast.FunctionDef],
    findings: List[Finding],
) -> None:
    jit_nodes = set()
    for fn in jit_fns:
        jit_nodes.update(id(n) for n in ast.walk(fn))
    for node in ast.walk(tree):
        if id(node) in jit_nodes:
            continue  # already covered by the in-jit check
        if isinstance(node, ast.Attribute):
            name = dotted_name(node) or ""
            parts = name.split(".")
            if parts[0] in {"jnp", "jax"} and parts[-1] in _WIDE_DTYPES:
                findings.append(Finding(
                    "CTT004", path, node.lineno,
                    f"`{name}` — jax arrays must stay <= 32-bit "
                    "(no jax_enable_x64 in this codebase)",
                ))
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func) or ""
            if fname.split(".")[0] in {"jnp"}:
                for kw in node.keywords:
                    if kw.arg == "dtype" and isinstance(kw.value, ast.Constant):
                        if kw.value.value in _WIDE_DTYPES:
                            findings.append(Finding(
                                "CTT004", path, node.lineno,
                                f"dtype='{kw.value.value}' passed to "
                                f"`{fname}`",
                            ))


# --------------------------------------------------------------------------
# CTT003: collectives outside parallel/

_COLLECTIVES = {
    "psum", "psum_scatter", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
    "all_gather", "all_to_all", "axis_index",
}


def _collective_allowed(path: str) -> bool:
    parts = os.path.normpath(path).split(os.sep)
    return "parallel" in parts


def _check_collectives(
    tree: ast.Module, path: str, findings: List[Finding]
) -> None:
    if _collective_allowed(path):
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func) or ""
        parts = name.split(".")
        if parts[-1] not in _COLLECTIVES:
            continue
        # only flag jax.lax-rooted (or bare-imported) collective names;
        # arbitrary methods that happen to collide are not collectives
        if len(parts) == 1 or parts[0] in {"jax", "lax"}:
            findings.append(Finding(
                "CTT003", path, node.lineno,
                f"collective `{name}` outside parallel/ — collectives "
                "need the mesh context that names their axes",
            ))


# --------------------------------------------------------------------------
# CTT005: order-sensitive set iteration

_ORDER_INVARIANT_CONSUMERS = {
    "sorted", "min", "max", "sum", "len", "any", "all", "set", "frozenset",
}
_ORDER_SENSITIVE_CONSUMERS = {"list", "tuple", "enumerate"}


class _SetIterVisitor(ast.NodeVisitor):
    """Track names bound to set expressions per function scope and flag
    order-sensitive iteration over them."""

    def __init__(self, path: str, findings: List[Finding]):
        self.path = path
        self.findings = findings
        self.set_names: List[Set[str]] = [set()]
        self.nonset_names: List[Set[str]] = [set()]

    # -- scope handling ---------------------------------------------------

    def _enter(self):
        self.set_names.append(set())
        self.nonset_names.append(set())

    def _exit(self):
        self.set_names.pop()
        self.nonset_names.pop()

    def visit_FunctionDef(self, node):
        self._enter()
        self.generic_visit(node)
        self._exit()

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- binding tracking -------------------------------------------------

    @staticmethod
    def _is_set_expr(node: ast.AST) -> bool:
        if isinstance(node, ast.Set):
            return True
        if isinstance(node, ast.SetComp):
            return True
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name in {"set", "frozenset"}:
                return True
            if isinstance(node.func, ast.Attribute) and node.func.attr in {
                "intersection", "union", "difference", "symmetric_difference",
            }:
                return False  # could be sets, but too ambiguous to track
        return False

    def _is_tracked_set(self, node: ast.AST) -> bool:
        if self._is_set_expr(node):
            return True
        if isinstance(node, ast.Name):
            for tracked, shadowed in zip(
                reversed(self.set_names), reversed(self.nonset_names)
            ):
                if node.id in shadowed:
                    return False
                if node.id in tracked:
                    return True
        return False

    def visit_Assign(self, node):
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                if self._is_set_expr(node.value):
                    self.set_names[-1].add(tgt.id)
                    self.nonset_names[-1].discard(tgt.id)
                else:
                    self.nonset_names[-1].add(tgt.id)
                    self.set_names[-1].discard(tgt.id)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if isinstance(node.target, ast.Name) and node.value is not None:
            if self._is_set_expr(node.value):
                self.set_names[-1].add(node.target.id)
            else:
                self.nonset_names[-1].add(node.target.id)
        self.generic_visit(node)

    # -- iteration sites --------------------------------------------------

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            "CTT005", self.path, node.lineno,
            f"{what} iterates a set — ordering is hash-seed dependent; "
            "wrap in sorted() or restructure",
        ))

    def visit_For(self, node):
        if self._is_tracked_set(node.iter):
            self._flag(node, "for-loop")
        self.generic_visit(node)

    def _check_comprehension(self, node, what: str):
        for gen in node.generators:
            if self._is_tracked_set(gen.iter):
                self._flag(node, what)
        self.generic_visit(node)

    def visit_ListComp(self, node):
        self._check_comprehension(node, "list comprehension")

    def visit_DictComp(self, node):
        self._check_comprehension(node, "dict comprehension")

    def visit_Call(self, node):
        name = dotted_name(node.func)
        if name in _ORDER_SENSITIVE_CONSUMERS and node.args:
            if self._is_tracked_set(node.args[0]):
                self._flag(node, f"{name}() over a set")
        self.generic_visit(node)


# --------------------------------------------------------------------------
# CTT008: wall clock in duration/deadline math

_WALL_CLOCK_CALLS = {"time.time"}


def _wall_clock_exempt(path: str) -> bool:
    # obs/ IS the clock vocabulary: it records wall-clock anchors next to
    # monotonic ones by design (trace shard headers, export alignment)
    return "obs" in os.path.normpath(path).split(os.sep)


def _check_wall_clock_math(
    tree: ast.Module, path: str, findings: List[Finding]
) -> None:
    """Flag ``time.time()`` participating in arithmetic or comparisons —
    that is duration/deadline math, where a clock jump corrupts the
    result.  A bare ``time.time()`` stored or serialized as a timestamp
    stays legal.  Jitted bodies are excluded: any clock there is already a
    CTT002 finding (host state baked into the program) — one report per
    defect."""
    if _wall_clock_exempt(path):
        return
    in_jit: Set[int] = set()
    for fn in jitted_functions(tree):
        in_jit.update(id(n) for n in ast.walk(fn))
    flagged: Set[int] = set()
    for node in ast.walk(tree):
        if id(node) in in_jit:
            continue
        if not isinstance(node, (ast.BinOp, ast.Compare, ast.AugAssign)):
            continue
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Call)
                and dotted_name(sub.func) in _WALL_CLOCK_CALLS
                and id(sub) not in flagged
            ):
                flagged.add(id(sub))
                findings.append(Finding(
                    "CTT008", path, sub.lineno,
                    "`time.time()` in duration/deadline math — wall clock "
                    "jumps corrupt intervals; use obs.trace.monotonic() "
                    "(time.time() is for timestamps only)",
                ))


# --------------------------------------------------------------------------
# CTT009: ad-hoc retry loops and swallowed exceptions


def _retry_helper_exempt(path: str) -> bool:
    # utils/retry.py IS the sanctioned backoff loop the rule points at
    parts = os.path.normpath(path).split(os.sep)
    return parts[-2:] == ["utils", "retry.py"]


def _check_resilience_hygiene(
    tree: ast.Module, path: str, findings: List[Finding]
) -> None:
    # (a) ad-hoc sleep-retry loops: a loop whose body holds both an
    # exception handler and a time.sleep — hand-rolled backoff
    if not _retry_helper_exempt(path):
        flagged: Set[int] = set()
        for node in ast.walk(tree):
            if not isinstance(node, (ast.While, ast.For)):
                continue
            if not any(isinstance(n, ast.Try) for n in ast.walk(node)):
                continue
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Call)
                    and dotted_name(sub.func) == "time.sleep"
                    and id(sub) not in flagged
                ):
                    flagged.add(id(sub))
                    findings.append(Finding(
                        "CTT009", path, sub.lineno,
                        "ad-hoc sleep-retry loop — route transient-IO "
                        "retries through utils.retry.io_retry (exponential "
                        "backoff + jitter + the store.io_retries counter)",
                    ))
    # (b) `except Exception: pass` / bare `except: pass`: the error is
    # swallowed without recording any status
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not (len(node.body) == 1 and isinstance(node.body[0], ast.Pass)):
            continue
        tname = dotted_name(node.type) if node.type is not None else None
        if node.type is None or tname in ("Exception", "BaseException"):
            findings.append(Finding(
                "CTT009", path, node.lineno,
                "`except"
                + (f" {tname}" if tname else "")
                + ": pass` swallows errors without recording status — "
                "narrow the exception or record/log the failure",
            ))


# --------------------------------------------------------------------------
# CTT010: metric-name literals must come from obs/registry.py

_METRIC_CALL_ATTRS = {"inc": "counter", "set_gauge": "gauge",
                      "observe": "histogram"}
# the receiver module alias each call kind must ride: `metrics.inc`,
# `obs_metrics.set_gauge`, `hist.observe`, `obs_hist.observe` — arbitrary
# objects with .inc()/.observe() are not metric sites
_METRIC_RECEIVER_HINT = {"counter": "metrics", "gauge": "metrics",
                         "histogram": "hist"}


def _check_metric_names(
    tree: ast.Module, path: str, findings: List[Finding]
) -> None:
    """Flag ``<...>metrics.inc("name")`` / ``set_gauge("name")`` /
    ``<...>hist.observe("name", v)`` literals absent from the registry.
    Only literal first arguments are checked — computed names
    (``f"faults.injected.{site}"``) are the dynamic path, covered by the
    registry's prefix list."""
    from ..obs import registry as metric_registry

    _known = {
        "counter": metric_registry.is_known_counter,
        "gauge": metric_registry.is_known_gauge,
        "histogram": metric_registry.is_known_histogram,
    }
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        name = dotted_name(node.func) or ""
        parts = name.split(".")
        if len(parts) < 2 or parts[-1] not in _METRIC_CALL_ATTRS:
            continue
        kind = _METRIC_CALL_ATTRS[parts[-1]]
        if _METRIC_RECEIVER_HINT[kind] not in parts[-2]:
            continue
        arg = node.args[0]
        if not isinstance(arg, ast.Constant) or not isinstance(arg.value, str):
            continue
        mname = arg.value
        if not _known[kind](mname):
            findings.append(Finding(
                "CTT010", path, node.lineno,
                f"{kind} name '{mname}' is not in obs/registry.py — a "
                "typo silently creates a series nothing reads; add it to "
                "the registry (or a DYNAMIC_PREFIXES family)",
            ))


# --------------------------------------------------------------------------
# CTT006: unregistered pytest markers

# markers pytest itself (or its bundled plugins) always knows
_BUILTIN_MARKERS = {
    "parametrize", "skip", "skipif", "xfail", "usefixtures", "filterwarnings",
}

_PYPROJECT_MARKER_RE = re.compile(
    r"markers\s*=\s*\[(?P<body>.*?)\]", re.DOTALL
)


def registered_markers(pyproject_path: str) -> Set[str]:
    """Markers declared in ``[tool.pytest.ini_options] markers``.  Parsed
    with a regex (no tomllib on py3.10); each entry is ``"name: doc"``."""
    try:
        with open(pyproject_path) as f:
            text = f.read()
    except OSError:
        return set()
    m = _PYPROJECT_MARKER_RE.search(text)
    if m is None:
        return set()
    out: Set[str] = set()
    for entry in re.findall(r"[\"']([^\"']+)[\"']", m.group("body")):
        out.add(entry.split(":")[0].strip().split("(")[0])
    return out


def _check_markers(
    tree: ast.Module, path: str, registered: Set[str],
    findings: List[Finding],
) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Attribute):
            continue
        name = dotted_name(node) or ""
        parts = name.split(".")
        if len(parts) < 3 or parts[-3:-1] != ["pytest", "mark"]:
            continue
        marker = parts[-1]
        if marker in _BUILTIN_MARKERS or marker in registered:
            continue
        findings.append(Finding(
            "CTT006", path, node.lineno,
            f"pytest marker `{marker}` is not registered in "
            "pyproject.toml [tool.pytest.ini_options] markers",
        ))


# --------------------------------------------------------------------------
# CTT007: noqa hygiene (regex over raw source; comments are not in the AST)

from .core import _NOQA_RE, comment_lines  # noqa: E402  (shared grammar)


def _check_noqa_hygiene(
    source: str, path: str, findings: List[Finding]
) -> None:
    known = REGISTRY.known_ids()
    for lineno, text in comment_lines(source).items():
        m = _NOQA_RE.search(text)
        if m is None:
            continue
        ids_raw = m.group("ids")
        if ids_raw is None:
            continue  # bare noqa: suppress-all is legal
        ids = [t.strip() for t in ids_raw.split(",") if t.strip()]
        if not ids:
            findings.append(Finding(
                "CTT007", path, lineno,
                "empty `# ctt: noqa[]` suppresses nothing — name the rule "
                "ids or drop the brackets",
            ))
            continue
        for rid in ids:
            if rid not in known:
                findings.append(Finding(
                    "CTT007", path, lineno,
                    f"noqa references unknown rule id `{rid}`",
                ))


# --------------------------------------------------------------------------
# driver


def _is_test_file(path: str) -> bool:
    base = os.path.basename(path)
    return base.startswith("test_") or base == "conftest.py"


def lint_source(
    source: str,
    path: str,
    pyproject_path: Optional[str] = None,
    apply_suppressions: bool = True,
) -> List[Finding]:
    """Run every applicable AST rule over one file's source."""
    findings: List[Finding] = []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("CTT000", path, e.lineno or 1, f"syntax error: {e.msg}")]

    if _is_test_file(path):
        registered = (
            registered_markers(pyproject_path) if pyproject_path else set()
        )
        _check_markers(tree, path, registered, findings)
    else:
        jit_fns = jitted_functions(tree)
        for fn in jit_fns:
            _check_jit_body(fn, path, findings)
        _check_wide_dtypes_module(tree, path, jit_fns, findings)
        _check_collectives(tree, path, findings)
        _check_wall_clock_math(tree, path, findings)
        _check_resilience_hygiene(tree, path, findings)
        _check_metric_names(tree, path, findings)
        _SetIterVisitor(path, findings).visit(tree)
        # shared-state protocol rules (CTT2xx) — imported lazily so the
        # two rule modules can share helpers without an import cycle
        from .proto_rules import check_proto_rules

        check_proto_rules(tree, path, findings)
    _check_noqa_hygiene(source, path, findings)

    if apply_suppressions:
        from .core import filter_suppressed

        findings = filter_suppressed(findings, source)
    return findings


def lint_paths(
    paths: Iterable[str], pyproject_path: Optional[str] = None
) -> List[Finding]:
    findings: List[Finding] = []
    for path in _iter_py_files(paths):
        try:
            with open(path) as f:
                source = f.read()
        except OSError as e:
            findings.append(Finding("CTT000", path, 1, f"unreadable: {e}"))
            continue
        findings.extend(lint_source(source, path, pyproject_path))
    return findings


def _iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                # ``fixtures`` holds deliberately-malformed lint corpora —
                # excluded from directory walks, lintable by explicit path
                dirs[:] = sorted(
                    d for d in dirs if d not in {"__pycache__", ".git", "fixtures"}
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        elif p.endswith(".py"):
            out.append(p)
    return out
