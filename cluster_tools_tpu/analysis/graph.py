"""Workflow-graph validation (CTT1xx): import each ``workflows/*.py``
module, build every workflow's task DAG *without executing it*, and check
structural invariants:

  CTT101  dependency cycle in the task DAG
  CTT102  a task consumes a dataset (``<x>_path``/``<x>_key`` pair) that no
          transitive upstream task produces and that was not handed in at
          the workflow boundary
  CTT103  a task/workflow reads a config key (``config["k"]`` /
          ``config.get("k")``) that is neither in the global/task config
          schema nor in the class's ``default_task_config()`` — the static
          shape of a config-file typo
  CTT104  a ``slow = True`` task is reachable from a workflow that is not
          itself marked ``slow`` — tier-1 entry points must not pull slow
          paths in by accident
  CTT105  the workflow could not even be instantiated / its ``requires()``
          raised under default flags — the DAG is not statically buildable

The DAG is built by instantiating each workflow with synthesized arguments:
``*_path``/``*_key`` parameters get unique ``<param>`` sentinel strings, so
dataset provenance can be checked by value equality (derived names like
``output_key + "_frag"`` keep their upstream identity).  Graph findings are
anchored at the workflow class's ``class`` line, so ``# ctt: noqa[...]``
suppression works there like everywhere else.
"""

from __future__ import annotations

import ast
import importlib
import importlib.util
import inspect
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, filter_suppressed, register_rule

register_rule("CTT011", "fused streaming chain contract violation")
register_rule("CTT101", "dependency cycle in a workflow task DAG")
register_rule("CTT102", "task input not produced upstream nor external")
register_rule("CTT103", "config key read outside the accepted schema")
register_rule("CTT104", "slow-marked task reachable from a tier-1 workflow")
register_rule("CTT105", "workflow DAG not statically buildable")


# --------------------------------------------------------------------------
# module loading


def load_workflow_module(path: str):
    """Import a workflow file.  Files inside the ``cluster_tools_tpu``
    package import as package modules (their relative imports need it);
    anything else (test fixtures) spec-loads by path."""
    import cluster_tools_tpu

    pkg_root = os.path.dirname(os.path.abspath(cluster_tools_tpu.__file__))
    apath = os.path.abspath(path)
    if apath.startswith(pkg_root + os.sep):
        rel = os.path.relpath(apath, os.path.dirname(pkg_root))
        mod_name = rel[:-3].replace(os.sep, ".")
        return importlib.import_module(mod_name)
    mod_name = "_ctt_lint_fixture_" + os.path.basename(path)[:-3]
    spec = importlib.util.spec_from_file_location(mod_name, apath)
    mod = importlib.util.module_from_spec(spec)
    # registered so inspect.getsourcelines can anchor findings to the file
    sys.modules[mod_name] = mod
    spec.loader.exec_module(mod)
    return mod


def discover_workflow_classes(mod) -> List[type]:
    from ..runtime.workflow import WorkflowBase

    out = []
    for name in sorted(vars(mod)):
        obj = vars(mod)[name]
        if (
            inspect.isclass(obj)
            and issubclass(obj, WorkflowBase)
            and obj is not WorkflowBase
            and obj.__module__ == mod.__name__
        ):
            out.append(obj)
    return out


# --------------------------------------------------------------------------
# instantiation with sentinel arguments


def _named_init_params(cls) -> Dict[str, inspect.Parameter]:
    """Named ``__init__`` parameters across the MRO.  ``*args/**kwargs``
    forwarder inits (the ``SkeletonEvaluationWorkflow`` pattern) pull in
    their base class's named parameters; the climb stops at the first
    ``__init__`` without ``**kwargs`` (nothing more can be passed)."""
    params: Dict[str, inspect.Parameter] = {}
    for klass in cls.__mro__:
        init = vars(klass).get("__init__")
        if init is None:
            continue
        try:
            sig = inspect.signature(init)
        except (TypeError, ValueError):
            break
        has_var_kw = False
        for name, p in sig.parameters.items():
            if p.kind == p.VAR_KEYWORD:
                has_var_kw = True
            if p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD) or name == "self":
                continue
            params.setdefault(name, p)
        if not has_var_kw:
            break
    return params


def synthesize_kwargs(cls) -> Dict[str, Any]:
    kwargs: Dict[str, Any] = {}
    for name, p in _named_init_params(cls).items():
        if name == "dependencies":
            continue
        if name == "tmp_folder":
            kwargs[name] = "<tmp_folder>"
        elif name == "config_dir":
            kwargs[name] = None
        elif name.endswith("_path"):
            # sentinel even when a default exists (a fully-wired DAG is
            # what makes the provenance check meaningful); the .n5 suffix
            # satisfies container-extension dispatch in requires() bodies
            kwargs[name] = f"<{name}>.n5"
        elif name.endswith("_key") or name.endswith("_prefix"):
            kwargs[name] = f"<{name}>"
        elif p.default is not inspect.Parameter.empty:
            continue  # keep the class's own default behavior
        elif p.annotation in (int, "int"):
            kwargs[name] = 1
        elif p.annotation in (bool, "bool"):
            kwargs[name] = False
        else:
            kwargs[name] = f"<{name}>"
    return kwargs


def _class_anchor(cls) -> Tuple[str, int]:
    try:
        path = inspect.getsourcefile(cls) or "<unknown>"
        _, line = inspect.getsourcelines(cls)
    except (OSError, TypeError):
        return "<unknown>", 1
    return path, line


# --------------------------------------------------------------------------
# DAG walk


class TaskGraph:
    """The instantiated DAG of one workflow: nodes keyed by ``id()``."""

    def __init__(self, roots: Sequence[Any]):
        self.nodes: List[Any] = []
        self.deps: Dict[int, List[Any]] = {}
        self.cycle: Optional[List[str]] = None
        self._seen: Set[int] = set()
        onstack: List[int] = []

        def visit(task) -> None:
            if self.cycle is not None:
                return
            tid = id(task)
            if tid in onstack:
                names = [type(t).__name__ for t in self.nodes if id(t) in onstack]
                self.cycle = names + [type(task).__name__]
                return
            if tid in self._seen:
                return
            self._seen.add(tid)
            onstack.append(tid)
            deps = list(task.requires())
            self.deps[tid] = deps
            self.nodes.append(task)
            for dep in deps:
                visit(dep)
            onstack.pop()

        for r in roots:
            visit(r)

    def transitive_deps(self, task) -> List[Any]:
        out: List[Any] = []
        seen: Set[int] = set()
        stack = list(self.deps.get(id(task), []))
        while stack:
            t = stack.pop()
            if id(t) in seen:
                continue
            seen.add(id(t))
            out.append(t)
            stack.extend(self.deps.get(id(t), []))
        return out


# --------------------------------------------------------------------------
# dataset provenance (CTT102)


def produced_pairs(task) -> Set[Tuple[str, str]]:
    """(path, key) datasets a task writes.  ``output_path``/``output_key``
    by default; tasks with additional outputs declare them via a
    ``produced_prefixes`` class attribute."""
    prefixes = getattr(task, "produced_prefixes", ("output",))
    out: Set[Tuple[str, str]] = set()
    for prefix in prefixes:
        path = getattr(task, f"{prefix}_path", None)
        key = getattr(task, f"{prefix}_key", None)
        if path is not None and key is not None:
            out.add((path, key))
    return out


def consumed_pairs(task) -> List[Tuple[str, Tuple[str, str]]]:
    """(attr-prefix, (path, key)) datasets a task reads."""
    prefixes = set(getattr(task, "produced_prefixes", ("output",)))
    out: List[Tuple[str, Tuple[str, str]]] = []
    for attr in sorted(vars(task)):
        if not attr.endswith("_path"):
            continue
        prefix = attr[: -len("_path")]
        if prefix in prefixes:
            continue
        path = getattr(task, attr)
        key = getattr(task, f"{prefix}_key", None)
        if path is None or key is None:
            continue
        out.append((prefix, (path, key)))
    return out


# --------------------------------------------------------------------------
# config-schema scan (CTT103)

_CONFIG_VAR_NAMES = {"config", "conf", "tconf", "gconf", "task_config"}


def _config_reads(cls) -> List[Tuple[str, int]]:
    """Literal config-key reads in a class body: (key, absolute line)."""
    try:
        source, start = inspect.getsourcelines(cls)
    except (OSError, TypeError):
        return []
    try:
        tree = ast.parse("".join(source).strip() or "pass")
    except (SyntaxError, IndentationError):
        try:
            import textwrap

            tree = ast.parse(textwrap.dedent("".join(source)))
        except SyntaxError:
            return []
    # ``get_config`` classmethods assemble the *collection* of per-task
    # configs (keys are task names, not config keys) — out of scope here
    skip_nodes: Set[int] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == "get_config"
        ):
            skip_nodes.update(id(n) for n in ast.walk(node))
    reads: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if id(node) in skip_nodes:
            continue
        key = None
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.ctx, ast.Load)
            and isinstance(node.value, ast.Name)
            and node.value.id in _CONFIG_VAR_NAMES
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)
        ):
            key = node.slice.value
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in {"get", "pop"}
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in _CONFIG_VAR_NAMES
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            key = node.args[0].value
        if key is not None:
            reads.append((key, start + node.lineno - 1))
    return reads


def accepted_config_keys(cls) -> Set[str]:
    from ..runtime import config as cfg

    accepted = set(cfg.DEFAULT_GLOBAL_CONFIG) | set(cfg.DEFAULT_TASK_CONFIG)
    default_fn = getattr(cls, "default_task_config", None)
    if default_fn is not None:
        try:
            accepted |= set(default_fn())
        except Exception:
            pass
    return accepted


# --------------------------------------------------------------------------
# fused-chain declarations (CTT011, ctt-stream)


def _task_graph_key(task) -> str:
    try:
        return (
            f"{type(task).__module__}.{type(task).__qualname__}:"
            f"{task.output().path}"
        )
    except Exception:
        return f"{type(task).__module__}.{type(task).__qualname__}:<?>"


def validate_fused_chains(cls, wf, graph) -> List[Finding]:
    """Statically verify a workflow's declared fused chains over the
    sentinel-built DAG: every member a fusable split-protocol block task
    with declared halo/carry contracts, in-chain consumers implementing
    ``fused_read_batch``, and no out-of-chain consumer of an elided
    intermediate (eliding it would hand that consumer a dataset that never
    exists)."""
    anchor_path, anchor_line = _class_anchor(cls)

    def finding(msg: str) -> Finding:
        return Finding("CTT011", anchor_path, anchor_line,
                       f"{cls.__name__}: {msg}")

    get = getattr(wf, "fused_chains", None)
    if get is None:
        return []
    try:
        chains = list(get())
    except Exception as e:
        return [finding(
            f"fused_chains() raised under sentinel args "
            f"({type(e).__name__}: {e})"
        )]
    if not chains:
        return []

    from ..runtime import config as rcfg
    from ..runtime.task import BlockTask

    out: List[Finding] = []
    for chain in chains:
        members = list(chain.members)
        produced: Dict[Tuple[str, str], Any] = {}
        elided_pairs: Set[Tuple[str, str]] = set()
        for m in members:
            name = type(m).__name__
            if not isinstance(m, BlockTask) or not all(
                callable(getattr(m, attr, None))
                for attr in ("read_batch", "compute_batch", "write_batch")
            ) or not getattr(m, "fusable", False):
                out.append(finding(
                    f"chain '{chain.name}' member {name} is not a fusable "
                    "split-protocol block task"
                ))
                continue
            try:
                conf = dict(rcfg.DEFAULT_GLOBAL_CONFIG)
                conf.update(type(m).default_task_config())
                halo = m.fusion_halo(conf)
                if halo is not None:
                    tuple(int(h) for h in halo)
                inputs = list(m.fusion_inputs(conf) or [])
            except Exception as e:
                out.append(finding(
                    f"chain '{chain.name}' member {name} halo/carry "
                    f"contract undeclared ({type(e).__name__}: {e})"
                ))
                continue
            for pair in inputs:
                if pair in produced and (
                    type(m).fused_read_batch is BlockTask.fused_read_batch
                ):
                    out.append(finding(
                        f"chain '{chain.name}' member {name} consumes "
                        f"in-chain product {pair} but does not implement "
                        "fused_read_batch"
                    ))
            opath = getattr(m, "output_path", None)
            okey = getattr(m, "output_key", None)
            if opath is not None and okey is not None:
                produced[(opath, okey)] = m
                if m.identifier in chain.elide:
                    elided_pairs.add((opath, okey))

        if not elided_pairs:
            continue
        skip_keys = {_task_graph_key(t) for t in members}
        skip_keys |= {_task_graph_key(t) for t in chain.covers}
        for node in graph.nodes:
            if _task_graph_key(node) in skip_keys:
                continue
            for prefix, pair in consumed_pairs(node):
                if pair in elided_pairs:
                    out.append(finding(
                        f"{type(node).__name__} consumes elided "
                        f"intermediate {prefix}={pair} from outside chain "
                        f"'{chain.name}' — that dataset never exists when "
                        "the chain fuses"
                    ))
    return out


# --------------------------------------------------------------------------
# validation driver


def validate_workflow_class(cls) -> List[Finding]:
    findings: List[Finding] = []
    anchor_path, anchor_line = _class_anchor(cls)

    try:
        kwargs = synthesize_kwargs(cls)
        wf = cls(**kwargs)
        graph = TaskGraph([wf])
    except RecursionError:
        findings.append(Finding(
            "CTT101", anchor_path, anchor_line,
            f"{cls.__name__}: dependency cycle (requires() recursion "
            "never terminates)",
        ))
        return findings
    except Exception as e:
        findings.append(Finding(
            "CTT105", anchor_path, anchor_line,
            f"{cls.__name__}: DAG not statically buildable under default "
            f"flags ({type(e).__name__}: {e})",
        ))
        return findings

    if graph.cycle is not None:
        findings.append(Finding(
            "CTT101", anchor_path, anchor_line,
            f"{cls.__name__}: dependency cycle "
            f"{' -> '.join(graph.cycle)}",
        ))
        return findings

    external = {v for v in kwargs.values() if isinstance(v, str)}

    seen_classes: Set[type] = set()
    for task in graph.nodes:
        # -- CTT102: dataset provenance -----------------------------------
        upstream: Set[Tuple[str, str]] = set()
        for dep in graph.transitive_deps(task):
            upstream |= produced_pairs(dep)
        own = produced_pairs(task)
        for prefix, (path, key) in consumed_pairs(task):
            if (path, key) in upstream or (path, key) in own:
                continue
            if path in external and key in external:
                continue  # handed in at the workflow boundary
            findings.append(Finding(
                "CTT102", anchor_path, anchor_line,
                f"{cls.__name__}: {type(task).__name__} consumes "
                f"{prefix}=({path}, {key}) which no upstream task "
                "produces and which is not a workflow input",
            ))

        # -- CTT103: config keys (once per class) -------------------------
        tcls = type(task)
        if tcls in seen_classes:
            continue
        seen_classes.add(tcls)
        accepted = accepted_config_keys(tcls)
        src_path = inspect.getsourcefile(tcls) or anchor_path
        for key, line in _config_reads(tcls):
            if key not in accepted:
                findings.append(Finding(
                    "CTT103", src_path, line,
                    f"{tcls.__name__} reads config key '{key}' which is "
                    "not in the global schema nor its "
                    "default_task_config()",
                ))

    # -- CTT011: fused-chain declarations (ctt-stream) ----------------------
    findings.extend(validate_fused_chains(cls, wf, graph))

    # -- CTT104: slow reachability ----------------------------------------
    if not getattr(cls, "slow", False):
        for task in graph.nodes:
            if getattr(type(task), "slow", False):
                findings.append(Finding(
                    "CTT104", anchor_path, anchor_line,
                    f"{cls.__name__} reaches slow-marked task "
                    f"{type(task).__name__} but is not itself marked "
                    "slow — tier-1 entry points must stay fast",
                ))
    return findings


def validate_workflow_file(path: str) -> List[Finding]:
    try:
        mod = load_workflow_module(path)
    except Exception as e:
        return [Finding(
            "CTT105", path, 1,
            f"workflow module failed to import: {type(e).__name__}: {e}",
        )]
    findings: List[Finding] = []
    seen: Set[Finding] = set()
    for cls in discover_workflow_classes(mod):
        # the same task class (and thus config-read scan) appears under
        # multiple workflow roots — dedupe identical findings
        for f in validate_workflow_class(cls):
            if f not in seen:
                seen.add(f)
                findings.append(f)
    # graph findings are anchored in source files; apply that file's noqas
    by_file: Dict[str, List[Finding]] = {}
    for f in findings:
        by_file.setdefault(f.path, []).append(f)
    out: List[Finding] = []
    for fpath, fs in sorted(by_file.items()):
        try:
            with open(fpath) as fh:
                source = fh.read()
        except OSError:
            out.extend(fs)
            continue
        out.extend(filter_suppressed(fs, source))
    return out


def validate_workflows_dir(dirpath: str) -> List[Finding]:
    findings: List[Finding] = []
    for name in sorted(os.listdir(dirpath)):
        if not name.endswith(".py") or name == "__init__.py":
            continue
        findings.extend(validate_workflow_file(os.path.join(dirpath, name)))
    return findings
