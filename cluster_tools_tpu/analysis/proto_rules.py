"""ctt-proto AST rules (CTT2xx): shared-state protocol hygiene.

The filesystem IS the communication backend (leases, first-writer-wins
results, heartbeats) — these rules lint the writer/reader discipline that
keeps it race-free, against the artifact declarations in
``protocols.py``:

  CTT201  writes into state/queue/run dirs must ride the atomic helpers
          (``publish_once``, ``atomic_write_bytes``, or an inline
          tmp+``os.replace``) — a bare ``open(..., "w")`` in a producer
          module is a torn-write race: a concurrent reader sees a
          half-written record as protocol data.  Append mode stays legal
          (span shards, task logs).
  CTT202  check-then-act races: an ``exists()`` test followed by a write
          to the *same* path inside the guarded branch — between the two
          calls any peer may publish; use ``publish_once`` (exclusive
          link) or an unconditional atomic replace.
  CTT203  a ``publish_once``-family call whose won/lost return value is
          discarded — the lost-race branch is the protocol (a peer
          already parked a record there); every caller must branch on it.
  CTT204  clock-contract drift: staleness comparisons against a numeric
          multiple of a cadence (``age > 3 * interval``) must use the
          shared constants (``STALE_INTERVALS``/``STRAGGLER_K``), and
          ``stale_intervals``-style parameters must not re-declare the
          constant as a fresh literal default (extends CTT008 to the
          lease/beat grain).
  CTT205  ``faults.check``/``mangle`` site literals must be in
          ``faults.KNOWN_SITES`` — a typo'd site silently never fires —
          and (whole-tree, :func:`check_fault_site_coverage`) every
          KNOWN_SITES entry must keep >= 1 call site.
  CTT206  producer/consumer key drift against the artifact registry: a
          producer function's statically-written keys must cover its
          schema's required keys, and a consumer function's literal reads
          must stay inside the schema's key set.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, register_rule
from .protocols import (
    LEASE_MODULES,
    PRODUCER_MODULES,
    PUBLISH_WRAPPERS,
    _module_suffix,
    schemas_for_module,
)

register_rule(
    "CTT201", "bare open(..., 'w') into a shared state/queue/run dir"
)
register_rule(
    "CTT202", "exists()-then-write race on the same shared path"
)
register_rule(
    "CTT203", "publish_once-family return value discarded (lost race unhandled)"
)
register_rule(
    "CTT204", "staleness math re-declares the cadence constants as literals"
)
register_rule(
    "CTT205", "faults.check/mangle site literal not in faults.KNOWN_SITES"
)
register_rule(
    "CTT206", "artifact keys drift from the analysis/protocols.py registry"
)


def _dotted(node: ast.AST) -> Optional[str]:
    from .ast_rules import dotted_name

    return dotted_name(node)


def _leaf(node: ast.AST) -> str:
    name = _dotted(node)
    if name:
        return name.split(".")[-1]
    if isinstance(node, ast.Attribute):
        return node.attr  # method on a computed receiver: x[0].get(...)
    return ""


def _enclosing_functions(
    tree: ast.Module,
) -> Dict[int, ast.FunctionDef]:
    """id(node) -> nearest enclosing function def, for every node."""
    out: Dict[int, ast.FunctionDef] = {}

    def visit(node: ast.AST, current) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            current = node
        if current is not None:
            out[id(node)] = current
        for child in ast.iter_child_nodes(node):
            visit(child, current)

    visit(tree, None)
    return out


# --------------------------------------------------------------------------
# CTT201: bare write-mode open() in producer modules

_WRITE_MODES = {"w", "wb", "w+", "wb+", "w+b", "xt"}
_ATOMIC_LEAVES = {"replace", "link", "rename"}


def _open_write_mode(node: ast.Call) -> bool:
    if _dotted(node.func) not in {"open", "io.open"}:
        return False
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value in _WRITE_MODES
    return False


def _fn_has_atomic_commit(fn: Optional[ast.AST], tree: ast.Module) -> bool:
    """True when the open()'s enclosing scope also calls os.replace /
    os.link / os.rename — the inline tmp-then-commit idiom (heartbeat,
    metrics flush, atomic_write_bytes itself)."""
    scope = fn if fn is not None else tree
    for node in ast.walk(scope):
        if isinstance(node, ast.Call):
            name = _dotted(node.func) or ""
            parts = name.split(".")
            if parts[0] == "os" and parts[-1] in _ATOMIC_LEAVES:
                return True
    return False


def _check_atomic_writes(
    tree: ast.Module, path: str, findings: List[Finding]
) -> None:
    if _module_suffix(path) not in PRODUCER_MODULES:
        return
    enclosing = _enclosing_functions(tree)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _open_write_mode(node)):
            continue
        fn = enclosing.get(id(node))
        if _fn_has_atomic_commit(fn, tree):
            continue  # tmp + os.replace/link: atomic by construction
        findings.append(Finding(
            "CTT201", path, node.lineno,
            "bare write-mode open() in a shared-state producer module — "
            "a concurrent reader can see the half-written record; use "
            "atomic_write_bytes / publish_once (or commit a tmp file "
            "with os.replace)",
        ))


# --------------------------------------------------------------------------
# CTT202: exists() check then write to the same path

_EXISTS_LEAVES = {"exists", "isfile", "lexists"}
_WRITE_CALL_LEAVES = {"atomic_write_bytes", "write_bytes"}


def _exists_args(test: ast.expr) -> List[str]:
    """ast.dump of every path tested for existence inside an if-test."""
    out = []
    for node in ast.walk(test):
        if isinstance(node, ast.Call) and _leaf(node.func) in _EXISTS_LEAVES:
            if node.args:
                out.append(ast.dump(node.args[0]))
    return out


def _branch_writes(body: List[ast.stmt]) -> List[Tuple[str, int]]:
    """(ast.dump(path-arg), lineno) for every write call in a branch."""
    out = []
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            if _open_write_mode(node) and node.args:
                out.append((ast.dump(node.args[0]), node.lineno))
            elif _leaf(node.func) in _WRITE_CALL_LEAVES and node.args:
                out.append((ast.dump(node.args[0]), node.lineno))
    return out


def _check_check_then_act(
    tree: ast.Module, path: str, findings: List[Finding]
) -> None:
    if _module_suffix(path) not in PRODUCER_MODULES:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.If):
            continue
        tested = set(_exists_args(node.test))
        if not tested:
            continue
        for dump, lineno in _branch_writes(node.body) + _branch_writes(
            node.orelse
        ):
            if dump in tested:
                findings.append(Finding(
                    "CTT202", path, lineno,
                    "exists()-guarded write to the same path — a peer can "
                    "publish between the check and the write; use "
                    "publish_once (exclusive link) or an unconditional "
                    "atomic replace",
                ))


# --------------------------------------------------------------------------
# CTT203: discarded publish_once-family returns


def _check_publish_branching(
    tree: ast.Module, path: str, findings: List[Finding]
) -> None:
    wrappers_active = _module_suffix(path) in LEASE_MODULES
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)):
            continue
        leaf = _leaf(node.value.func)
        if leaf == "publish_once" or (wrappers_active and leaf in PUBLISH_WRAPPERS):
            findings.append(Finding(
                "CTT203", path, node.value.lineno,
                f"`{leaf}(...)` return value discarded — the False branch "
                "IS the protocol (a peer already parked a record there); "
                "branch on won/lost",
            ))


# --------------------------------------------------------------------------
# CTT204: staleness/cadence literals outside the shared constants

_CADENCE_TOKENS = ("lease", "interval", "cadence", "beat")
_CADENCE_PARAMS = ("stale_intervals", "straggler_k")


def _names_cadence(node: ast.AST) -> bool:
    name = _dotted(node)
    if not name:
        return False
    leaf = name.split(".")[-1].lower()
    return any(tok in leaf for tok in _CADENCE_TOKENS)


def _check_clock_contract(
    tree: ast.Module, path: str, findings: List[Finding]
) -> None:
    # (a) `age > 3 * interval`-style comparisons: the multiplier must be
    # the shared constant, or staleness policy forks per call site
    flagged: Set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Mult)):
                continue
            left, right = sub.left, sub.right
            for const, other in ((left, right), (right, left)):
                if (
                    isinstance(const, ast.Constant)
                    and isinstance(const.value, (int, float))
                    and not isinstance(const.value, bool)
                    and const.value >= 2
                    and _names_cadence(other)
                    and id(sub) not in flagged
                ):
                    flagged.add(id(sub))
                    findings.append(Finding(
                        "CTT204", path, sub.lineno,
                        f"staleness comparison multiplies a cadence by the "
                        f"literal {const.value!r} — use STALE_INTERVALS/"
                        "STRAGGLER_K (runtime/queue.py) so the expiry "
                        "policy cannot fork per call site",
                    ))
    # (b) re-declaring the constant as a parameter default
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        args = node.args
        all_args = args.posonlyargs + args.args + args.kwonlyargs
        defaults = ([None] * (len(args.posonlyargs) + len(args.args)
                              - len(args.defaults))
                    + list(args.defaults) + list(args.kw_defaults))
        for arg, default in zip(all_args, defaults):
            if default is None:
                continue
            if not any(tok in arg.arg.lower() for tok in _CADENCE_PARAMS):
                continue
            if (
                isinstance(default, ast.Constant)
                and isinstance(default.value, (int, float))
                and not isinstance(default.value, bool)
            ):
                findings.append(Finding(
                    "CTT204", path, default.lineno,
                    f"parameter `{arg.arg}` re-declares the staleness "
                    f"constant as the literal {default.value!r} — default "
                    "to the shared constant (runtime/queue.py) instead",
                ))


# --------------------------------------------------------------------------
# CTT205: fault-site literals vs faults.KNOWN_SITES

_FAULT_CALL_LEAVES = {"check", "mangle"}


def _fault_site_literal(node: ast.Call) -> Optional[str]:
    """The site string of a ``faults.check("x")``-style call, else None."""
    name = _dotted(node.func) or ""
    parts = name.split(".")
    if parts[-1] not in _FAULT_CALL_LEAVES:
        return None
    if len(parts) < 2 or "faults" not in parts[-2]:
        return None  # only faults-module receivers; dict.get etc. stay out
    if not node.args:
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return None


def _check_fault_sites(
    tree: ast.Module, path: str, findings: List[Finding]
) -> None:
    # import inside the check (the CTT010 idiom): the registry is the
    # faults module's own KNOWN_SITES constant
    from .. import faults

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        site = _fault_site_literal(node)
        if site is None:
            continue
        if site not in faults.KNOWN_SITES:
            findings.append(Finding(
                "CTT205", path, node.lineno,
                f"fault site '{site}' is not in faults.KNOWN_SITES — a "
                "typo'd site silently never fires; add it to SITE_DOCS "
                "or fix the literal",
            ))


def check_fault_site_coverage(paths) -> List[Finding]:
    """Whole-tree reverse check: every ``faults.KNOWN_SITES`` entry must
    keep >= 1 ``faults.check``/``mangle`` call site in the package source,
    or the documented chaos surface is dead weight.  Findings anchor at
    the site's SITE_DOCS line in ``faults/__init__.py``."""
    from .. import faults
    from .ast_rules import _iter_py_files

    seen: Set[str] = set()
    for path in _iter_py_files(paths):
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        has_fault_call = False
        site_literals: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                site = _fault_site_literal(node)
                if site is not None:
                    seen.add(site)
                name = _dotted(node.func) or ""
                parts = name.split(".")
                if (
                    len(parts) >= 2
                    and parts[-1] in _FAULT_CALL_LEAVES
                    and "faults" in parts[-2]
                ):
                    has_fault_call = True
            elif isinstance(node, ast.Constant) and isinstance(node.value, str):
                if node.value in faults.KNOWN_SITES:
                    site_literals.add(node.value)
        if has_fault_call:
            # the conditional-site idiom: `site = "a" if ... else "b";
            # faults.check(site)` — any KNOWN_SITES literal in a module
            # that fires injections counts as a live call site
            seen.update(site_literals)
    findings: List[Finding] = []
    faults_path = os.path.abspath(faults.__file__)
    try:
        with open(faults_path) as f:
            faults_lines = f.read().splitlines()
    except OSError:
        faults_lines = []
    for site in sorted(faults.KNOWN_SITES - seen):
        lineno = 1
        for i, text in enumerate(faults_lines, start=1):
            if f'"{site}"' in text:
                lineno = i
                break
        findings.append(Finding(
            "CTT205", faults_path, lineno,
            f"KNOWN_SITES entry '{site}' has no faults.check/mangle call "
            "site left in the package — remove it from SITE_DOCS or "
            "restore the injection point",
        ))
    return findings


# --------------------------------------------------------------------------
# CTT206: producer/consumer key drift against the registry


def _function_defs(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    out: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # first definition wins (shadowed nested defs are unlikely and
            # harmless for key collection)
            out.setdefault(node.name, node)
    return out


def _written_keys(fn: ast.FunctionDef) -> Set[str]:
    """String keys the function statically writes: dict-literal keys,
    ``d["k"] = v`` stores, and ``.setdefault("k", ...)`` calls."""
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for key in node.keys:
                if isinstance(key, ast.Constant) and isinstance(key.value, str):
                    keys.add(key.value)
        elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Store):
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                keys.add(sl.value)
        elif isinstance(node, ast.Call) and _leaf(node.func) == "setdefault":
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                keys.add(node.args[0].value)
    return keys


def _read_keys(fn: ast.FunctionDef) -> Dict[str, int]:
    """String keys the function statically reads (first lineno each):
    ``d["k"]`` loads and ``.get("k")`` calls."""
    keys: Dict[str, int] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                keys.setdefault(sl.value, node.lineno)
        elif isinstance(node, ast.Call) and _leaf(node.func) == "get":
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                keys.setdefault(node.args[0].value, node.lineno)
    return keys


def _check_key_drift(
    tree: ast.Module, path: str, findings: List[Finding], schemas=None
) -> None:
    if schemas is None:
        sites = schemas_for_module(path)
    else:
        sites = schemas_for_module(path, schemas)
    if not sites:
        return
    defs = _function_defs(tree)
    # a consumer shared by several schemas is judged against their union
    consumer_allowed: Dict[str, Set[str]] = {}
    for schema, role, fn_name in sites:
        if role == "consumer":
            consumer_allowed.setdefault(fn_name, set()).update(
                schema.key_types()
            )
    for schema, role, fn_name in sites:
        if role != "producer":
            continue
        fn = defs.get(fn_name)
        if fn is None:
            findings.append(Finding(
                "CTT206", path, 1,
                f"registry names `{fn_name}` as the producer of "
                f"'{schema.name}' but no such function exists here — "
                "update analysis/protocols.py with the rename",
            ))
            continue
        missing = set(schema.required) - _written_keys(fn)
        for key in sorted(missing):
            findings.append(Finding(
                "CTT206", path, fn.lineno,
                f"producer `{fn_name}` never writes required key "
                f"\"{key}\" of '{schema.name}' — every consumer of "
                "the artifact expects it",
            ))
    for fn_name, allowed in sorted(consumer_allowed.items()):
        fn = defs.get(fn_name)
        if fn is None:
            continue  # consumers may be refactored away harmlessly
        for key, lineno in sorted(_read_keys(fn).items()):
            if key not in allowed:
                findings.append(Finding(
                    "CTT206", path, lineno,
                    f"consumer `{fn_name}` reads key \"{key}\" outside "
                    "every schema it consumes — add the key to "
                    "analysis/protocols.py or fix the read",
                ))


def _dedupe(findings: List[Finding]) -> List[Finding]:
    seen: Set[Tuple[str, str, int, str]] = set()
    out = []
    for f in findings:
        key = (f.rule_id, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def check_proto_rules(
    tree: ast.Module, path: str, findings: List[Finding], schemas=None
) -> None:
    """Entry point called from ``ast_rules.lint_source`` on non-test
    files.  ``schemas`` overrides the artifact registry (fixture tests
    exercise the CTT206 machinery against synthetic declarations)."""
    pre = len(findings)
    _check_atomic_writes(tree, path, findings)
    _check_check_then_act(tree, path, findings)
    _check_publish_branching(tree, path, findings)
    _check_clock_contract(tree, path, findings)
    _check_fault_sites(tree, path, findings)
    _check_key_drift(tree, path, findings, schemas=schemas)
    findings[pre:] = _dedupe(findings[pre:])
