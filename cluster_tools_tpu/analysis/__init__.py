"""ctt-lint: static analysis for the TPU pipeline.

Three families of checks (see COMPONENTS.md, "Static analysis"):

  * AST invariant lints (CTT0xx) over ``ops/``, ``parallel/``,
    ``runtime/``, ``tasks/``, ``workflows/``, ``utils/`` and the marker /
    noqa hygiene rules over ``tests/`` — ``ast_rules.py``;
  * workflow-graph validation (CTT1xx) over every workflow's task DAG,
    built by instantiation with sentinel arguments, never executed —
    ``graph.py``;
  * shared-state protocol rules (CTT2xx) over the lease/heartbeat/result
    file protocols, against the artifact registry in ``protocols.py`` —
    ``proto_rules.py`` — plus the ``conformance`` CLI verb that validates
    a *real* state/run dir against the same registry.

CLI: ``python -m cluster_tools_tpu.analysis [--fail-on-findings]`` and
``python -m cluster_tools_tpu.analysis conformance <dir>``.
Suppression: ``# ctt: noqa[CTT003] reason``.
"""

from .core import Finding, REGISTRY, filter_suppressed, parse_suppressions
from .ast_rules import lint_paths, lint_source, registered_markers
from .conformance import conformance_report, run_conformance
from .graph import (
    validate_workflow_class,
    validate_workflow_file,
    validate_workflows_dir,
)
from .protocols import SCHEMAS, check_docstring_sync, schema_for_filename
from .proto_rules import check_fault_site_coverage

__all__ = [
    "Finding",
    "REGISTRY",
    "filter_suppressed",
    "parse_suppressions",
    "lint_paths",
    "lint_source",
    "registered_markers",
    "conformance_report",
    "run_conformance",
    "SCHEMAS",
    "check_docstring_sync",
    "schema_for_filename",
    "check_fault_site_coverage",
    "validate_workflow_class",
    "validate_workflow_file",
    "validate_workflows_dir",
]
