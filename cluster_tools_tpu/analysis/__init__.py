"""ctt-lint: static analysis for the TPU pipeline.

Two families of checks (see COMPONENTS.md, "Static analysis"):

  * AST invariant lints (CTT0xx) over ``ops/``, ``parallel/``,
    ``runtime/``, ``tasks/``, ``workflows/``, ``utils/`` and the marker /
    noqa hygiene rules over ``tests/`` — ``ast_rules.py``;
  * workflow-graph validation (CTT1xx) over every workflow's task DAG,
    built by instantiation with sentinel arguments, never executed —
    ``graph.py``.

CLI: ``python -m cluster_tools_tpu.analysis [--fail-on-findings]``.
Suppression: ``# ctt: noqa[CTT003] reason``.
"""

from .core import Finding, REGISTRY, filter_suppressed, parse_suppressions
from .ast_rules import lint_paths, lint_source, registered_markers
from .graph import (
    validate_workflow_class,
    validate_workflow_file,
    validate_workflows_dir,
)

__all__ = [
    "Finding",
    "REGISTRY",
    "filter_suppressed",
    "parse_suppressions",
    "lint_paths",
    "lint_source",
    "registered_markers",
    "validate_workflow_class",
    "validate_workflow_file",
    "validate_workflows_dir",
]
