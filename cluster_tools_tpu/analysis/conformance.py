"""``analysis conformance <dir>``: validate a real state/run dir against
the artifact registry.

The chaos smokes leave behind exactly the dirs this checks — a
SIGKILL-recovered serve state dir, a drained work queue — and protocol
conformance is what "recovered" means: every surviving file matches a
registered pattern, parses, carries its schema's required keys with the
right JSON types, and the serve job sequence stays dense (the fleet
recount is only sound on dense ids).

Exit codes (the CI contract):

  0  every recognized artifact conforms (torn tails of ``torn_ok``
     artifacts degrade to warnings — a killed writer is exactly the
     case the protocol is designed around)
  1  the dir holds no recognized artifact at all (nothing to judge —
     almost always a wrong path)
  2  malformed: unknown files, unparsable non-torn records, missing
     required keys, wrong types, or serve-job seq gaps
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Tuple

from ..utils.store_backend import backend_for
from .protocols import (
    ArtifactSchema,
    check_value_type,
    schema_for_filename,
)

__all__ = ["conformance_report", "run_conformance"]

_SERVE_JOB_ID_RE = re.compile(r"^job\.j(\d{6})\.json$")


def _check_record(
    rel: str, rec, schema: ArtifactSchema, problems: List[str]
) -> None:
    if not isinstance(rec, dict):
        problems.append(f"{rel}: top-level JSON is not an object")
        return
    for key, spec in schema.required.items():
        if key not in rec:
            problems.append(
                f"{rel}: missing required key \"{key}\" "
                f"({schema.name})"
            )
        elif not check_value_type(rec[key], spec):
            problems.append(
                f"{rel}: key \"{key}\" = {rec[key]!r} is not {spec} "
                f"({schema.name})"
            )
    for key, spec in schema.optional.items():
        if key in rec and not check_value_type(rec[key], spec):
            problems.append(
                f"{rel}: key \"{key}\" = {rec[key]!r} is not {spec} "
                f"({schema.name})"
            )
    if schema.closed:
        for key in sorted(set(rec) - set(schema.key_types())):
            problems.append(
                f"{rel}: unknown key \"{key}\" in closed schema "
                f"{schema.name}"
            )


def _check_jsonl(
    backend, path: str, rel: str, schema: ArtifactSchema,
    problems: List[str], warnings: List[str],
) -> None:
    try:
        raw = backend.read_bytes(path)
    except OSError as e:
        problems.append(f"{rel}: unreadable: {e}")
        return
    lines = raw.decode("utf-8", errors="replace").splitlines()
    if not lines:
        warnings.append(f"{rel}: empty span shard (writer died pre-header)")
        return
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            if i == len(lines) - 1 and schema.torn_ok:
                warnings.append(
                    f"{rel}: torn tail line (killed writer) — tolerated"
                )
            else:
                problems.append(f"{rel}: unparsable line {i + 1}")
            continue
        if i == 0:
            _check_record(rel, rec, schema, problems)
            if isinstance(rec, dict) and rec.get("type") != "header":
                problems.append(f"{rel}: line 1 is not the header record")
        elif not (isinstance(rec, dict) and isinstance(rec.get("type"), str)):
            problems.append(f"{rel}: line {i + 1} has no \"type\"")


def _walk_files(backend, root):
    """Depth-first (dirs after their files, both sorted) ``(path, rel)``
    pairs under ``root`` through the store backend — the one walk that
    serves POSIX dirs and object-store prefixes (``http(s)://``/``s3://``)
    alike, so conformance judges a diskless fleet's surviving state dir
    exactly like a local one."""
    prefix = root.rstrip("/")
    stack = [prefix]
    while stack:
        d = stack.pop()
        subdirs = []
        for name in sorted(backend.listdir(d)):
            path = backend.join(d, name)
            if backend.isdir(path):
                subdirs.append(path)
                continue
            yield path, path[len(prefix):].lstrip("/")
        # reversed push: pop() then visits subdirs in sorted order
        stack.extend(reversed(subdirs))


def conformance_report(
    root: str,
) -> Tuple[List[str], List[str], int]:
    """(problems, warnings, recognized_artifact_count) for one dir tree
    (POSIX path or object-store prefix)."""
    problems: List[str] = []
    warnings: List[str] = []
    recognized = 0
    job_seqs: Dict[int, int] = {}  # filename seq -> record seq (or -1)
    backend = backend_for(root)
    if not backend.isdir(root):
        return [f"{root}: not a directory"], warnings, 0
    for path, rel in _walk_files(backend, root):
        name = os.path.basename(rel)
        if ".tmp" in name:
            continue  # staging debris of a killed atomic writer
        schema = schema_for_filename(name)
        if schema is None:
            problems.append(
                f"{rel}: unknown file — no registered artifact "
                "pattern matches (analysis/protocols.py)"
            )
            continue
        recognized += 1
        if schema.jsonl:
            _check_jsonl(backend, path, rel, schema, problems,
                         warnings)
            continue
        try:
            rec = json.loads(backend.read_bytes(path).decode("utf-8"))
        except OSError as e:
            problems.append(f"{rel}: unreadable: {e}")
            continue
        except ValueError:
            if schema.torn_ok:
                warnings.append(
                    f"{rel}: torn record (killed writer) — readers "
                    "age it from mtime; tolerated"
                )
            else:
                problems.append(f"{rel}: unparsable JSON")
            continue
        _check_record(rel, rec, schema, problems)
        m = _SERVE_JOB_ID_RE.match(name)
        if m and isinstance(rec, dict):
            seq = rec.get("seq")
            job_seqs[int(m.group(1))] = (
                int(seq) if isinstance(seq, int) else -1
            )
    # serve-job density: ids are a dense sequence from j000001 — the fleet
    # admission recount and the stats index frontier both rely on it
    if job_seqs:
        ids = sorted(job_seqs)
        expected = list(range(ids[0], ids[0] + len(ids)))
        if ids != expected:
            gaps = sorted(set(expected) - set(ids))
            problems.append(
                "serve job sequence has gaps at "
                + ", ".join(f"j{g:06d}" for g in gaps)
                + " — dense ids are the admission-recount invariant"
            )
        for fid, seq in sorted(job_seqs.items()):
            if seq != fid:
                problems.append(
                    f"job.j{fid:06d}.json: record seq {seq} does not "
                    "match its filename id"
                )
    return problems, warnings, recognized


def run_conformance(root: str) -> int:
    problems, warnings, recognized = conformance_report(root)
    for msg in warnings:
        print(f"warning: {msg}")
    for msg in problems:
        print(f"FAIL: {msg}")
    if problems:
        print(
            f"conformance: {root}: {len(problems)} problem(s), "
            f"{len(warnings)} warning(s), {recognized} artifact(s)"
        )
        return 2
    if recognized == 0:
        print(f"conformance: {root}: no recognized artifacts")
        return 1
    print(
        f"conformance: {root}: OK — {recognized} artifact(s), "
        f"{len(warnings)} warning(s)"
    )
    return 0
