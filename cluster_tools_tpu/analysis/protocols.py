"""ctt-proto: the machine-readable registry of shared-state artifacts.

Every file two processes communicate through — queue manifests, leases,
results, heartbeats, fleet beats, serve-daemon job records — is declared
here once: filename pattern, key schema (required + optional, with JSON
types), the functions that statically produce and consume it, and its
torn-read semantics.  The prose twin of this registry is the
``obs/trace.py`` module docstring ("Run-directory file formats" etc.);
:func:`check_docstring_sync` keeps the two from drifting, and the CTT2xx
rules in ``proto_rules.py`` plus the ``analysis conformance <dir>`` CLI
verb enforce the declarations against the code and against real state
dirs.

Vocabulary:

* **producers** — ``(module_suffix, function_name)`` pairs whose dict
  literals / subscript stores must statically cover the artifact's
  required keys (CTT206 producer side).  Producers that assemble the
  record by merging a caller-supplied dict (``serve/jobs.py complete``,
  ``submit``) cannot be checked statically and are listed under
  ``merge_producers`` for documentation; the conformance verb checks
  their output at runtime instead.
* **consumers** — ``(module_suffix, function_name)`` pairs whose literal
  ``rec["k"]`` / ``rec.get("k")`` reads must stay inside the schema's
  key set (CTT206 consumer side).  A function consuming several
  artifacts (``runtime/queue.py aggregate`` reads leases *and* results)
  is judged against the union of every schema that names it.
* **torn_ok** — readers of this artifact already tolerate a torn/partial
  record (the mtime-ageing convention for leases and beats, the tail
  line of an append-only span shard); conformance degrades a torn file
  to a warning instead of a failure.
* **closed** — the key set is exhaustive: conformance flags unknown keys.
  Open schemas (fleet beats carry ``info_fn`` extras, job records carry
  workflow kwargs) only get required/optional keys type-checked.

Type grammar for key specs: ``str int number bool list dict any``,
``|``-joined for alternatives, with ``null`` allowing None
(``"str|null"``).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

__all__ = [
    "ArtifactSchema",
    "SCHEMAS",
    "PRODUCER_MODULES",
    "LEASE_MODULES",
    "PUBLISH_WRAPPERS",
    "schema_for_filename",
    "schemas_for_module",
    "check_value_type",
    "check_docstring_sync",
]

Site = Tuple[str, str]  # (module path suffix, function name)


@dataclass(frozen=True)
class ArtifactSchema:
    name: str
    pattern: str  # regex over the file's basename
    description: str
    required: Dict[str, str] = field(default_factory=dict)
    optional: Dict[str, str] = field(default_factory=dict)
    producers: Tuple[Site, ...] = ()
    merge_producers: Tuple[Site, ...] = ()
    consumers: Tuple[Site, ...] = ()
    torn_ok: bool = False
    closed: bool = False
    jsonl: bool = False  # span shards: header line + record lines
    # schemas whose prose lives elsewhere (obs/heartbeat.py defers its
    # field list) are skipped by the docstring-sync check
    doc_in_trace: bool = True

    def matches(self, basename: str) -> bool:
        return re.match(self.pattern, basename) is not None

    def key_types(self) -> Dict[str, str]:
        out = dict(self.required)
        out.update(self.optional)
        return out


SCHEMAS: Tuple[ArtifactSchema, ...] = (
    # -- obs run dir (everything obs.live tails) ----------------------------
    ArtifactSchema(
        name="trace_spans",
        pattern=r"^spans\.p\d+\.t\d+\.jsonl$",
        description="append-only span shard: header line then span records",
        required={  # the header record; span lines are checked separately
            "type": "str", "run": "str|null", "pid": "int", "tid": "int",
            "host": "str", "wall": "number", "mono": "number",
        },
        producers=(("obs/trace.py", "_shard"),),
        consumers=(),
        torn_ok=True,  # a SIGKILL mid-line tears exactly the tail line
        jsonl=True,
    ),
    ArtifactSchema(
        name="metrics_snapshot",
        pattern=r"^metrics\.p\d+\.json$",
        description="per-process counter/gauge snapshot, atomically replaced",
        required={"counters": "dict", "gauges": "dict"},
        # snapshot() builds the record; flush() commits it verbatim
        producers=(("obs/metrics.py", "snapshot"),),
        merge_producers=(("obs/metrics.py", "flush"),),
        consumers=(("obs/live.py", "_read_metrics"),),
        closed=True,
    ),
    ArtifactSchema(
        name="hist_snapshot",
        pattern=r"^hist\.p\d+\.json$",
        description="ctt-slo per-process latency-histogram snapshot, "
        "atomically replaced; fixed bucket edges make cross-process "
        "merge exact (bucket-wise addition)",
        required={"schema": "int", "edges": "list", "hists": "list"},
        producers=(("obs/hist.py", "snapshot"),),
        merge_producers=(("obs/hist.py", "flush"),),
        consumers=(),  # load_run_hists/merge_into read per-series dicts
        closed=True,
    ),
    ArtifactSchema(
        name="heartbeat",
        pattern=r"^hb\.p\d+\.json$",
        description="ctt-watch per-process liveness/progress beat",
        required={
            "pid": "int", "host": "str", "role": "str", "job_id": "any",
            "process_id": "any", "run": "str|null", "wall": "number",
            "mono": "number", "interval_s": "number", "seq": "int",
            "exiting": "bool", "task": "str|null", "blocks_total": "int",
            "blocks_done": "int", "blocks_failed": "int",
            "blocks_retried": "int", "grid": "any", "current_blocks": "list",
            "queue_depth": "int|null", "draining": "bool",
            "device_mem_peak_bytes": "number|null",
        },
        producers=(("obs/heartbeat.py", "_write_beat"),),
        consumers=(("obs/live.py", "_worker_rows"),),
        doc_in_trace=False,  # trace.py defers to obs/heartbeat.py for fields
    ),
    # -- ctt-steal work queue (<job_dir>/queue/) ----------------------------
    ArtifactSchema(
        name="queue_manifest",
        pattern=r"^manifest\.json$",
        description="work-queue item list, written once by the driver",
        required={
            "task": "str", "items": "list", "lease_s": "number",
            "duplicate": "bool", "created_wall": "number",
        },
        producers=(("runtime/queue.py", "create"),),
        consumers=(("runtime/queue.py", "__init__"),),
        closed=True,
    ),
    ArtifactSchema(
        name="queue_lease",
        pattern=r"^lease\.\d+\.g\d+\.json$",
        description="generation-g item ownership, re-stamped every lease_s",
        required={
            "item": "int", "gen": "int", "blocks": "list",
            "owner_pid": "int", "job_id": "any", "host": "str",
            "claim_wall": "number", "wall": "number", "mono": "number",
        },
        producers=(("runtime/queue.py", "_lease_payload"),),
        consumers=(
            ("runtime/queue.py", "_lease_age_s"),
            ("runtime/queue.py", "_claim_duplicate"),
            ("runtime/queue.py", "aggregate"),
        ),
        torn_ok=True,  # torn stamp ages from mtime (documented convention)
        closed=True,
    ),
    ArtifactSchema(
        name="queue_result",
        pattern=r"^result\.\d+\.json$",
        description="item terminal record, published first-writer-wins",
        required={
            "item": "int", "gen": "int", "done": "list", "failed": "list",
            "errors": "dict", "pid": "int", "job_id": "any",
            "duplicate": "bool", "seconds": "number", "wall": "number",
        },
        producers=(("runtime/queue.py", "complete"),),
        consumers=(
            ("runtime/queue.py", "aggregate"),
            ("runtime/queue.py", "_item_median_s"),
        ),
        closed=True,
    ),
    ArtifactSchema(
        name="config_file",
        pattern=r"^[A-Za-z0-9_.-]+\.config$",
        description="merged-over-defaults config JSON (global/task/serve)",
        required={},  # free-form dict; the defaults tables own the keys
        producers=(("runtime/config.py", "write_config"),),
        consumers=(),
        doc_in_trace=False,
    ),
    # -- ctt-serve daemon state dir -----------------------------------------
    ArtifactSchema(
        name="serve_endpoint",
        pattern=r"^serve\.json$",
        description="daemon endpoint + auth token, mode 0600",
        required={
            "host": "str", "port": "int", "pid": "int", "daemon_id": "str",
            "started_wall": "number", "run_id": "str|null", "token": "str",
        },
        producers=(("serve/server.py", "start"),),
        consumers=(),  # clients read via serve/client.py read_endpoint
        closed=True,
    ),
    ArtifactSchema(
        name="serve_job",
        pattern=r"^job\.j\d{6}\.json$",
        description="one submission, published exactly once (dense seq)",
        required={
            "id": "str", "seq": "int", "schema": "int|str",
            "workflow": "str", "tenant": "str", "submit_wall": "number",
        },
        optional={
            "type": "str", "kwargs": "dict", "configs": "dict",
            "priority": "int", "daemon": "str|null", "admitted": "bool",
            # ctt-microbatch: explicit False opts the job out of
            # cross-tenant aggregation (absent/True = eligible)
            "microbatch": "bool",
        },
        merge_producers=(
            # submit() stamps id/seq/submit_wall/daemon/admitted over the
            # validate_submission record — the union is only visible at
            # runtime, so the conformance verb owns this contract
            ("serve/jobs.py", "submit"),
            ("serve/protocol.py", "validate_submission"),
        ),
        consumers=(
            # server._run_job also reads the record ("tenant"/"workflow"/
            # "type") but mixes in metric-snapshot reads — function-granular
            # key checking would false-positive, so it stays undeclared
            ("serve/jobs.py", "_index_advance_locked"),
            ("serve/jobs.py", "_reap_limbo"),
            ("serve/jobs.py", "pending"),
            ("serve/jobs.py", "claim_next"),
        ),
    ),
    ArtifactSchema(
        name="serve_lease",
        pattern=r"^lease\.j\d{6}\.g\d+\.json$",
        description="generation-g job ownership, re-stamped every lease_s",
        required={
            "job": "str", "gen": "int", "owner_pid": "int",
            "daemon": "str|null", "claim_wall": "number", "wall": "number",
            "mono": "number",
        },
        # released=true: the owner gave the job back voluntarily (drain
        # suspend of a long-lived ingest stream) — stamped with wall=0 so
        # the lease classifies expired immediately, and excluded from the
        # generation budget on quarantine accounting.
        # dispatch_wall (ctt-slo): when this generation's execution began
        # after any microbatch aggregation window — the claim→dispatch
        # span is the window-wait phase ``obs journey`` reads back
        optional={"released": "bool", "dispatch_wall": "number"},
        producers=(("serve/jobs.py", "_lease_payload"),),
        consumers=(
            ("serve/jobs.py", "_stamp_age_s"),
            ("serve/jobs.py", "_lease_state"),
            ("serve/jobs.py", "_released_gens"),
            ("obs/journey.py", "_lease_row"),
        ),
        torn_ok=True,
        closed=True,
    ),
    ArtifactSchema(
        name="serve_admit",
        pattern=r"^admit\.j\d{6}\.json$",
        description="ctt-fleet two-phase admission marker, exclusive link",
        required={"id": "str", "wall": "number", "daemon": "str|null"},
        producers=(("serve/jobs.py", "admit"),),
        consumers=(),  # presence-only reads (the _scan admit set)
        closed=True,
    ),
    ArtifactSchema(
        name="serve_result",
        pattern=r"^result\.j\d{6}\.json$",
        description="job terminal record, first writer wins",
        required={
            "id": "str", "gen": "int", "ok": "bool", "pid": "int",
            "daemon": "str|null", "finished_wall": "number",
        },
        optional={
            "error": "str|null", "seconds": "number", "warm": "bool",
            "compile_cache": "dict", "tenant": "str|null",
            "rejected": "bool", "quarantined": "bool", "failure_log": "list",
            # ctt-microbatch annotation: {"jobs": n, "index": i} when the
            # job rode an aggregation window (+"split": true when it was
            # re-dispatched individually after a batch-path failure)
            "microbatch": "dict",
            # ctt-slo phase walls: the winning generation's claim /
            # execution-start / publish stamps, so the per-job phase
            # breakdown (``obs journey``) reconstructs from the terminal
            # record alone even after the leases are gone
            "claimed_wall": "number", "dispatch_wall": "number",
            "published_wall": "number",
        },
        producers=(
            ("serve/jobs.py", "retract"),
            ("serve/jobs.py", "_quarantine"),
        ),
        merge_producers=(
            # complete() stamps identity keys over the server-built result
            ("serve/jobs.py", "complete"),
            ("serve/server.py", "_run_job"),
        ),
        consumers=(("serve/jobs.py", "get"),),
    ),
    ArtifactSchema(
        name="fleet_beat",
        pattern=r"^daemon\.[A-Za-z0-9_.-]+\.json$",
        description="ctt-fleet daemon heartbeat, atomically replaced",
        required={
            "id": "str", "pid": "int", "wall": "number", "mono": "number",
            "interval_s": "number", "seq": "int", "exiting": "bool",
        },
        optional={
            "host": "str", "port": "int", "draining": "bool",
            "running_jobs": "int", "queued": "int", "concurrency": "int",
            "info_error": "str",
        },
        producers=(("serve/fleet.py", "beat"),),
        consumers=(
            ("serve/fleet.py", "_beat_age_s"),
            ("serve/fleet.py", "is_dead"),
        ),
        torn_ok=True,  # read_peers degrades a torn beat to {"torn": True}
    ),
    ArtifactSchema(
        name="fleet_snap",
        pattern=r"^snap\.[A-Za-z0-9_.-]+\.json$",
        description="ctt-slo per-daemon metrics+histogram snapshot, "
        "published into the SHARED state dir on the fleet-beat cadence "
        "— ``obs fleet`` merges every daemon's snap into one rollup",
        required={
            "schema": "int", "daemon": "str", "pid": "int",
            "wall": "number", "counters": "dict", "gauges": "dict",
            "hists": "dict",
        },
        producers=(("serve/server.py", "_publish_snapshot"),),
        consumers=(("obs/slo.py", "merge_fleet"),),
        torn_ok=True,  # best-effort beat-side write; readers skip torn
        closed=True,
    ),
    ArtifactSchema(
        name="supervisor_state",
        pattern=r"^supervisor\.[A-Za-z0-9_.-]+\.json$",
        description="ctt-diskless supervisor decision record, "
        "observational only (never a scaling input)",
        required={
            "id": "str", "pid": "int", "wall": "number", "mono": "number",
            "interval_s": "number", "seq": "int", "exiting": "bool",
            "target_daemons": "int",
        },
        optional={
            "host": "str", "active": "int", "action": "str",
            "reason": "str",
        },
        producers=(("serve/supervisor.py", "_publish_state"),),
        consumers=(),  # by design: a restarted supervisor reads beats
        torn_ok=True,  # best-effort PUT, the beat convention
        closed=True,
        doc_in_trace=False,  # field list lives in serve/supervisor.py
    ),
    # -- ctt-ingest control dir (the growing source's prefix) ---------------
    ArtifactSchema(
        name="ingest_manifest",
        pattern=r"^ingest\.manifest\.json$",
        description="stream geometry, published once by the writer",
        required={
            "schema": "int", "domain": "str", "shape": "list",
            "slab_depth": "int", "slabs_total": "int",
            "created_wall": "number",
        },
        producers=(("ingest/source.py", "publish_manifest"),),
        consumers=(("ingest/source.py", "manifest"),),
        closed=True,
    ),
    ArtifactSchema(
        name="ingest_slab_marker",
        pattern=r"^slab\.\d{6}\.json$",
        description="per-slab landing marker, create-only after data lands",
        required={"slab": "int", "wall": "number"},
        optional={"digest": "str"},
        producers=(("ingest/source.py", "publish_slab"),),
        consumers=(("ingest/source.py", "poll"),),
        torn_ok=True,  # a torn marker is skipped until a later poll
        closed=True,
    ),
    ArtifactSchema(
        name="ingest_carry",
        pattern=r"^ingest\.carry\.s\d{6}\.json$",
        description="per-slab carry snapshot, create-only after commit",
        required={
            "schema": "int", "chain": "str", "slab": "int",
            "slabs_done": "int", "carry": "str", "carry_bytes": "int",
            "cap_hint": "dict", "wall": "number",
        },
        producers=(("ingest/runner.py", "_persist_carry"),),
        consumers=(("ingest/runner.py", "_load_carry"),),
        torn_ok=True,  # an unreadable record falls back to the previous one
        closed=True,
    ),
    ArtifactSchema(
        name="ingest_frontier",
        pattern=r"^ingest\.frontier\.json$",
        description="commit frontier, atomically replaced per slab",
        required={
            "schema": "int", "slabs_done": "int", "slabs_total": "int",
            "resumes": "int", "wall": "number",
        },
        producers=(("ingest/runner.py", "_publish_frontier"),),
        consumers=(("ingest/runner.py", "_read_frontier"),),
        torn_ok=True,  # advisory progress record; carry records are truth
        closed=True,
    ),
)


# -- module scoping for the CTT2xx rules ------------------------------------

# modules that write into shared state/queue/run dirs: bare open(..., "w")
# here is a torn-write race (CTT201) and exists()->write a TOCTOU (CTT202)
PRODUCER_MODULES = frozenset({
    "runtime/queue.py",
    "runtime/cluster_executor.py",
    "runtime/cluster_worker.py",
    "runtime/config.py",
    "runtime/task.py",
    "serve/jobs.py",
    "serve/fleet.py",
    "serve/server.py",
    "serve/supervisor.py",
    "serve/admission.py",
    "obs/heartbeat.py",
    "obs/hist.py",
    "obs/metrics.py",
    "obs/trace.py",
    "utils/store_backend.py",
    "ingest/source.py",
    "ingest/runner.py",
})

# modules where a discarded publish_once-family return value loses the
# lost-race branch (CTT203)
LEASE_MODULES = frozenset({
    "runtime/queue.py",
    "runtime/cluster_executor.py",
    "serve/jobs.py",
    "serve/server.py",
})

# methods that return publish_once's won/lost bool and must be branched on
# inside LEASE_MODULES (publish_once itself is checked everywhere)
PUBLISH_WRAPPERS = frozenset({
    "admit", "retract", "complete", "_reap_limbo", "_try_claim",
})


def _module_suffix(path: str) -> str:
    """Last two path components, normalized — the registry's module key."""
    parts = os.path.normpath(path).replace(os.sep, "/").split("/")
    return "/".join(parts[-2:])


def schema_for_filename(basename: str) -> Optional[ArtifactSchema]:
    for schema in SCHEMAS:
        if schema.matches(basename):
            return schema
    return None


def schemas_for_module(path: str, schemas=SCHEMAS):
    """(schema, role, function) triples whose producer/consumer site lives
    in ``path`` — the per-file work list for CTT206."""
    suffix = _module_suffix(path)
    out = []
    for schema in schemas:
        for mod, fn in schema.producers:
            if mod == suffix:
                out.append((schema, "producer", fn))
        for mod, fn in schema.consumers:
            if mod == suffix:
                out.append((schema, "consumer", fn))
    return out


# -- JSON type grammar -------------------------------------------------------

def check_value_type(value, spec: str) -> bool:
    """True when ``value`` satisfies a ``"str|int|null"``-style spec."""
    for alt in spec.split("|"):
        alt = alt.strip()
        if alt == "any":
            return True
        if alt == "null" and value is None:
            return True
        if alt == "str" and isinstance(value, str):
            return True
        if alt == "bool" and isinstance(value, bool):
            return True
        if alt == "int" and isinstance(value, int) and not isinstance(value, bool):
            return True
        if alt == "number" and isinstance(value, (int, float)) \
                and not isinstance(value, bool):
            return True
        if alt == "list" and isinstance(value, list):
            return True
        if alt == "dict" and isinstance(value, dict):
            return True
    return False


# -- docstring sync ----------------------------------------------------------

def check_docstring_sync() -> list:
    """The ``obs/trace.py`` docstring documents every registered artifact:
    each schema's required keys must appear as quoted names in the prose
    (schemas with ``doc_in_trace=False`` defer their field list to their
    own module and are skipped).  Returns human-readable drift messages —
    empty means the prose and the registry agree."""
    from ..obs import trace as trace_mod

    doc = trace_mod.__doc__ or ""
    problems = []
    for schema in SCHEMAS:
        if not schema.doc_in_trace:
            continue
        for key in schema.required:
            if f'"{key}"' not in doc:
                problems.append(
                    f"{schema.name}: required key \"{key}\" is not "
                    "documented in the obs/trace.py docstring"
                )
    return problems
