"""ctt-serve admission control: who gets into the queue, and when not.

Two gates, both evaluated against the durable queue's live accounting
(:meth:`serve.jobs.JobQueue.stats`) at submission time:

  * **queue depth** — total unfinished jobs (queued + running) at or over
    ``max_queue_depth`` rejects the submission.  Backpressure, not
    buffering: a client is told *now* that the daemon is saturated
    (HTTP 429) instead of its job aging silently at the queue tail.
  * **tenant quota** — per-tenant in-flight ceiling (``tenant_quota``
    default, ``tenant_quotas[name]`` override, None disables): one noisy
    tenant cannot occupy the whole queue; everyone else's admission
    headroom is what the quota leaves free.

Rejections count as ``serve.quota_rejections`` (the lease-budget analog
of the steal queue's admission role: here a *job* lease you cannot take
yet is simply a job the daemon refuses to enqueue).

The controller itself is stateless — it judges whatever ``stats`` dict
it is handed.  Fleet consistency (ctt-fleet) therefore lives entirely in
*which* stats the daemon passes: the two-phase flow publishes the record
provisionally, recounts the **shared state dir** restricted to
earlier-sequence jobs (``JobQueue.stats(before_seq=...)``), and only then
admits — so k daemons over one state dir enforce ONE queue-depth and ONE
per-tenant ceiling between them, instead of each admitting a full quota.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from ..obs import metrics as obs_metrics

__all__ = ["AdmissionController"]


class AdmissionController:
    def __init__(
        self,
        max_queue_depth: Optional[int] = 64,
        tenant_quota: Optional[int] = 8,
        tenant_quotas: Optional[Dict[str, int]] = None,
    ):
        # None disables a gate; 0 is a real ceiling ("admit nothing"),
        # so normalize on identity, not truthiness
        self.max_queue_depth = (
            int(max_queue_depth) if max_queue_depth is not None else None
        )
        self.tenant_quota = (
            int(tenant_quota) if tenant_quota is not None else None
        )
        self.tenant_quotas = {
            str(k): int(v) for k, v in (tenant_quotas or {}).items()
        }

    def quota_for(self, tenant: str) -> Optional[int]:
        return self.tenant_quotas.get(tenant, self.tenant_quota)

    def describe(self) -> Dict[str, Any]:
        """The configured limits, for ``/healthz`` — alongside the live
        decision inputs (queued / in_flight / per-tenant counts) an
        operator needs to see *why* a submission was rejected."""
        return {
            "max_queue_depth": self.max_queue_depth,
            "tenant_quota": self.tenant_quota,
            "tenant_quotas": dict(self.tenant_quotas),
        }

    def admit(self, tenant: str,
              stats: Dict[str, Any]) -> Tuple[bool, Optional[str]]:
        """(admitted, reason-if-not) for one submission given the queue's
        current accounting."""
        if (
            self.max_queue_depth is not None
            and stats.get("in_flight", 0) >= self.max_queue_depth
        ):
            obs_metrics.inc("serve.quota_rejections")
            return False, (
                f"queue full: {stats['in_flight']} jobs in flight "
                f">= max_queue_depth {self.max_queue_depth}"
            )
        quota = self.quota_for(tenant)
        if quota is not None:
            used = stats.get("per_tenant", {}).get(tenant, 0)
            if used >= quota:
                obs_metrics.inc("serve.quota_rejections")
                return False, (
                    f"tenant {tenant!r} quota exhausted: {used} jobs in "
                    f"flight >= quota {quota}"
                )
        return True, None
