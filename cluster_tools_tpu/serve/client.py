"""ctt-serve client: submit workflows to a running daemon and wait.

Discovery is file-based: the daemon publishes ``serve.json`` (host, port,
pid, run id, auth token) into its state dir with mode 0600;
``ServeClient(state_dir)`` reads it — being able to read the file IS the
authorization, and the client sends the token on every request.  When
constructed from a bare ``endpoint`` URL instead, pass ``token=``
explicitly.  Everything else is four tiny HTTP calls over loopback
(stdlib urllib — a client must not drag jax in just to submit).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

from ..obs import trace as obs_trace
from ..utils import store_backend
from .server import ENDPOINT_NAME

__all__ = ["QuotaRejected", "ServeClient", "read_endpoint"]


class QuotaRejected(RuntimeError):
    """The daemon refused admission (429: queue depth or tenant quota)."""


class JobFailed(RuntimeError):
    """The daemon executed the job and it failed."""


def read_endpoint(state_dir: str) -> Dict[str, Any]:
    # routes through the store backend so ``http(s)://``/``s3://`` state
    # dirs (ctt-diskless) resolve exactly like POSIX ones; on a remote
    # store the credential that reads the prefix IS the authorization
    backend = store_backend.backend_for(state_dir)
    raw = backend.read_bytes(backend.join(state_dir, ENDPOINT_NAME))
    return json.loads(raw.decode())


class ServeClient:
    def __init__(
        self,
        state_dir: Optional[str] = None,
        endpoint: Optional[str] = None,
        timeout_s: float = 30.0,
        token: Optional[str] = None,
    ):
        if state_dir is not None and (endpoint is None or token is None):
            ep = read_endpoint(state_dir)
            if endpoint is None:
                endpoint = f"http://{ep['host']}:{ep['port']}"
            if token is None:
                token = ep.get("token")
        if endpoint is None:
            raise ValueError("need state_dir or endpoint")
        self.base = endpoint.rstrip("/")
        self.token = token
        self.timeout_s = float(timeout_s)

    # -- raw HTTP ------------------------------------------------------------

    def _headers(self) -> Dict[str, str]:
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["X-CTT-Serve-Token"] = self.token
        return headers

    def _request(self, method: str, path: str,
                 payload: Optional[dict] = None):
        req = urllib.request.Request(
            self.base + path,
            data=(
                json.dumps(payload).encode() if payload is not None else None
            ),
            headers=self._headers(),
            method=method,
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                body = resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            try:
                reason = json.loads(detail).get("reason", detail)
            except ValueError:
                reason = detail
            if e.code == 429:
                raise QuotaRejected(reason) from None
            raise RuntimeError(
                f"{method} {path} -> HTTP {e.code}: {reason}"
            ) from None
        return json.loads(body) if body else None

    # -- API -----------------------------------------------------------------

    def submit(
        self,
        workflow: str,
        kwargs: Dict[str, Any],
        configs: Optional[Dict[str, dict]] = None,
        tenant: str = "default",
        priority: int = 0,
    ) -> str:
        """Submit one workflow; returns the job id.  Raises
        :class:`QuotaRejected` when admission says no."""
        out = self._request("POST", "/api/v1/jobs", {
            "workflow": workflow,
            "kwargs": kwargs,
            "configs": configs or {},
            "tenant": tenant,
            "priority": priority,
        })
        return out["job_id"]

    def resegment(
        self,
        hierarchy: str,
        labels_path: str,
        labels_key: str,
        output_path: str,
        output_key: str,
        threshold: float,
        tmp_folder: str,
        config_dir: str,
        configs: Optional[Dict[str, dict]] = None,
        tenant: str = "default",
        priority: int = 0,
        write_volume: bool = True,
        microbatch: Optional[bool] = None,
    ) -> str:
        """ctt-hier threshold sweep step: submit one ``resegment`` job
        (re-cut a built hierarchy at ``threshold``); returns the job id.
        Against a warm daemon every step after the first touches only the
        cached hierarchy + one relabel gather per block batch.
        ``write_volume=False`` is the interactive mode: the job persists
        only the relabel table (``<output_key>_cut.npz``) for the client
        to apply to its current view — the millisecond sweep step.
        ``microbatch=False`` opts the job out of the daemon's cross-tenant
        aggregation window (ctt-microbatch)."""
        payload = {
            "type": "resegment",
            "hierarchy": hierarchy,
            "labels_path": labels_path,
            "labels_key": labels_key,
            "output_path": output_path,
            "output_key": output_key,
            "threshold": float(threshold),
            "write_volume": bool(write_volume),
            "tmp_folder": tmp_folder,
            "config_dir": config_dir,
            "configs": configs or {},
            "tenant": tenant,
            "priority": priority,
        }
        if microbatch is not None:
            payload["microbatch"] = bool(microbatch)
        out = self._request("POST", "/api/v1/jobs", payload)
        return out["job_id"]

    def event_batch(
        self,
        input_path: str,
        input_key: str,
        output_path: str,
        output_key: str,
        tmp_folder: str,
        config_dir: str,
        threshold: Optional[float] = None,
        connectivity: Optional[int] = None,
        max_clusters: Optional[int] = None,
        configs: Optional[Dict[str, dict]] = None,
        tenant: str = "default",
        priority: int = 0,
        microbatch: Optional[bool] = None,
    ) -> str:
        """ctt-events front-end step: submit one ``event_batch`` job
        (label + summarize every frame of the ``(n_frames, h, w)`` stack
        at ``input_path/input_key``); returns the job id.  Against a warm
        daemon every batch after the first reuses the compiled kernels —
        the job signature is frame-count-blind — so a sustained stream
        pays submission + IO, not compiles.  ``microbatch=False`` opts
        the job out of the daemon's cross-tenant aggregation window
        (ctt-microbatch); by default same-signature bursts coalesce into
        one stacked dispatch."""
        payload = {
            "type": "event_batch",
            "input_path": input_path,
            "input_key": input_key,
            "output_path": output_path,
            "output_key": output_key,
            "tmp_folder": tmp_folder,
            "config_dir": config_dir,
            "configs": configs or {},
            "tenant": tenant,
            "priority": priority,
        }
        if threshold is not None:
            payload["threshold"] = float(threshold)
        if connectivity is not None:
            payload["connectivity"] = int(connectivity)
        if max_clusters is not None:
            payload["max_clusters"] = int(max_clusters)
        if microbatch is not None:
            payload["microbatch"] = bool(microbatch)
        out = self._request("POST", "/api/v1/jobs", payload)
        return out["job_id"]

    def ingest(
        self,
        control_dir: str,
        input_path: str,
        input_key: str,
        output_path: str,
        output_key: str,
        tmp_folder: str,
        config_dir: str,
        domain: str = "volume",
        watershed: Optional[bool] = None,
        poll_s: Optional[float] = None,
        timeout_s: Optional[float] = None,
        configs: Optional[Dict[str, dict]] = None,
        tenant: str = "default",
        priority: int = 0,
    ) -> str:
        """ctt-ingest front-end: submit one long-lived ``ingest`` job that
        watches ``control_dir`` for slab markers and segments the volume
        (or builds frame events, ``domain="frames"``) while it is still
        being acquired; returns the job id.  The job is drain-safe — a
        draining daemon releases it between slabs and a successor resumes
        from the persisted carry, byte-identical to the batch run."""
        payload = {
            "type": "ingest",
            "control_dir": control_dir,
            "domain": domain,
            "input_path": input_path,
            "input_key": input_key,
            "output_path": output_path,
            "output_key": output_key,
            "tmp_folder": tmp_folder,
            "config_dir": config_dir,
            "configs": configs or {},
            "tenant": tenant,
            "priority": priority,
        }
        if watershed is not None:
            payload["watershed"] = bool(watershed)
        if poll_s is not None:
            payload["poll_s"] = float(poll_s)
        if timeout_s is not None:
            payload["timeout_s"] = float(timeout_s)
        out = self._request("POST", "/api/v1/jobs", payload)
        return out["job_id"]

    def status(self, job_id: str) -> Dict[str, Any]:
        return self._request("GET", f"/api/v1/jobs/{job_id}")

    def list_jobs(self) -> list:
        return self._request("GET", "/api/v1/jobs")["jobs"]

    def wait(self, job_id: str, timeout_s: float = 600.0,
             poll_s: float = 0.1,
             raise_on_failure: bool = True) -> Dict[str, Any]:
        """Poll until the job reaches a terminal state; returns the full
        state dict."""
        deadline = obs_trace.monotonic() + float(timeout_s)
        while True:
            state = self.status(job_id)
            if state["state"] in ("done", "failed"):
                if state["state"] == "failed" and raise_on_failure:
                    err = (state.get("result") or {}).get("error")
                    raise JobFailed(f"job {job_id} failed: {err}")
                return state
            if obs_trace.monotonic() > deadline:
                raise TimeoutError(
                    f"job {job_id} still {state['state']} after "
                    f"{timeout_s:.0f}s"
                )
            time.sleep(poll_s)  # ctt: noqa[CTT009] status poll, not an IO retry — the daemon pushes nothing, clients poll

    def submit_and_wait(self, workflow: str, kwargs: Dict[str, Any],
                        **kw) -> Dict[str, Any]:
        wait_kw = {
            k: kw.pop(k)
            for k in ("timeout_s", "poll_s", "raise_on_failure")
            if k in kw
        }
        return self.wait(self.submit(workflow, kwargs, **kw), **wait_kw)

    def healthz(self) -> Dict[str, Any]:
        return self._request("GET", "/healthz")

    def fleet(self) -> Dict[str, Any]:
        """The daemon's fleet view (ctt-fleet): its id, live peer count
        and ids, the fleet-wide queue depth, and the elastic-capacity
        ``scale_advice`` — what an external supervisor polls to decide
        whether to spawn or drain daemons."""
        return self.healthz().get("fleet", {})

    def metrics_text(self) -> str:
        req = urllib.request.Request(
            self.base + "/metrics", headers=self._headers()
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return resp.read().decode()
