"""ctt-serve daemon: one warm process serving many workflow submissions.

``ServeDaemon`` owns the warm :class:`runtime.workflow.ExecutionContext`
(device set, persistent compile cache, decoded-chunk LRU, heartbeat
wiring) and runs three kinds of threads over the durable
:class:`serve.jobs.JobQueue`:

  * an HTTP thread (``ThreadingHTTPServer`` on loopback) handling
    submissions, status reads, ``/metrics`` (OpenMetrics — the obs.live
    exposition, so a scrape job watches the daemon exactly like a cluster
    run) and ``/healthz``.  Every request except the bare ``/healthz``
    liveness probe must present the daemon's auth token (published only
    through the mode-0600 ``serve.json``): a loopback port is reachable
    by any local user, and a submission resolves and instantiates Task
    classes — admission is gated on filesystem permissions instead;
  * ``concurrency`` executor threads that claim leased jobs in priority
    order and run ``runtime.build([task], context=<warm context>)`` —
    byte-identical to a fresh-process build, minus the setup cost.  With
    ``microbatch_window_s > 0`` a claim first holds an aggregation
    window (ctt-microbatch): queued jobs sharing its microbatch
    signature coalesce — across tenants — into ONE stacked dispatch
    (serve/microbatch.py), results split back per member job, faults and
    accounting stay per member;
  * per-running-job lease-renewal threads (the runtime/queue.py cadence),
    so a daemon killed mid-job leaves a lease that goes stale and
    requeues on the next daemon over the same state dir.

Daemons are **fleet-native** (ctt-fleet): every daemon publishes a fleet
heartbeat ``daemon.<id>.json`` into the state dir (first beat lands
*before* the executor threads start, so a lease can never precede its
owner's beat), stamps its id into every job lease at claim time, and
judges peers' leases through :class:`serve.fleet.FleetView` — a peer
that dies mid-job is failed over within one heartbeat staleness window
(3 x cadence) instead of the full lease window.  Admission is two-phase
over the shared dir (provisional record → earlier-sequence recount →
admit marker or 429 retraction), so queue depth and tenant quotas hold
across the whole fleet, not per daemon.

Shutdown is a **drain** (rides ``obs.heartbeat.install_sigterm_flush``:
the chained SIGTERM handler flushes telemetry, then triggers the drain
instead of dying): submissions start answering 503, heartbeats carry
``draining: true``, in-flight jobs finish and publish results, queued
jobs stay durable on disk for the next daemon.  A mid-job client
disconnect affects only that client's HTTP thread — the job keeps
running and its result stays readable.
"""

from __future__ import annotations

import hmac
import json
import os
import secrets
import signal
import socket
import tempfile
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from ..ingest.runner import IngestSuspended, install_suspend_check
from ..obs import heartbeat as obs_heartbeat
from ..obs import hist as obs_hist
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..runtime import config as cfg
from ..runtime.workflow import ExecutionContext, build
from ..utils import store_backend
from . import fleet as fleet_mod
from . import protocol
from .admission import AdmissionController
from .jobs import JobClaim, JobQueue

__all__ = ["ServeDaemon", "ENDPOINT_NAME"]

ENDPOINT_NAME = "serve.json"


def _write_private(path: str, payload: bytes) -> None:
    """Atomic replace with mode 0600 from birth: ``serve.json`` carries
    the daemon's auth token, so its readability IS the trust boundary —
    a loopback port is reachable by every local user, the endpoint file
    only by the daemon's owner."""
    tmp = path + f".tmp{os.getpid()}.{threading.get_ident()}"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class ServeDaemon:
    def __init__(self, state_dir: str,
                 config: Optional[Dict[str, Any]] = None):
        # ctt-diskless: the state dir may be an object-store prefix
        # (``http(s)://``, ``s3://``) — every shared-state file then rides
        # the store backend and the daemon holds ZERO local shared state
        self._backend = store_backend.backend_for(state_dir)
        self._remote_state = self._backend.is_remote
        self._backend.makedirs(state_dir)
        self.state_dir = state_dir
        conf = cfg.serve_config(state_dir)
        if config:
            conf.update({k: v for k, v in config.items() if v is not None})
        self.config = conf
        # telemetry: join the ambient run when CTT_TRACE_DIR is set (CI,
        # bench), else trace into the state dir so /metrics and heartbeats
        # are always live for scrapes.  Telemetry is per-process scratch,
        # not shared state — with a remote state dir it goes to local tmp
        if not obs_trace.enabled() and not os.environ.get(obs_trace.ENV_DIR):
            trace_dir = (
                os.path.join(
                    tempfile.gettempdir(), f"ctt-serve-trace-{os.getpid()}"
                )
                if self._remote_state
                else os.path.join(state_dir, "trace")
            )
            obs_trace.enable(
                trace_dir, f"serve_{os.getpid()}", export_env=False,
            )
        # hbm_cache_mb: the daemon's warm device-buffer cache (ctt-hbm) —
        # the "HBM stays warm across jobs" half of the amortization story;
        # the two-slot upload gate (runtime/hbm.py) doubles as the
        # dispatch-interleaving policy at concurrency > 1 (two jobs'
        # transfer bursts alternate instead of convoying)
        self.context = ExecutionContext(
            role="serve", hbm_cache_mb=conf.get("hbm_cache_mb"),
        ).install()
        # ctt-fleet identity + peer view: the daemon id rides every lease
        # this daemon claims, the view judges every lease it considers
        # stealing
        self.daemon_id = str(
            conf.get("daemon_id") or fleet_mod.default_daemon_id()
        )
        self.fleet = fleet_mod.FleetView(state_dir, self_id=self.daemon_id)
        # the beat rides the ctt-watch cadence (CTT_HEARTBEAT_S), NOT
        # lease_s: an operator sets lease_s to bound long jobs' renewal
        # period, but failover latency must stay bounded by the (much
        # shorter) heartbeat rule — that is the whole fast path
        self._fleet_beat = fleet_mod.FleetBeat(
            state_dir, self.daemon_id, info_fn=self._beat_info,
        )
        self.jobs = JobQueue(
            self._backend.join(state_dir, "jobs"),
            lease_s=conf.get("lease_s"),
            daemon_id=self.daemon_id, fleet=self.fleet,
            max_job_gens=conf.get("max_job_gens"),
        )
        self.admission = AdmissionController(
            conf.get("max_queue_depth"), conf.get("tenant_quota"),
            conf.get("tenant_quotas"),
        )
        # per-daemon auth secret: published only through serve.json
        # (mode 0600), required on every request except /healthz — a
        # submission instantiates arbitrary Task classes, so admission
        # to the socket must be gated on filesystem permissions, not on
        # loopback reachability (any local user can reach 127.0.0.1)
        self.token = secrets.token_hex(16)
        self.draining = False
        self._stop = threading.Event()   # end of the main run() loop
        self._wake = threading.Event()   # new work / drain for executors
        self._running_jobs = 0
        self._state_lock = threading.Lock()
        self._submit_lock = threading.Lock()
        self._warm_signatures: set = set()
        self._live_lock = threading.Lock()
        self._live_reader = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads: list = []
        self.port: Optional[int] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> Dict[str, Any]:
        """Bind, spawn HTTP + executor threads, publish the endpoint
        record.  Returns the endpoint dict."""
        host = str(self.config.get("host", "127.0.0.1"))
        port = int(self.config.get("port", 0) or 0)
        daemon = self

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._httpd = _Server((host, port), _Handler)
        self._httpd.ctt_daemon = daemon
        self.port = self._httpd.server_address[1]
        # ctt-ingest: a drain must also reach a long-lived ingest stream
        # parked deep inside an executing job — the probe surfaces the
        # draining flag between slabs as IngestSuspended, and _run_job
        # releases the lease instead of publishing a result
        install_suspend_check(lambda: self.draining)
        # first fleet beat BEFORE any executor thread exists: a lease
        # stamped with this daemon's id can then never be orphaned in a
        # no-beat blind window — SIGKILL at any later instant leaves a
        # beat for peers to age (satellite: claim-to-first-heartbeat)
        self._fleet_beat.start()
        http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="ctt-serve-http",
            daemon=True,
        )
        http_thread.start()
        self._threads.append(http_thread)
        for i in range(max(int(self.config.get("concurrency", 1)), 1)):
            t = threading.Thread(
                target=self._executor_loop, name=f"ctt-serve-exec-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        endpoint = {
            "host": host,
            "port": self.port,
            "pid": os.getpid(),
            "daemon_id": self.daemon_id,
            "started_wall": time.time(),
            "run_id": obs_trace.current_run_id(),
            "token": self.token,
        }
        payload = json.dumps(endpoint, sort_keys=True).encode()
        if self._remote_state:
            # on an object store the credential that reads the state dir
            # IS the trust boundary (there is no POSIX mode to narrow);
            # holding store keys already implies submit rights
            self._backend.write_bytes(
                self._backend.join(self.state_dir, ENDPOINT_NAME), payload
            )
        else:
            _write_private(
                os.path.join(self.state_dir, ENDPOINT_NAME), payload
            )
        self._publish_gauges()
        return endpoint

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → drain.  The drain trigger goes in FIRST, then
        ``install_sigterm_flush`` wraps it: on SIGTERM the flush handler
        runs (metrics + shards + final heartbeat land even if the drain
        then hangs) and chains into the trigger instead of re-raising —
        the daemon drains and exits cleanly rather than dying mid-job."""
        if threading.current_thread() is not threading.main_thread():
            return

        def _trigger(signum, frame):
            self.request_drain()

        signal.signal(signal.SIGTERM, _trigger)
        signal.signal(signal.SIGINT, _trigger)
        obs_heartbeat.install_sigterm_flush()

    def request_drain(self) -> None:
        """Flip into draining: refuse new submissions, let in-flight jobs
        finish, keep queued jobs durable for the next daemon."""
        self.draining = True
        # on the SIGTERM path the flush handler (install_sigterm_flush)
        # has already stopped the beat thread before chaining here —
        # restart it so heartbeats keep carrying ``draining: true`` for
        # the whole drain window (up to drain_timeout_s) instead of the
        # daemon going silent and readers flagging it stale; run()'s
        # final teardown stops it for good
        obs_heartbeat.ensure_started(role="serve")
        obs_heartbeat.note_draining()
        obs_heartbeat.beat()  # readers see the flag now, not next cadence
        self._fleet_beat.beat()  # peers see ``draining: true`` now too
        self._wake.set()
        self._stop.set()

    def run(self) -> int:
        """Foreground loop: start (if not already), serve until drained,
        tear down."""
        if self._httpd is None:
            self.start()
        try:
            while not self._stop.wait(0.2):
                pass
            return self._drain_and_stop()
        finally:
            if self._httpd is not None:
                self._httpd.shutdown()
                self._httpd.server_close()
            # final snap BEFORE the exiting beat: the fleet rollup keeps
            # this daemon's complete totals even after it is gone
            try:
                self._publish_snapshot()
            except OSError:
                pass  # ctt: noqa[CTT009] best-effort telemetry on the way out
            # stop the (possibly drain-restarted) beat thread and stamp
            # the final ``exiting`` heartbeat in one move; same for the
            # fleet beat — the ``exiting`` stamp lets peers fail over in
            # one cadence instead of aging the beat out over three
            self._fleet_beat.stop(final=True)
            obs_heartbeat.stop(final=True)
            obs_trace.flush()

    def _drain_and_stop(self) -> int:
        deadline = obs_trace.monotonic() + float(
            self.config.get("drain_timeout_s", 300.0)
        )
        self._wake.set()
        while obs_trace.monotonic() < deadline:
            with self._state_lock:
                busy = self._running_jobs
            if busy == 0:
                break
            time.sleep(0.1)
        stats = self.jobs.stats()
        print(
            f"[serve] drained: {stats['queued']} queued job(s) left durable "
            f"for the next daemon, {self._running_jobs} still running "
            "(leases will expire and requeue)",
            flush=True,
        )
        return 0

    # -- submission (HTTP thread) -------------------------------------------

    def submit(self, payload: Any) -> Dict[str, Any]:
        """Validate + admit + enqueue one submission.  Raises
        ``protocol.ProtocolError`` (400) or ``Rejected`` (429)."""
        record = protocol.validate_submission(payload)
        if self.draining:
            raise Draining("daemon is draining; resubmit to its successor")
        # two-phase fleet admission (ctt-fleet): publish the record
        # provisionally, recount the SHARED dir restricted to jobs that
        # precede it in the dense sequence, then admit or retract.  The
        # sequence gives every concurrent submitter — across all daemons
        # on this state dir — the same total order to judge against, so
        # k daemons cannot each admit a full quota's worth together (the
        # per-daemon lock alone only serializes this daemon's handlers)
        t_adm = obs_trace.monotonic()
        try:
            with self._submit_lock:
                job_id = self.jobs.submit(record, admitted=False)
                ok, reason = self.admission.admit(
                    record["tenant"],
                    self.jobs.stats(before_seq=int(job_id[1:])),
                )
                if not ok:
                    if not self.jobs.retract(job_id, reason):
                        # lost the result race: a peer's limbo reaper
                        # already parked a terminal record for this
                        # provisional job — same outcome (rejected),
                        # different author
                        obs_metrics.inc("serve.retract_races")
                    raise Rejected(reason)
                if self.jobs.admit(job_id):
                    obs_metrics.inc("serve.jobs_admitted")
        finally:
            # ctt-slo: admission latency covers the whole two-phase
            # decision, admitted and rejected alike — a quota-edge 429
            # that takes seconds is a tail the SLO gate must see
            obs_hist.observe(
                "serve.latency.admission", obs_trace.monotonic() - t_adm,
                tenant=record["tenant"],
                priority=int(record.get("priority", 0) or 0),
            )
        self._publish_gauges()
        self._wake.set()
        return {"job_id": job_id, "state": "queued"}

    # -- execution (executor threads) ---------------------------------------

    def _executor_loop(self) -> None:
        while True:
            if self.draining:
                # queued jobs stay durable for the next daemon — the
                # drain only finishes what is already executing
                return
            claim = self.jobs.claim_next()
            if claim is None:
                self._wake.wait(timeout=self.jobs.lease_s / 4.0)
                self._wake.clear()
                continue
            claims = self._gather_batch(claim)
            with self._state_lock:
                self._running_jobs += len(claims)
            self._publish_gauges()
            try:
                if len(claims) == 1:
                    self._run_job(claims[0])
                else:
                    self._run_job_batch(claims)
            finally:
                with self._state_lock:
                    self._running_jobs -= len(claims)
                self._publish_gauges()

    def _gather_batch(self, first: JobClaim) -> list:
        """ctt-microbatch aggregation window: hold the first claim open
        for up to ``microbatch_window_s``, then multi-claim queued jobs
        sharing its :func:`protocol.microbatch_signature` into one batch
        of at most ``microbatch_max_jobs`` members.

        Members are claimed at window CLOSE in (-priority, seq) order,
        so a higher-priority arrival during the window joins this batch
        ahead of lower-priority queue residents.  The window closes
        early once enough batchmates are queued
        (``serve.microbatch_window_timeouts`` counts deadline closes).
        Only fresh (gen 0) jobs batch: a requeued job re-runs SOLO, so a
        shared crash can never burn a batchmate's retry budget twice —
        after a mid-batch daemon death every member resumes individually,
        exactly like today's single-job failover."""
        window = float(self.config.get("microbatch_window_s", 0.0) or 0.0)
        max_jobs = int(self.config.get("microbatch_max_jobs", 1) or 1)
        sig = protocol.microbatch_signature(first.record)
        if (window <= 0.0 or max_jobs <= 1 or sig is None
                or first.gen != 0 or self.draining):
            return [first]

        def matches(rec, gen):
            return gen == 0 and protocol.microbatch_signature(rec) == sig

        deadline = obs_trace.monotonic() + window
        filled = False
        while obs_trace.monotonic() < deadline:
            if self.draining:
                # a drain only finishes what is claimed — never widen it
                return [first]
            if self.jobs.count_matching(matches) >= max_jobs - 1:
                filled = True
                break
            time.sleep(max(min(0.005, window / 4.0), 1e-4))
        if not filled:
            obs_metrics.inc("serve.microbatch_window_timeouts")
        claims = [first] + self.jobs.claim_batch(matches, max_jobs - 1)
        claims.sort(key=lambda c: (
            -int(c.record.get("priority", 0) or 0),
            int(c.record.get("seq", 0) or 0),
        ))
        obs_metrics.set_gauge("serve.microbatch_depth", len(claims))
        if len(claims) > 1:
            obs_metrics.inc("serve.microbatch_batches")
            obs_metrics.inc("serve.microbatch_jobs_batched", len(claims))
        return claims

    def _run_job(self, claim: JobClaim,
                 microbatch_note: Optional[Dict[str, Any]] = None) -> None:
        rec = claim.record
        stop = threading.Event()
        renewer = threading.Thread(
            target=self._renew_loop, args=(claim, stop),
            name="ctt-serve-lease", daemon=True,
        )
        renewer.start()
        # ctt-slo: execution starts NOW — stamp dispatch_wall into the
        # lease (claim→dispatch is the window-wait phase; on the
        # microbatch solo-retry path this re-stamps to the solo dispatch)
        self.jobs.note_dispatch(claim)
        sig = protocol.job_signature(rec)
        warm = sig in self._warm_signatures
        before = obs_metrics.snapshot()["counters"]
        t0 = obs_trace.monotonic()
        ok, error, suspended = True, None, False
        try:
            try:
                with obs_trace.span(
                    "serve_job", kind="host", job=claim.job_id,
                    tenant=rec.get("tenant"), workflow=rec.get("workflow"),
                ):
                    task = self._instantiate(rec)
                    if not build([task], context=self.context):
                        ok, error = False, "build returned failure"
            except IngestSuspended:
                # drain reached a long-lived ingest stream between slabs;
                # not a failure — the carry is persisted, the job goes
                # back to the queue for a successor
                suspended = True
            except Exception:
                ok, error = False, traceback.format_exc()
        finally:
            # the renewer dies with the job: a persistent daemon would
            # otherwise accumulate one thread (each re-stamping the lease
            # file forever) per executed job
            stop.set()
            renewer.join(timeout=5.0)
        if suspended:
            # release AFTER the renewer is down (a late renew would
            # overwrite the released stamp): the lease classifies expired
            # at once, no result is published, and the next claimer —
            # this daemon post-drain or a peer — resumes from the carry
            # at gen+1 without burning the retry budget
            self.jobs.release(claim)
            obs_metrics.flush()
            return
        seconds = obs_trace.monotonic() - t0
        after = obs_metrics.snapshot()["counters"]

        def delta(name: str) -> float:
            return after.get(name, 0.0) - before.get(name, 0.0)

        if ok:
            self._warm_signatures.add(sig)
            obs_metrics.inc("serve.jobs_done")
            if rec.get("type") == "resegment":
                # ctt-hier: the threshold-sweep accounting — a warm sweep
                # is resegment jobs moving while upload bytes stand still
                obs_metrics.inc("hier.resegment_jobs")
            obs_metrics.inc(
                "serve.warm_compile_jobs" if warm
                else "serve.cold_compile_jobs"
            )
        else:
            obs_metrics.inc("serve.jobs_failed")
        result = {
            "ok": ok,
            "error": (error or "")[-4000:] or None,
            "seconds": seconds,
            "warm": warm and ok,
            "compile_cache": {
                "hits": delta("compile_cache.cache_hits"),
                "misses": delta("compile_cache.cache_misses"),
            },
            "tenant": rec.get("tenant"),
        }
        if microbatch_note:
            result["microbatch"] = dict(microbatch_note)
        t_pub = obs_trace.monotonic()
        won = self.jobs.complete(claim, result)
        publish_s = obs_trace.monotonic() - t_pub
        if not won:
            # a peer presumed us dead mid-run (stale lease or dead fleet
            # beat) and re-ran the job at gen+1; first writer won and ours
            # is the duplicate — correct by design, but worth counting
            obs_metrics.inc("serve.result_races")
        else:
            self._observe_job_phases(claim, rec, seconds, publish_s)
        obs_metrics.flush()  # results readable => counters scrapeable
        obs_hist.flush()

    def _run_job_batch(self, claims: list) -> None:
        """ctt-microbatch: run same-signature member jobs as ONE stacked
        dispatch (serve/microbatch.py), keeping every per-member
        contract: own lease (renewed for the whole batch), own result
        record, per-member warm/cold and tenant accounting.  Members the
        runner cannot stack run the ordinary solo path; members that
        FAIL any stacked stage are re-dispatched individually
        (``serve.microbatch_splits``) so only the true culprit burns
        budget and publishes a failure."""
        stops, renewers = [], []
        for claim in claims:
            stop = threading.Event()
            r = threading.Thread(
                target=self._renew_loop, args=(claim, stop),
                name="ctt-serve-lease", daemon=True,
            )
            r.start()
            stops.append(stop)
            renewers.append(r)
        try:
            self._run_job_batch_inner(claims)
        finally:
            for stop in stops:
                stop.set()
            for r in renewers:
                r.join(timeout=5.0)

    def _run_job_batch_inner(self, claims: list) -> None:
        from . import microbatch

        n = len(claims)
        index = {c.job_id: i for i, c in enumerate(claims)}
        for claim in claims:
            # ctt-slo: the aggregation window is over — every member's
            # window-wait phase ends at this shared dispatch instant
            self.jobs.note_dispatch(claim)
        warm_by_job = {
            c.job_id: protocol.job_signature(c.record)
            in self._warm_signatures
            for c in claims
        }
        before = obs_metrics.snapshot()["counters"]
        t0 = obs_trace.monotonic()

        solo: list = []       # (claim, split) — split=True burns a split
        groups: Dict[Any, list] = {}
        plan_claims: Dict[int, JobClaim] = {}
        with obs_trace.span(
            "serve_job_batch", kind="host", jobs=n,
            job_ids=[c.job_id for c in claims],
            tenants=sorted({
                str(c.record.get("tenant")) for c in claims
            }),
        ):
            for claim in claims:
                try:
                    plan = microbatch.plan_member(
                        self._instantiate(claim.record)
                    )
                except Exception:
                    plan = None  # the solo path reports the real error
                if plan is None:
                    solo.append((claim, False))
                    continue
                plan_claims[id(plan)] = claim
                groups.setdefault(microbatch.stack_key(plan), []).append(
                    plan
                )
            ok_plans, failed_plans = [], []
            for plans in groups.values():
                ok_p, failed_p = microbatch.run_stacked(plans)
                ok_plans.extend(ok_p)
                failed_plans.extend(failed_p)
        seconds = obs_trace.monotonic() - t0
        after = obs_metrics.snapshot()["counters"]
        compile_delta = {
            "hits": after.get("compile_cache.cache_hits", 0.0)
            - before.get("compile_cache.cache_hits", 0.0),
            "misses": after.get("compile_cache.cache_misses", 0.0)
            - before.get("compile_cache.cache_misses", 0.0),
        }

        for i, plan in enumerate(ok_plans):
            claim = plan_claims[id(plan)]
            rec = claim.record
            warm = warm_by_job[claim.job_id]
            self._warm_signatures.add(protocol.job_signature(rec))
            obs_metrics.inc("serve.jobs_done")
            if rec.get("type") == "resegment":
                obs_metrics.inc("hier.resegment_jobs")
            obs_metrics.inc(
                "serve.warm_compile_jobs" if warm
                else "serve.cold_compile_jobs"
            )
            member_s = plan.seconds or seconds / n
            t_pub = obs_trace.monotonic()
            won = self.jobs.complete(claim, {
                "ok": True,
                "error": None,
                "seconds": member_s,
                "warm": warm,
                # compile accounting is per dispatch, and the batch IS
                # one dispatch: the whole delta rides the first member,
                # so summing members' results equals the solo totals
                "compile_cache": compile_delta if i == 0
                else {"hits": 0.0, "misses": 0.0},
                "tenant": rec.get("tenant"),
                "microbatch": {"jobs": n, "index": index[claim.job_id]},
            })
            publish_s = obs_trace.monotonic() - t_pub
            if not won:
                obs_metrics.inc("serve.result_races")
            else:
                self._observe_job_phases(claim, rec, member_s, publish_s)
        obs_metrics.flush()
        obs_hist.flush()

        for plan in failed_plans:
            solo.append((plan_claims[id(plan)], True))
        # failed/ineligible members re-dispatch through the EXACT solo
        # path (own build, own spans, own fault surface): a poisoned
        # member fails alone here while its batchmates' ok results are
        # already published above
        for claim, split in solo:
            note = {"jobs": n, "index": index[claim.job_id]}
            if split:
                obs_metrics.inc("serve.microbatch_splits")
                note["split"] = True
            self._run_job(claim, microbatch_note=note)

    def _instantiate(self, rec: Dict[str, Any]):
        cls = protocol.resolve_workflow(rec["workflow"])
        kwargs = dict(rec.get("kwargs") or {})
        configs = rec.get("configs") or {}
        if configs:
            config_dir = kwargs["config_dir"]
            for name, conf in configs.items():
                if name == "global":
                    cfg.write_global_config(config_dir, conf)
                else:
                    cfg.write_config(config_dir, name, conf)
        return cls(**kwargs)

    def _renew_loop(self, claim: JobClaim, stop: threading.Event) -> None:
        interval = max(self.jobs.lease_s / 2.0, 0.05)
        while not stop.wait(interval):
            try:
                self.jobs.renew(claim)
            except OSError:
                # best-effort liveness, the heartbeat/queue convention: a
                # full disk costs at worst a spurious requeue later
                pass

    # -- observability -------------------------------------------------------

    def _observe_job_phases(self, claim: JobClaim, rec: Dict[str, Any],
                            exec_s: float, publish_s: float) -> None:
        """ctt-slo: record one published job's per-phase latencies into
        the tenant/priority-labeled histograms.  Called only by the
        daemon that WON the result race, so a job counts exactly once
        fleet-wide.  Cross-process phases subtract durable wall stamps
        (the lease/record convention: good to host clock skew), clamped
        at zero so skew can only shrink a phase, never fabricate one."""
        tenant = str(rec.get("tenant", "default"))
        priority = str(int(rec.get("priority", 0) or 0))

        def note(name: str, value: float) -> None:
            obs_hist.observe(name, max(0.0, float(value)),
                             tenant=tenant, priority=priority)

        try:
            submit_wall = float(rec["submit_wall"])
        except (KeyError, TypeError, ValueError):
            submit_wall = None
        start = self.jobs.admit_wall(claim.job_id)
        if start is None:
            start = submit_wall
        if start is not None:
            note("serve.latency.queue_wait", claim.claim_wall - start)
        if claim.dispatch_wall is not None:
            note("serve.latency.window_wait",
                 claim.dispatch_wall - claim.claim_wall)
        note("serve.latency.execution", exec_s)
        note("serve.latency.publish", publish_s)
        if submit_wall is not None:
            published_wall = time.time()  # timestamp pair with submit_wall
            note("serve.latency.e2e", published_wall - submit_wall)

    def _publish_snapshot(self) -> None:
        """ctt-slo fleet rollup: publish this daemon's counters, gauges,
        and latency histograms as ``snap.<daemon_id>.json`` into the
        SHARED state dir (atomic-replace per write, torn reads skipped
        by the reader) — ``obs fleet`` merges every daemon's snap over
        one backend listing, POSIX or object-store prefix alike."""
        metrics_snap = obs_metrics.snapshot()
        snap = {
            "schema": 1,
            "daemon": self.daemon_id,
            "pid": os.getpid(),
            "wall": time.time(),
            "counters": metrics_snap["counters"],
            "gauges": metrics_snap["gauges"],
            "hists": obs_hist.snapshot(),
        }
        self._backend.write_bytes(
            self._backend.join(
                self.state_dir, f"snap.{self.daemon_id}.json"
            ),
            json.dumps(snap, sort_keys=True).encode(),
        )

    def _beat_info(self) -> Dict[str, Any]:
        """The capacity/load fields riding each fleet beat — what
        :func:`serve.fleet.scale_advice` and ``obs watch`` read.  Also
        the cadence the metrics/histogram snap publication rides: one
        snap per fleet beat keeps ``obs fleet`` at most one heartbeat
        stale without a thread of its own."""
        try:
            self._publish_snapshot()
        except OSError:
            pass  # ctt: noqa[CTT009] best-effort telemetry: the beat must land even if the snap write hiccups
        with self._state_lock:
            running = self._running_jobs
        return {
            "host": str(self.config.get("host", "127.0.0.1")),
            "port": self.port,
            "draining": self.draining,
            "concurrency": max(int(self.config.get("concurrency", 1)), 1),
            "running_jobs": running,
            "queued": self.jobs.stats()["queued"],
        }

    def _publish_gauges(self) -> None:
        stats = self.jobs.stats()
        obs_metrics.set_gauge("serve.queue_depth", stats["queued"])
        # fleet-wide mirrors: the shared-dir scan already IS fleet-wide,
        # and the live-peer count makes a lost daemon visible on watch
        obs_metrics.set_gauge("fleet.queue_depth", stats["queued"])
        obs_metrics.set_gauge("serve.peers", len(self.fleet.live()))
        with self._state_lock:
            obs_metrics.set_gauge("serve.running_jobs", self._running_jobs)

    def metrics_text(self) -> str:
        """The OpenMetrics exposition for ``/metrics``: flush this
        process's counters, then render the live snapshot of the run dir
        (all participating processes' counters + heartbeats), falling
        back to a process-local snapshot when tracing is off."""
        obs_metrics.flush()
        obs_hist.flush()  # latency histograms ride the same exposition
        rdir = obs_trace.run_dir()
        from ..obs import live as obs_live

        if rdir is not None:
            with self._live_lock:
                if (
                    self._live_reader is None
                    or self._live_reader.run_dir != rdir
                ):
                    self._live_reader = obs_live.LiveRun(rdir)
                snap = self._live_reader.poll()
        else:
            snap = {
                "counters": obs_metrics.snapshot()["counters"],
                "gauges": obs_metrics.snapshot()["gauges"],
                "hists": obs_hist.snapshot(),
                "workers": [], "tasks": {}, "stragglers": [],
                "malformed_lines": 0,
            }
        return obs_live.render_openmetrics(snap)

    def healthz(self) -> Dict[str, Any]:
        stats = self.jobs.stats()
        live = self.fleet.live()
        return {
            "ok": True,
            "draining": self.draining,
            "pid": os.getpid(),
            "daemon_id": self.daemon_id,
            "queue": stats,
            # the admission decision inputs AND limits, verbatim: an
            # operator (or the overshoot regression test) reads off
            # exactly what the next submission will be judged against
            "admission": {
                **self.admission.describe(),
                "queued": stats["queued"],
                "in_flight": stats["in_flight"],
                "per_tenant": stats["per_tenant"],
            },
            "fleet": {
                "id": self.daemon_id,
                "peers": len(live),
                "daemons": sorted(live),
                "queue_depth": stats["queued"],
                "scale_advice": fleet_mod.scale_advice(
                    self.state_dir, stats=stats, view=self.fleet,
                ),
            },
            "context": self.context.describe(),
            "run_id": obs_trace.current_run_id(),
        }


class Rejected(RuntimeError):
    """Admission said no (HTTP 429)."""


class Draining(RuntimeError):
    """The daemon is shutting down (HTTP 503)."""


class _Handler(BaseHTTPRequestHandler):
    # soak-hardening (ctt-events): HTTP/1.1 keep-alive — a front-end
    # submitting at rate reuses one connection instead of paying a socket
    # + handler thread per request (every reply carries Content-Length,
    # the framing 1.1 persistence needs); idle kept-alive connections
    # close after ``timeout`` so a silent client cannot pin a thread
    protocol_version = "HTTP/1.1"
    timeout = 30.0

    # one daemon serves many short local requests; default request logging
    # to stderr would drown the job logs
    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass

    def setup(self):
        super().setup()
        # resolve the daemon once per CONNECTION, not per routed call —
        # with keep-alive a connection spans many requests
        self.daemon: ServeDaemon = self.server.ctt_daemon

    def _authorized(self) -> bool:
        """The per-daemon token from serve.json (mode 0600), via
        ``X-CTT-Serve-Token`` or ``Authorization: Bearer``.  Everything
        but the bare liveness probe requires it: loopback reachability
        is not a trust boundary on a shared host."""
        supplied = self.headers.get("X-CTT-Serve-Token") or ""
        if not supplied:
            auth = self.headers.get("Authorization") or ""
            if auth.startswith("Bearer "):
                supplied = auth[len("Bearer "):]
        return hmac.compare_digest(supplied, self.daemon.token)

    def _reject_unauthorized(self):
        return self._reply(401, {
            "error": "unauthorized",
            "reason": "missing or wrong daemon token (read it from the "
                      "state dir's serve.json)",
        })

    def _reply(self, code: int, payload, content_type="application/json"):
        try:
            body = (
                payload.encode()
                if isinstance(payload, str)
                else json.dumps(payload, sort_keys=True).encode()
            )
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError, socket.timeout):
            # mid-response client disconnect: the client's problem, never
            # the daemon's — the job (if any) keeps running
            pass

    def do_GET(self):  # noqa: N802 - stdlib naming
        path = self.path.split("?", 1)[0].rstrip("/")
        if path == "/healthz":
            # tokenless liveness probe (the k8s/scrape-target convention);
            # everything else is authenticated
            return self._reply(200, self.daemon.healthz())
        if not self._authorized():
            return self._reject_unauthorized()
        if path == "/metrics":
            return self._reply(
                200, self.daemon.metrics_text(),
                content_type=(
                    "application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8"
                ),
            )
        if path == "/api/v1/jobs":
            return self._reply(200, {"jobs": self.daemon.jobs.list()})
        if path.startswith("/api/v1/jobs/"):
            state = self.daemon.jobs.get(path.rsplit("/", 1)[1])
            if state is None:
                return self._reply(404, {"error": "no such job"})
            return self._reply(200, state)
        return self._reply(404, {"error": f"no such path {path!r}"})

    def do_POST(self):  # noqa: N802 - stdlib naming
        path = self.path.split("?", 1)[0].rstrip("/")
        if path != "/api/v1/jobs":
            return self._reply(404, {"error": f"no such path {path!r}"})
        if not self._authorized():
            # refused before the body is even parsed: an unauthenticated
            # submission must never reach workflow resolution
            return self._reject_unauthorized()
        try:
            length = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, OSError) as e:
            return self._reply(400, {"error": f"bad request body: {e}"})
        try:
            return self._reply(200, self.daemon.submit(payload))
        except protocol.ProtocolError as e:
            return self._reply(400, {"error": "invalid", "reason": str(e)})
        except Rejected as e:
            return self._reply(429, {"error": "rejected", "reason": str(e)})
        except Draining as e:
            return self._reply(503, {"error": "draining", "reason": str(e)})
        except Exception:
            return self._reply(
                500, {"error": "internal", "reason": traceback.format_exc()}
            )
