"""ctt-serve submission protocol: the wire schema and its validation.

One job = one JSON object POSTed to ``/api/v1/jobs`` (full file-format
reference beside the heartbeat/lease schemas in ``obs/trace.py``)::

    {
      "workflow": "WatershedWorkflow"            # name in
                                                 # cluster_tools_tpu.workflows,
                  | "pkg.mod:ClassName",         # or an importable dotted
                                                 # path to any Task subclass
      "kwargs":   {"tmp_folder": ..., ...},      # constructor arguments
      "configs":  {"global": {...},              # optional: config files the
                   "<task_name>": {...}},        # daemon writes into
                                                 # kwargs["config_dir"] before
                                                 # building ("global" goes
                                                 # through write_global_config)
      "tenant":   "default",                     # quota accounting key
      "priority": 0                              # higher claims first
    }

ctt-hier sugar — the ``resegment`` job type, the proofreading-sweep wire
shape.  A client that built a hierarchy once (``HierarchyWorkflow``)
sweeps merge thresholds against one warm daemon without knowing the task
wiring::

    {
      "type":        "resegment",
      "hierarchy":   "/.../seg_hierarchy.npz",   # the build's artifact
      "labels_path": ..., "labels_key": ...,     # the GLOBAL-id labels
      "output_path": ..., "output_key": ...,     # per-threshold output
      "threshold":   0.3,                        # the merge level to cut at
      "write_volume": false,                     # optional: persist only the
                                                 # relabel table (_cut.npz) —
                                                 # the millisecond sweep step
      "tmp_folder":  ..., "config_dir": ...,
      "configs":     {"global": {...}},          # optional (block_shape &c)
      "tenant": ..., "priority": ...
    }

:func:`validate_submission` normalizes this into a plain workflow record
over ``cluster_tools_tpu.tasks.hier:ResegmentTask`` (the threshold rides
the ``resegment`` task config), so queueing, leases, quotas, and warm
accounting are the ordinary job machinery — the type survives on the
record for the ``hier.resegment_jobs`` counter, and ``job_signature``
ignores the threshold: every sweep step after the first is a warm job.

ctt-events sugar — the ``event_batch`` job type, the high-rate detector
front-end wire shape.  One submission = one batch of frames to label and
summarize (``(n_frames, h, w)`` stack at ``input_path/input_key``)::

    {
      "type":         "event_batch",
      "input_path":   ..., "input_key": ...,      # the frame stack
      "output_path":  ..., "output_key": ...,     # labels volume (+ the
                                                  # ragged _events tables)
      "threshold":    0.0,                        # optional kernel knobs →
      "connectivity": 2,                          # the "events" task config
      "max_clusters": 16,
      "tmp_folder":   ..., "config_dir": ...,
      "configs":      {...}, "tenant": ..., "priority": ...
    }

Normalizes over ``cluster_tools_tpu.tasks.events:EventBuildingTask``;
``job_signature`` for this type is frame-count- and block-shape-blind
(the kernel pow2-pads both), so every batch after the first is warm.

ctt-ingest sugar — the ``ingest`` job type, the streaming-acquisition
wire shape.  One submission = one long-lived stream: the daemon watches
``control_dir`` (manifest + slab markers; see ``obs/trace.py``) and
feeds every landed slab through the domain's fused chain, persisting the
carry per slab so a drain suspend or daemon death resumes mid-stream::

    {
      "type":        "ingest",
      "control_dir": ...,                         # POSIX dir or object-store
                                                  # prefix being acquired into
      "domain":      "volume" | "frames",
      "input_path":  ..., "input_key": ...,       # the growing dataset
      "output_path": ..., "output_key": ...,
      "watershed":   false,                       # optional (volume domain)
      "poll_s":      0.2, "timeout_s": 600,       # optional watcher knobs
      "tmp_folder":  ..., "config_dir": ...,
      "configs":     {...}, "tenant": ..., "priority": ...
    }

Normalizes over ``cluster_tools_tpu.ingest.runner:IngestTask``.

ctt-microbatch — cross-tenant job aggregation.  Every submission accepts
an optional ``"microbatch": false`` key (preserved on the job record) to
opt a job out of the daemon's aggregation window; by default, queued
jobs whose :func:`microbatch_signature` matches (same workflow + job
type + configs + pinned artifacts) may be coalesced into ONE stacked
device dispatch by the executing daemon.  The batch is an in-daemon
execution detail: every member keeps its own job/lease/result records,
admission and quotas are judged per member, and results are
byte-identical to per-job dispatch.  A member of a stacked dispatch
carries a ``"microbatch": {"jobs": n, "index": i}`` annotation on its
result record (``"split": true`` when it was re-dispatched individually
after a batch failure).

Every request except the bare ``/healthz`` liveness probe must carry the
daemon's auth token (``X-CTT-Serve-Token: <token>`` or ``Authorization:
Bearer <token>``), published only through the mode-0600 ``serve.json``
endpoint record — reading that file is the authorization; the loopback
port itself is reachable by any local user and grants nothing.

Responses: ``{"job_id": "j000001", "state": "queued"}`` on admission,
HTTP 429 ``{"error": "rejected", "reason": ...}`` on quota/queue-depth
rejection, HTTP 400 on schema violations, HTTP 401 on a missing/wrong
token, HTTP 503 while draining.

Job state read back from ``GET /api/v1/jobs/<id>``::

    {"id", "state": "queued" | "running" | "done" | "failed",
     "record": {<the submission>},
     "result": {"ok", "error", "seconds", "warm",
                "compile_cache": {"hits", "misses"}, "finished_wall"} | null}

The daemon executes jobs by resolving ``workflow`` to a Task class,
instantiating it with ``kwargs``, and running ``runtime.build([task],
context=<the daemon's warm ExecutionContext>)`` — the submission/
execution split: clients describe work, the daemon owns the warm device
state that executes it.
"""

from __future__ import annotations

import importlib
import json
from typing import Any, Dict, Optional, Tuple

SCHEMA_VERSION = 1

JOB_STATES = ("queued", "running", "done", "failed")

JOB_TYPES = ("workflow", "resegment", "event_batch", "ingest")

# the task class a ``resegment`` submission resolves to (ctt-hier)
RESEGMENT_TASK = "cluster_tools_tpu.tasks.hier:ResegmentTask"

# the task class an ``event_batch`` submission resolves to (ctt-events)
EVENTS_TASK = "cluster_tools_tpu.tasks.events:EventBuildingTask"

# the task class an ``ingest`` submission resolves to (ctt-ingest)
INGEST_TASK = "cluster_tools_tpu.ingest.runner:IngestTask"


class ProtocolError(ValueError):
    """A submission that violates the schema (HTTP 400, never a retry)."""


def _normalize_resegment(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Rewrite a ``resegment`` submission into the plain workflow shape
    (see the module docstring): the sweep-specific fields become
    ResegmentTask kwargs and the threshold lands in the ``resegment``
    task config the daemon writes before building."""
    for field in ("hierarchy", "labels_path", "labels_key",
                  "output_path", "output_key", "tmp_folder", "config_dir"):
        if not isinstance(payload.get(field), str) or not payload[field]:
            raise ProtocolError(
                f"resegment submission requires '{field}' (string)"
            )
    threshold = payload.get("threshold")
    if not isinstance(threshold, (int, float)) or isinstance(threshold, bool):
        raise ProtocolError(
            "resegment submission requires a numeric 'threshold'"
        )
    configs = payload.get("configs") or {}
    if not isinstance(configs, dict):
        raise ProtocolError("'configs' must map config names to objects")
    configs = dict(configs)
    reseg_conf = dict(configs.get("resegment") or {})
    reseg_conf["threshold"] = float(threshold)
    if "write_volume" in payload:
        # interactive sweep steps persist the relabel TABLE only
        # (<output_key>_cut.npz); the volume gather is the commit job
        reseg_conf["write_volume"] = bool(payload["write_volume"])
    configs["resegment"] = reseg_conf
    return {
        "type": "resegment",
        "workflow": RESEGMENT_TASK,
        "kwargs": {
            "tmp_folder": payload["tmp_folder"],
            "config_dir": payload["config_dir"],
            "input_path": payload["labels_path"],
            "input_key": payload["labels_key"],
            "output_path": payload["output_path"],
            "output_key": payload["output_key"],
            "hierarchy_path": payload["hierarchy"],
        },
        "configs": configs,
        "tenant": payload.get("tenant", "default"),
        "priority": payload.get("priority", 0),
    }


def _normalize_event_batch(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Rewrite an ``event_batch`` submission (ctt-events — one detector
    frame batch: label + summarize every frame) into the plain workflow
    shape over :data:`EVENTS_TASK`.  The kernel knobs (threshold /
    connectivity / max_clusters) land in the ``events`` task config the
    daemon writes before building."""
    for field in ("input_path", "input_key", "output_path", "output_key",
                  "tmp_folder", "config_dir"):
        if not isinstance(payload.get(field), str) or not payload[field]:
            raise ProtocolError(
                f"event_batch submission requires '{field}' (string)"
            )
    configs = payload.get("configs") or {}
    if not isinstance(configs, dict):
        raise ProtocolError("'configs' must map config names to objects")
    configs = dict(configs)
    ev_conf = dict(configs.get("events") or {})
    if "threshold" in payload:
        threshold = payload["threshold"]
        if (not isinstance(threshold, (int, float))
                or isinstance(threshold, bool)):
            raise ProtocolError(
                "event_batch 'threshold' must be numeric"
            )
        ev_conf["threshold"] = float(threshold)
    for field in ("connectivity", "max_clusters"):
        if field in payload:
            value = payload[field]
            if not isinstance(value, int) or isinstance(value, bool):
                raise ProtocolError(
                    f"event_batch '{field}' must be an integer"
                )
            ev_conf[field] = value
    configs["events"] = ev_conf
    return {
        "type": "event_batch",
        "workflow": EVENTS_TASK,
        "kwargs": {
            "tmp_folder": payload["tmp_folder"],
            "config_dir": payload["config_dir"],
            "input_path": payload["input_path"],
            "input_key": payload["input_key"],
            "output_path": payload["output_path"],
            "output_key": payload["output_key"],
        },
        "configs": configs,
        "tenant": payload.get("tenant", "default"),
        "priority": payload.get("priority", 0),
    }


def _normalize_ingest(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Rewrite an ``ingest`` submission (ctt-ingest — a long-lived job
    that watches a growing source and streams every landed slab through
    the domain's fused chain) into the plain workflow shape over
    :data:`INGEST_TASK`.  ``control_dir`` is the watcher's poll target (a
    POSIX dir or object-store prefix holding the manifest + slab
    markers); ``domain`` picks the chain ("volume": streaming
    segmentation, "frames": event building)."""
    for field in ("control_dir", "input_path", "input_key", "output_path",
                  "output_key", "tmp_folder", "config_dir"):
        if not isinstance(payload.get(field), str) or not payload[field]:
            raise ProtocolError(
                f"ingest submission requires '{field}' (string)"
            )
    domain = payload.get("domain", "volume")
    if domain not in ("volume", "frames"):
        raise ProtocolError(
            f"ingest 'domain' must be 'volume' or 'frames', got {domain!r}"
        )
    configs = payload.get("configs") or {}
    if not isinstance(configs, dict):
        raise ProtocolError("'configs' must map config names to objects")
    kwargs: Dict[str, Any] = {
        "tmp_folder": payload["tmp_folder"],
        "config_dir": payload["config_dir"],
        "control_dir": payload["control_dir"],
        "domain": domain,
        "input_path": payload["input_path"],
        "input_key": payload["input_key"],
        "output_path": payload["output_path"],
        "output_key": payload["output_key"],
    }
    if "watershed" in payload:
        kwargs["watershed"] = bool(payload["watershed"])
    for field in ("poll_s", "timeout_s"):
        if field in payload:
            value = payload[field]
            if (not isinstance(value, (int, float))
                    or isinstance(value, bool) or value <= 0):
                raise ProtocolError(
                    f"ingest '{field}' must be a positive number"
                )
            kwargs[field] = float(value)
    return {
        "type": "ingest",
        "workflow": INGEST_TASK,
        "kwargs": kwargs,
        "configs": dict(configs),
        "tenant": payload.get("tenant", "default"),
        "priority": payload.get("priority", 0),
    }


def validate_submission(payload: Any) -> Dict[str, Any]:
    """Normalize + validate one submission JSON into a job record.  Loud:
    a malformed submission is a client bug, not a degraded default."""
    if not isinstance(payload, dict):
        raise ProtocolError("submission must be a JSON object")
    # capture the aggregation opt-out before the typed normalizers rebuild
    # the payload (they only keep their own fields)
    microbatch = payload.get("microbatch")
    if microbatch is not None and not isinstance(microbatch, bool):
        raise ProtocolError("'microbatch' must be a boolean")
    job_type = payload.get("type", "workflow")
    if job_type not in JOB_TYPES:
        raise ProtocolError(
            f"unknown job type {job_type!r} (one of {JOB_TYPES})"
        )
    if job_type == "resegment":
        payload = _normalize_resegment(payload)
    elif job_type == "event_batch":
        payload = _normalize_event_batch(payload)
    elif job_type == "ingest":
        payload = _normalize_ingest(payload)
    workflow = payload.get("workflow")
    if not isinstance(workflow, str) or not workflow.strip():
        raise ProtocolError("'workflow' must be a non-empty string")
    kwargs = payload.get("kwargs", {})
    if not isinstance(kwargs, dict):
        raise ProtocolError("'kwargs' must be an object")
    if not isinstance(kwargs.get("tmp_folder"), str):
        raise ProtocolError("kwargs.tmp_folder (string) is required")
    configs = payload.get("configs", {})
    if configs is None:
        configs = {}
    if not isinstance(configs, dict) or not all(
        isinstance(k, str) and isinstance(v, dict) for k, v in configs.items()
    ):
        raise ProtocolError("'configs' must map config names to objects")
    if configs and not isinstance(kwargs.get("config_dir"), str):
        raise ProtocolError(
            "'configs' given but kwargs.config_dir (the directory to write "
            "them into) is missing"
        )
    tenant = payload.get("tenant", "default")
    if not isinstance(tenant, str) or not tenant:
        raise ProtocolError("'tenant' must be a non-empty string")
    try:
        priority = int(payload.get("priority", 0))
    except (TypeError, ValueError):
        raise ProtocolError("'priority' must be an integer") from None
    record = {
        "schema": SCHEMA_VERSION,
        "type": payload.get("type", "workflow"),
        "workflow": workflow.strip(),
        "kwargs": kwargs,
        "configs": configs,
        "tenant": tenant,
        "priority": priority,
    }
    if microbatch is not None:
        record["microbatch"] = microbatch
    return record


def resolve_workflow(spec: str):
    """Resolve a workflow spec to a Task class.

    A bare name looks up ``cluster_tools_tpu.workflows`` (the supported
    catalog); ``pkg.mod:Class`` (or dotted ``pkg.mod.Class``) imports any
    Task subclass.  Resolution runs arbitrary import-time code, which is
    why it is only ever reached behind the daemon's request token (the
    mode-0600 ``serve.json``): the trust boundary is "can read the
    daemon owner's files", like the pickled ``task.pkl`` the cluster
    workers already load — NOT "can open a loopback socket"."""
    from ..runtime.task import Task

    cls = None
    if ":" in spec:
        mod_name, _, cls_name = spec.partition(":")
    elif "." in spec:
        mod_name, _, cls_name = spec.rpartition(".")
    else:
        mod_name, cls_name = "", spec
    if not mod_name:
        from .. import workflows

        cls = getattr(workflows, cls_name, None)
        if cls is None:
            raise ProtocolError(
                f"unknown workflow {spec!r} (not in "
                "cluster_tools_tpu.workflows; use 'pkg.mod:Class' for "
                "custom tasks)"
            )
    else:
        try:
            mod = importlib.import_module(mod_name)
        except ImportError as e:
            raise ProtocolError(f"cannot import {mod_name!r}: {e}") from e
        cls = getattr(mod, cls_name, None)
        if cls is None:
            raise ProtocolError(f"{mod_name!r} has no attribute {cls_name!r}")
    if not (isinstance(cls, type) and issubclass(cls, Task)):
        raise ProtocolError(f"{spec!r} is not a Task subclass")
    return cls


def job_signature(record: Dict[str, Any]) -> Tuple:
    """The warm-state key of a job: workflow class + block geometry.

    Two jobs sharing a signature run the same jit programs at the same
    shapes, so the second is served from the daemon's in-process compile
    caches — the ``serve.warm_compile_jobs`` counter keys on this (the
    per-job persistent-cache hit/miss deltas are recorded alongside in
    the job result; in-memory cache hits emit no jax events, which is
    precisely why they need their own accounting)."""
    if record.get("type") == "event_batch":
        # ctt-events: the kernel pads frame counts AND frame shapes to
        # pow2 buckets, so compiled programs key on connectivity (the only
        # compile-static knob), not on block geometry or how many frames a
        # batch carries — a sustained stream of ragged batches is warm
        # from the second submission on
        ev_conf = record.get("configs", {}).get("events")
        connectivity = 2
        if isinstance(ev_conf, dict):
            connectivity = int(ev_conf.get("connectivity", 2))
        return (record["workflow"], "event_batch", connectivity)
    if record.get("type") == "ingest":
        # ctt-ingest: the chain's compiled programs key on the domain
        # (which chain runs) and block geometry; a takeover/resume of the
        # same stream — or a second stream at the same geometry — is warm
        kwargs = record.get("kwargs", {})
        domain = kwargs.get("domain", "volume") if isinstance(
            kwargs, dict) else "volume"
        block_shape = None
        gconf = record.get("configs", {}).get("global")
        if isinstance(gconf, dict):
            bs = gconf.get("block_shape")
            if isinstance(bs, (list, tuple)):
                block_shape = tuple(int(b) for b in bs)
        return (record["workflow"], "ingest", domain, block_shape)
    block_shape = None
    gconf = record.get("configs", {}).get("global")
    if isinstance(gconf, dict):
        bs = gconf.get("block_shape")
        if isinstance(bs, (list, tuple)):
            block_shape = tuple(int(b) for b in bs)
    return (record["workflow"], block_shape)


# job types whose compute stage is safe to stack across jobs: both speak
# the split batch protocol, and everything their compute reads beyond the
# stacked payload is pinned by the signature below (configs JSON; the
# hierarchy artifact for resegment).  "workflow" stays out — arbitrary
# Task classes make no stacking promise — and "ingest" is long-lived.
MICROBATCH_TYPES = ("event_batch", "resegment")


def microbatch_signature(record: Dict[str, Any]) -> Optional[Tuple]:
    """The aggregation key of a job (ctt-microbatch), or None when the
    job must dispatch alone.

    Strictly finer than :func:`job_signature`: two jobs may only share a
    stacked dispatch when their compute stages are interchangeable —
    same workflow/type, byte-identical configs (``compute_batch`` reads
    kernel knobs from the merged config, so "same compiled program" is
    not enough), and for ``resegment`` the same hierarchy artifact (the
    cut table lives on the task instance, derived from hierarchy +
    threshold).  Block geometry rides the configs.  Inputs/outputs stay
    per member: the stack contract concatenates read payloads, so member
    volumes only need to share the block shape, never the data."""
    if record.get("microbatch") is False:
        return None
    if record.get("type") not in MICROBATCH_TYPES:
        return None
    configs = record.get("configs") or {}
    try:
        conf_key = json.dumps(configs, sort_keys=True)
    except (TypeError, ValueError):
        return None
    artifact = None
    if record.get("type") == "resegment":
        kwargs = record.get("kwargs") or {}
        artifact = kwargs.get("hierarchy_path") if isinstance(
            kwargs, dict) else None
    return (job_signature(record), conf_key, artifact)
