"""ctt-serve: the persistent serving daemon ("millions of users" mode).

Every workflow run used to be a cold process: interpreter + jax import,
mesh/device resolution, XLA compiles (the persistent disk cache helps but
still re-loads executables), an empty decoded-chunk LRU, and device
buffers dropped between tasks.  ``python -m cluster_tools_tpu.serve``
keeps all of that warm in ONE long-lived process — the
:class:`runtime.workflow.ExecutionContext` extracted from ``build()`` —
and accepts workflow *submissions* over a local HTTP API, with a durable
job queue, admission control, per-tenant concurrency quotas, and
priorities.  Execution is byte-identical to a fresh-process ``build()``;
only the setup cost is amortized.

Layout:

  * :mod:`serve.protocol`  — the submission wire schema + workflow
    resolution (what a job JSON may say and how it becomes a Task);
  * :mod:`serve.jobs`      — the durable on-disk job queue (the ctt-steal
    ``publish_once`` lease/result idiom over job granularity: queued jobs
    survive daemon death, stale leases requeue on restart);
  * :mod:`serve.admission` — queue-depth + per-tenant quota gate (held
    fleet-wide via the two-phase shared-dir recount);
  * :mod:`serve.fleet`     — multi-daemon fault tolerance (ctt-fleet):
    fleet heartbeats, peer liveness, fast-path lease failover, elastic
    capacity advice;
  * :mod:`serve.server`    — the daemon (HTTP endpoints, executor
    threads, SIGTERM drain);
  * :mod:`serve.client`    — the local submission client.
"""

from .client import QuotaRejected, ServeClient, read_endpoint
from .fleet import FleetView, read_peers, scale_advice
from .jobs import JobQueue
from .server import ServeDaemon

__all__ = [
    "FleetView",
    "JobQueue",
    "QuotaRejected",
    "ServeClient",
    "ServeDaemon",
    "read_endpoint",
    "read_peers",
    "scale_advice",
]
