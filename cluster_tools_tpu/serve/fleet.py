"""ctt-fleet: peer liveness + capacity advice for a multi-daemon fleet.

N serve daemons over ONE shared state dir are already *correct* — the
durable job queue's exclusive leases arbitrate who runs what — but a
dead daemon's leases only expire through the slow staleness rule
(3 x ``lease_s``, which an operator may set to minutes for long jobs).
This module adds the fast path: each daemon publishes a **fleet
heartbeat** into the state dir on the ctt-watch cadence,

    <state_dir>/daemon.<id>.json
      {"id", "pid", "host", "port", "wall", "mono", "interval_s",
       "seq", "draining", "exiting", "running_jobs", "queued",
       "concurrency"}

and a peer that finds a job lease owned by a daemon whose beat says it
is gone — an ``exiting`` stamp, or a beat older than
``STALE_INTERVALS`` x its *promised* cadence (the ctt-watch rule: every
beat carries its own ``interval_s``, so readers never guess) — expires
that lease **immediately** instead of waiting out the lease window.
Recovery latency is then bounded by the heartbeat cadence, not by
``lease_s``.

Liveness is deliberately three-valued (:meth:`FleetView.is_dead`):
``True`` only on positive evidence of death; ``None`` when the owner
never published a beat (a pre-fleet daemon, or one killed inside the
claim-to-first-beat window — the daemon closes that window by beating
*before* its executors start, but a reader still must not guess).
``None`` falls back to the slow lease-staleness rule, so the fast path
can only ever be an *optimization*, never a new way to steal a live
daemon's job.

Chaos: beat payloads pass through the ``fleet.write`` torn-write site —
a truncated ``daemon.<id>.json`` must degrade to mtime ageing (the
runtime/queue.py torn-lease convention), not crash a peer or misdeclare
the writer dead.

:func:`scale_advice` is the elastic-capacity hook: advice only (spawn /
drain / hold from fleet-wide backlog vs live capacity), for an external
supervisor to act on — the fleet itself never forks daemons.
"""

from __future__ import annotations

import itertools
import json
import os
import re
import socket
import threading
import time
from typing import Any, Callable, Dict, Optional

from .. import faults
from ..obs import heartbeat as obs_heartbeat
from ..obs import trace as obs_trace
from ..runtime.queue import STALE_INTERVALS
from ..utils.store_backend import backend_for

__all__ = [
    "FleetBeat",
    "FleetView",
    "beat_path",
    "default_daemon_id",
    "read_peers",
    "scale_advice",
]

_BEAT_RE = re.compile(r"^daemon\.([A-Za-z0-9_.-]+)\.json$")
_ID_SAFE_RE = re.compile(r"[^A-Za-z0-9_.-]+")
_instance_seq = itertools.count()


def default_daemon_id() -> str:
    """``<host>-<pid>-<n>``: unique per daemon *instance*, not just per
    process — the test harness runs several in-process daemons over one
    state dir, and two daemons sharing an id would shadow each other's
    beats.  ``CTT_DAEMON_ID`` overrides (sanitized to filename-safe)."""
    env = os.environ.get("CTT_DAEMON_ID")
    if env:
        return _ID_SAFE_RE.sub("-", env.strip()) or "daemon"
    host = socket.gethostname().split(".")[0] or "host"
    return _ID_SAFE_RE.sub(
        "-", f"{host}-{os.getpid()}-{next(_instance_seq)}"
    )


def beat_path(state_dir: str, daemon_id: str) -> str:
    # backend join: the state dir may be an object-store prefix
    # (ctt-diskless) — beats then ride PUTs like every other state file
    return backend_for(state_dir).join(state_dir, f"daemon.{daemon_id}.json")


class FleetBeat:
    """One daemon's fleet heartbeat publisher.

    ``start()`` stamps the first beat *synchronously* before returning —
    the daemon calls it before its executor threads exist, so by the
    time any lease carries this daemon's id there is already a beat for
    peers to judge it by (no claim-to-first-beat blind window).  Then a
    thread re-stamps every ``interval_s``; ``stop(final=True)`` stamps a
    terminal ``exiting`` beat so peers fail over in one cadence instead
    of three."""

    def __init__(
        self,
        state_dir: str,
        daemon_id: str,
        interval_s: Optional[float] = None,
        info_fn: Optional[Callable[[], Dict[str, Any]]] = None,
    ):
        self.state_dir = state_dir
        self.id = daemon_id
        self._backend = backend_for(state_dir)
        self.path = beat_path(state_dir, daemon_id)
        try:
            self.interval_s = float(interval_s) if interval_s else 0.0
        except (TypeError, ValueError):
            self.interval_s = 0.0
        if self.interval_s <= 0:
            self.interval_s = obs_heartbeat.interval_s()
        self._info_fn = info_fn
        self._seq = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self, exiting: bool = False) -> None:
        """Stamp one beat (atomic replace, the lease convention)."""
        with self._lock:
            rec = {
                "id": self.id,
                "pid": os.getpid(),
                "wall": time.time(),
                "mono": obs_trace.monotonic(),
                "interval_s": self.interval_s,
                "seq": self._seq,
                "exiting": bool(exiting),
            }
            if self._info_fn is not None:
                try:
                    rec.update(self._info_fn() or {})
                except Exception as e:
                    # a beat must land even if the stats scan hiccups —
                    # record the failure in the beat itself
                    rec["info_error"] = repr(e)
            self._seq += 1
            payload = json.dumps(rec, sort_keys=True).encode()
        torn = faults.mangle("fleet.write", payload, id=self.id)
        try:
            self._backend.write_bytes(
                self.path, torn if torn is not None else payload
            )
        except OSError:
            # best-effort, the heartbeat convention: a full disk costs a
            # spurious fast-path miss (peers fall back to lease ageing)
            pass

    def start(self) -> "FleetBeat":
        self.beat()  # synchronous first stamp: no blind window
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="ctt-fleet-beat", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.beat()

    def stop(self, final: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final:
            self.beat(exiting=True)


def read_peers(state_dir: str) -> Dict[str, Dict[str, Any]]:
    """All published fleet beats, id -> record.  A torn/unreadable beat
    degrades to ``{"id": ..., "torn": True}`` with no ``wall`` stamp —
    callers age it from file mtime (:meth:`FleetView.is_dead` does)."""
    peers: Dict[str, Dict[str, Any]] = {}
    backend = backend_for(state_dir)
    try:
        # backend-routed: paginated continuation listing on a remote
        # state dir — >1 page of peers scans complete, never truncated
        names = backend.listdir(state_dir)
    except OSError:
        return peers
    for name in names:
        m = _BEAT_RE.match(name)
        if not m:
            continue
        pid = m.group(1)
        path = backend.join(state_dir, name)
        try:
            rec = json.loads(backend.read_bytes(path).decode())
            if not isinstance(rec, dict):
                rec = {"torn": True}
        except FileNotFoundError:
            continue  # beat vanished between listing and read
        except (OSError, ValueError):
            # torn payload — or a transient remote read failure, which
            # degrades the same safe way: mtime ageing of a FRESH beat
            # never declares its writer dead
            rec = {"torn": True}
        rec.setdefault("id", pid)
        peers[pid] = rec
    return peers


class FleetView:
    """Peer liveness over the shared state dir, with a tiny TTL cache so
    a claim scan over many candidate leases costs one directory read,
    not one per lease."""

    def __init__(self, state_dir: str, self_id: Optional[str] = None,
                 cache_ttl_s: float = 0.2):
        self.state_dir = state_dir
        self.self_id = self_id
        self.cache_ttl_s = float(cache_ttl_s)
        self._backend = backend_for(state_dir)
        self._remote = self._backend.is_remote
        self._lock = threading.Lock()
        self._cached: Optional[Dict[str, Dict[str, Any]]] = None
        self._cached_mono = -1.0
        # first-seen-torn tracking (monotonic): the store-clock-skew
        # guard for remote torn-beat ageing — see _beat_age_s
        self._torn_seen: Dict[str, float] = {}
        try:
            self._clock_skew = float(
                os.getenv("CTT_SCHED_CLOCK_SKEW_S") or 0.0
            )
        except (TypeError, ValueError):
            self._clock_skew = 0.0

    def _now(self) -> float:
        # the injected-clock seam shared with runtime/queue.py and
        # JobQueue: skew shifts every staleness judgement this reader
        # makes, never the stamps writers publish
        return time.time() + self._clock_skew  # ctt: noqa[CTT008] wall by design: beat stamps are cross-process wall times (mtime-ageing contract), not durations

    def peers(self, refresh: bool = False) -> Dict[str, Dict[str, Any]]:
        now = obs_trace.monotonic()
        with self._lock:
            if (
                not refresh
                and self._cached is not None
                and now - self._cached_mono <= self.cache_ttl_s
            ):
                return self._cached
        fresh = read_peers(self.state_dir)
        with self._lock:
            self._cached = fresh
            self._cached_mono = now
        return fresh

    def _beat_age_s(self, daemon_id: str, rec: Dict[str, Any],
                    now: float) -> Optional[float]:
        path = beat_path(self.state_dir, daemon_id)
        stamp = None
        try:
            stamp = float(rec["wall"])
        except (KeyError, TypeError, ValueError):
            pass
        if stamp is None:
            # torn beat: age from mtime, the torn-lease convention
            # (POSIX getmtime / remote Last-Modified HEAD)
            mtime = self._backend.mtime(path)
            if mtime is None:
                return None
            age = max(0.0, now - mtime)
            if self._remote:
                # Last-Modified carries the STORE's wall clock; cap the
                # age by how long THIS process has actually watched the
                # beat be torn (monotonic) so a store clock running
                # behind can only delay a death verdict, never hasten it
                now_mono = obs_trace.monotonic()
                with self._lock:
                    first = self._torn_seen.setdefault(path, now_mono)
                age = min(age, max(0.0, now_mono - first))
            return age
        with self._lock:
            self._torn_seen.pop(path, None)
        return max(0.0, now - stamp)

    def is_dead(self, daemon_id: str,
                now: Optional[float] = None) -> Optional[bool]:
        """Three-valued liveness: ``True`` = positive evidence the
        daemon is gone (``exiting`` stamp, or beat age over
        ``STALE_INTERVALS`` x its promised cadence), ``False`` = provably
        beating, ``None`` = no beat published (unknown — callers MUST
        fall back to the slow lease-staleness rule).  A daemon never
        declares itself dead."""
        if self.self_id is not None and daemon_id == self.self_id:
            return False
        rec = self.peers().get(daemon_id)
        if rec is None:
            return None
        if rec.get("exiting"):
            return True
        if now is None:
            now = self._now()
        age = self._beat_age_s(daemon_id, rec, now)
        if age is None:
            return None  # beat vanished between scan and stat: unknown
        try:
            interval = float(rec.get("interval_s") or 0.0)
        except (TypeError, ValueError):
            interval = 0.0
        if interval <= 0:
            interval = obs_heartbeat.interval_s()
        return age > STALE_INTERVALS * interval

    def live(self, now: Optional[float] = None) -> Dict[str, Dict[str, Any]]:
        """The beating (non-dead, non-exiting) peers, id -> record."""
        if now is None:
            now = self._now()
        return {
            pid: rec for pid, rec in self.peers().items()
            if self.is_dead(pid, now=now) is False
        }


def scale_advice(state_dir: str,
                 stats: Optional[Dict[str, Any]] = None,
                 view: Optional[FleetView] = None) -> Dict[str, Any]:
    """Elastic-capacity hook: ``{"action": "spawn"|"drain"|"hold", ...}``
    from fleet-wide backlog vs live capacity.  **Advice only** — the
    fleet never forks daemons; an external supervisor polls this (via
    ``/healthz``) and acts.  ``stats`` is a ``JobQueue.stats()`` dict
    (the caller usually has one in hand); without it only the peer-side
    numbers are reported and the action is ``hold``."""
    if view is None:
        view = FleetView(state_dir)
    now = view._now()
    live = view.live(now=now)
    capacity = 0
    draining = 0
    for rec in live.values():
        if rec.get("draining"):
            draining += 1
            continue
        try:
            capacity += max(int(rec.get("concurrency", 1)), 1)
        except (TypeError, ValueError):
            capacity += 1
    advice: Dict[str, Any] = {
        "daemons": len(live),
        "draining": draining,
        "capacity": capacity,
        "action": "hold",
    }
    if stats is None:
        advice["reason"] = "no queue stats supplied"
        return advice
    queued = int(stats.get("queued", 0))
    running = int(stats.get("running", 0))
    advice["queued"] = queued
    advice["running"] = running
    if queued > capacity:
        advice["action"] = "spawn"
        advice["reason"] = (
            f"{queued} queued job(s) exceed fleet capacity {capacity}"
        )
    elif (
        len(live) - draining > 1
        and queued == 0
        and running < max(capacity - 1, 0)
    ):
        advice["action"] = "drain"
        advice["reason"] = (
            f"idle headroom: {running} running over {capacity} capacity "
            f"across {len(live) - draining} active daemon(s)"
        )
    else:
        advice["reason"] = "backlog within capacity"
    return advice
