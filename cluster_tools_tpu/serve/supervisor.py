"""ctt-diskless supervisor: ACT on :func:`serve.fleet.scale_advice`.

``scale_advice`` is advice only — the fleet never forks daemons.  This
module is the actor: a :class:`Supervisor` polls the shared state dir
(POSIX or an object-store prefix — ``http(s)://``/``s3://``), compares
fleet-wide backlog against live capacity, and converges the daemon count
toward a clamped target by spawning real ``python -m
cluster_tools_tpu.serve`` processes or draining surplus ones (SIGTERM —
the daemon's drain path: in-flight jobs finish, queued jobs stay
durable).

The supervisor is **stateless by construction**: every input to a
scaling decision lives in the state dir (fleet beats, job records), and
the supervisor's own ``supervisor.<id>.json`` record is published there
too — purely observational output, never read back for decisions.  A
supervisor SIGKILLed mid-burst and restarted re-adopts the running fleet
from beats alone (counted in ``serve.supervisor_adoptions``) and resumes
scaling as if it had never died.  The in-memory child-process table is a
*preference* (drain own children first, cheap reaping), not a source of
truth.

Pacing: at most ONE spawn or drain per poll round, and an own child
that is alive but not yet beating counts as *pending* capacity (for
``spawn_grace_s`` after its spawn) — a daemon takes longer to publish
its first beat than a poll round, and spawning again before the beat
lands would overshoot the ceiling.  Capacity changes take a heartbeat
cadence to show up in beats; acting faster than the feedback loop
oscillates.

Cross-host scope: the default drain path signals by pid and therefore
only reaches daemons on the supervisor's own host (own children, or a
pid the beat proves is local).  Multi-host fleets inject ``drain_fn``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .. import faults
from ..obs import heartbeat as obs_heartbeat
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..utils.store_backend import backend_for
from .fleet import FleetView, scale_advice
from .jobs import JobQueue

__all__ = ["Supervisor", "default_supervisor_id", "main"]


def default_supervisor_id() -> str:
    """``sup-<host>-<pid>``: unique per supervisor process; a restarted
    supervisor gets a fresh id and its predecessor's state record simply
    ages out (the record is observational, never a decision input)."""
    host = socket.gethostname().split(".")[0] or "host"
    return f"sup-{host}-{os.getpid()}"


class Supervisor:
    """Elastic-fleet actor over one shared state dir.

    ``spawn_fn(daemon_id) -> handle`` and ``drain_fn(daemon_id, rec)``
    are injection seams (tests drive scaling without real processes);
    the defaults spawn ``python -m cluster_tools_tpu.serve`` children
    and SIGTERM by beat pid.  ``poll_once()`` is the whole control step
    — public so tests and the CLI ``--once`` mode can single-step it.
    """

    def __init__(
        self,
        state_dir: str,
        min_daemons: int = 1,
        max_daemons: int = 3,
        poll_s: Optional[float] = None,
        daemon_args: Optional[List[str]] = None,
        spawn_fn: Optional[Callable[[str], Any]] = None,
        drain_fn: Optional[Callable[[str, Dict[str, Any]], None]] = None,
        supervisor_id: Optional[str] = None,
    ):
        self._backend = backend_for(state_dir)
        self._backend.makedirs(state_dir)
        self.state_dir = state_dir
        self.id = supervisor_id or default_supervisor_id()
        self.min_daemons = max(int(min_daemons), 0)
        self.max_daemons = max(int(max_daemons), self.min_daemons)
        try:
            self.poll_s = float(poll_s) if poll_s else 0.0
        except (TypeError, ValueError):
            self.poll_s = 0.0
        if self.poll_s <= 0:
            self.poll_s = obs_heartbeat.interval_s()
        self.daemon_args = list(daemon_args or [])
        self._spawn_fn = spawn_fn
        self._drain_fn = drain_fn
        # own children this incarnation: daemon_id -> subprocess handle.
        # Convenience only — a restarted supervisor has an empty table
        # and still manages the fleet correctly through beats.
        self._procs: Dict[str, Any] = {}
        self._spawn_times: Dict[str, float] = {}  # daemon_id -> monotonic
        # how long an own child may live un-beating before it stops
        # counting as pending capacity (hung-startup escape hatch)
        self.spawn_grace_s = 30.0
        # flicker damping: a daemon seen live this recently still counts
        # as capacity even when its current beat reads stale — on a
        # loaded host a beat delayed one staleness window is overwhelming
        # likely a scheduling hiccup, and replacing it would overshoot.
        # Reaped children and ``exiting`` beats bypass the grace (positive
        # death evidence), so only genuinely ambiguous silence is damped.
        self.flicker_grace_s = max(2.0 * self.poll_s, 5.0)
        self._seen_live: Dict[str, float] = {}  # daemon_id -> monotonic
        self._known: set = set()  # daemon ids already counted (adoption)
        self._spawn_seq = 0
        self._seq = 0
        self._exiting = False
        self._stop = threading.Event()
        # queue accounting reuses the daemon's own stats path (dense-seq
        # index, paginated listings on remote stores)
        self._jobs = JobQueue(self._backend.join(state_dir, "jobs"))

    # -- control step --------------------------------------------------------

    def poll_once(self) -> Dict[str, Any]:
        """One decision round: observe (beats + queue), compute the
        clamped target, act (at most one spawn OR one drain), publish
        the supervisor state record.  Returns the advice dict augmented
        with ``target``/``acted`` for callers that introspect."""
        faults.check("fleet.supervisor", id=self.id)
        self._reap()
        view = FleetView(self.state_dir)
        stats = self._jobs.stats()
        live = view.live()
        for daemon_id in live:
            if daemon_id not in self._known:
                self._known.add(daemon_id)
                if daemon_id not in self._procs:
                    # running daemon we never spawned: a restarted
                    # supervisor re-adopting its predecessor's fleet
                    obs_metrics.inc("serve.supervisor_adoptions")
        advice = scale_advice(self.state_dir, stats=stats, view=view)
        active = int(advice["daemons"]) - int(advice["draining"])
        target = active
        if advice["action"] == "spawn":
            target = active + 1
        elif advice["action"] == "drain":
            target = active - 1
        target = min(max(target, self.min_daemons), self.max_daemons)
        obs_metrics.set_gauge("fleet.target_daemons", target)
        # pending: own children provably alive (poll() is None) whose
        # first beat has not landed yet — already-bought capacity, so a
        # faster-than-heartbeat poll cadence cannot overshoot the ceiling
        now = time.monotonic()
        for daemon_id in live:
            self._seen_live[daemon_id] = now
            self._spawn_times.pop(daemon_id, None)
        for daemon_id, rec in view.peers().items():
            if rec.get("exiting"):
                # a clean exit is positive death evidence: no flicker
                # grace (a drained daemon must not suppress a spawn)
                self._seen_live.pop(daemon_id, None)
        pending = 0
        for daemon_id, proc in self._procs.items():
            if daemon_id in live or daemon_id in self._seen_live:
                continue  # beating (or flicker-covered below)
            poll = getattr(proc, "poll", None)
            if poll is None or poll() is not None:
                continue  # opaque handle (tests) or exited: beats decide
            born = self._spawn_times.get(daemon_id)
            if born is not None and now - born <= self.spawn_grace_s:
                pending += 1
        # flicker: recently-live daemons whose beat went stale this very
        # moment — damped capacity, not a death verdict (a SIGKILLed
        # daemon stops beating for good and ages past the grace)
        flicker = 0
        for daemon_id, seen in list(self._seen_live.items()):
            if daemon_id in live:
                continue
            if now - seen <= self.flicker_grace_s:
                flicker += 1
            else:
                del self._seen_live[daemon_id]
        acted = "hold"
        if target > active + pending + flicker:
            self._spawn_one()
            acted = "spawn"
        elif target < active:
            acted = "drain" if self._drain_one(live) else "hold"
        advice = dict(advice)
        advice["target"] = target
        advice["acted"] = acted
        self._publish_state(advice)
        obs_metrics.flush()
        return advice

    def _reap(self) -> None:
        for daemon_id, proc in list(self._procs.items()):
            poll = getattr(proc, "poll", None)
            if poll is not None and poll() is not None:
                del self._procs[daemon_id]
                self._spawn_times.pop(daemon_id, None)
                # a reaped child is positive death evidence: no flicker
                # grace, its replacement can spawn this round
                self._seen_live.pop(daemon_id, None)

    def _spawn_one(self) -> None:
        daemon_id = f"{self.id}-d{self._spawn_seq}"
        self._spawn_seq += 1
        if self._spawn_fn is not None:
            handle = self._spawn_fn(daemon_id)
        else:
            handle = subprocess.Popen(
                [
                    sys.executable, "-m", "cluster_tools_tpu.serve",
                    "--state-dir", self.state_dir,
                    "--daemon-id", daemon_id,
                ]
                + self.daemon_args
            )
        self._procs[daemon_id] = handle
        self._spawn_times[daemon_id] = time.monotonic()
        self._known.add(daemon_id)
        obs_metrics.inc("serve.supervisor_spawns")

    def _drain_one(self, live: Dict[str, Dict[str, Any]]) -> bool:
        """SIGTERM one surplus daemon (its drain path, not a kill).
        Prefers own children; falls back to a live peer whose beat pid
        is reachable on this host.  Returns whether anyone was told."""
        victims = [
            d for d, rec in live.items() if not rec.get("draining")
        ]
        victims.sort(key=lambda d: (d not in self._procs, d))
        for daemon_id in victims:
            rec = live[daemon_id]
            if self._drain_fn is not None:
                self._drain_fn(daemon_id, rec)
            else:
                try:
                    pid = int(rec.get("pid") or 0)
                except (TypeError, ValueError):
                    pid = 0
                if pid <= 0:
                    continue
                if daemon_id not in self._procs:
                    try:
                        os.kill(pid, 0)  # local-host guard: pid exists?
                    except OSError:
                        continue  # foreign host (or gone): not ours
                try:
                    os.kill(pid, signal.SIGTERM)
                except OSError:
                    continue
            obs_metrics.inc("serve.supervisor_drains")
            return True
        return False

    # -- state record ---------------------------------------------------------

    def _publish_state(self, advice: Dict[str, Any]) -> None:
        """``supervisor.<id>.json``: the heartbeat-shaped observational
        record (analysis/protocols.py ``supervisor_state`` schema).
        Best-effort, the beat convention — a failed PUT costs one stale
        observation, never a scaling decision."""
        rec = {
            "id": self.id,
            "pid": os.getpid(),
            "host": socket.gethostname().split(".")[0] or "host",
            "wall": time.time(),
            "mono": obs_trace.monotonic(),
            "interval_s": self.poll_s,
            "seq": self._seq,
            "exiting": self._exiting,
            "target_daemons": int(advice.get("target", 0)),
            "active": int(advice.get("daemons", 0))
            - int(advice.get("draining", 0)),
            "action": str(advice.get("acted", "hold")),
            "reason": str(advice.get("reason", "")),
        }
        self._seq += 1
        try:
            self._backend.write_bytes(
                self._backend.join(
                    self.state_dir, f"supervisor.{self.id}.json"
                ),
                json.dumps(rec, sort_keys=True).encode(),
            )
        except OSError:
            pass

    # -- lifecycle ------------------------------------------------------------

    def install_signal_handlers(self) -> None:
        def _stop(signum, frame):
            self._stop.set()

        signal.signal(signal.SIGTERM, _stop)
        signal.signal(signal.SIGINT, _stop)

    def run(self) -> int:
        """Poll until SIGTERM/SIGINT.  Exiting leaves the fleet RUNNING
        — daemons are durable state-dir citizens, and the next
        supervisor (or the restarted same one) re-adopts them from
        beats; that asymmetry is what makes SIGKILLing the supervisor
        harmless."""
        while not self._stop.is_set():
            try:
                self.poll_once()
            except OSError:
                # store hiccup mid-poll: skip the round, the next one
                # re-observes from scratch (no carried state to corrupt)
                pass
            self._stop.wait(self.poll_s)
        self._exiting = True
        try:
            self._publish_state({"target": 0, "acted": "exit",
                                 "reason": "supervisor stopped"})
        except OSError:
            pass
        obs_metrics.flush()
        return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m cluster_tools_tpu.serve.supervisor",
        description="ctt-diskless: act on fleet scale advice — spawn or "
        "drain serve daemons over a shared (object-store or POSIX) "
        "state dir",
    )
    parser.add_argument("--state-dir", required=True,
                        help="shared state dir; POSIX path or "
                        "http(s):// / s3:// object-store prefix")
    parser.add_argument("--min", type=int, default=1, dest="min_daemons",
                        help="daemon floor (default 1)")
    parser.add_argument("--max", type=int, default=3, dest="max_daemons",
                        help="daemon ceiling (default 3)")
    parser.add_argument("--poll-s", type=float, default=None,
                        help="decision cadence (default: heartbeat "
                        "interval)")
    parser.add_argument("--once", action="store_true",
                        help="single decision round, then exit (smoke "
                        "and debugging)")
    parser.add_argument("--daemon-arg", action="append", default=[],
                        help="extra arg passed through to each spawned "
                        "daemon (repeatable), e.g. --daemon-arg "
                        "--concurrency --daemon-arg 2")
    args = parser.parse_args(argv)

    # telemetry mirrors the daemon: join the ambient run when
    # CTT_TRACE_DIR is set, else trace locally (tmp for remote state
    # dirs — telemetry is per-process scratch, not shared state)
    if not obs_trace.enabled() and not os.environ.get(obs_trace.ENV_DIR):
        backend = backend_for(args.state_dir)
        trace_dir = (
            os.path.join(tempfile.gettempdir(),
                         f"ctt-supervisor-trace-{os.getpid()}")
            if backend.is_remote
            else os.path.join(args.state_dir, "trace")
        )
        obs_trace.enable(trace_dir, f"supervisor_{os.getpid()}",
                         export_env=False)

    sup = Supervisor(
        args.state_dir,
        min_daemons=args.min_daemons,
        max_daemons=args.max_daemons,
        poll_s=args.poll_s,
        daemon_args=args.daemon_arg,
    )
    sup.install_signal_handlers()
    print(f"[supervisor] {sup.id} over {args.state_dir} "
          f"(min {sup.min_daemons}, max {sup.max_daemons}, "
          f"poll {sup.poll_s:.2f}s)", flush=True)
    if args.once:
        advice = sup.poll_once()
        print(json.dumps(advice, sort_keys=True), flush=True)
        return 0
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
