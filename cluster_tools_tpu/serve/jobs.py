"""ctt-serve durable job queue: the ctt-steal lease idiom at job grain.

``runtime/queue.py`` arbitrates *block batches* inside one dispatch with
an immutable manifest; the daemon needs the same guarantees for *jobs*
that arrive over time — so this module reuses the exact primitives
(``publish_once`` exclusive links, atomically re-stamped leases, the
``STALE_INTERVALS`` staleness rule, first-writer-wins results) over a
growing directory instead of a fixed manifest:

    <state_dir>/jobs/
      job.<id>.json          the submission record (published exactly once)
      admit.<id>.json        fleet admission marker (ctt-fleet): a record
                             submitted provisionally (``admitted: false``)
                             becomes claimable only once its submitter
                             recounts the shared dir and publishes this —
                             the two-phase step that makes queue depth and
                             tenant quotas hold across k daemons instead
                             of per daemon
      lease.<id>.g<g>.json   generation-g execution ownership, re-stamped
                             every ``lease_s`` by the running daemon and
                             stamped with the owner's **daemon id at claim
                             time**; a stamp older than 3 x lease_s means
                             the owner died mid-job — the next daemon on
                             the same state dir claims gen g+1 (requeue)
      result.<id>.json       terminal record, first writer wins

ctt-fleet hardening on top of the base queue:

  * **fast-path expiry** — with a :class:`serve.fleet.FleetView`, a lease
    whose owning daemon's fleet heartbeat says it is gone (``exiting``
    stamp, or beat age > 3 x its cadence) expires *immediately*; recovery
    latency is bounded by the heartbeat cadence, not ``lease_s``.  Such
    takeovers count as ``serve.jobs_reclaimed`` (a subset of
    ``serve.leases_requeued``, which counts every gen>0 takeover).
  * **retry budget + quarantine** — a job may burn at most
    ``max_job_gens`` generations (takeover of gen g additionally waits
    out ``utils.retry.backoff_delay_s(g)``, so a poison job decelerates);
    the claim that would start generation ``max_job_gens`` instead parks
    the job as a first-writer-wins failed result with ``quarantined:
    true`` and a ``failure_log`` of every generation's last lease stamp
    (``serve.jobs_quarantined``).  Daemons survive; the job does not.
  * **limbo reaping** — a provisional record whose submitter died before
    publishing the admit marker is retracted (rejected result) once its
    submitter is fleet-dead or the record outlives the stale window, so
    it stops occupying admission headroom.

Everything a client submitted is therefore durable: daemon death loses
nothing (queued jobs sit untouched, a leased job's stale lease requeues),
and a SIGTERM drain only has to finish in-flight work — the disk is the
queue.  Claim order is (-priority, submission sequence): priorities are
literally claim order, as the lease substrate makes natural.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..obs import heartbeat as obs_heartbeat
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..runtime.queue import STALE_INTERVALS, publish_once
from ..utils.retry import backoff_delay_s
from ..utils.store_backend import backend_for

__all__ = ["JobClaim", "JobQueue"]

_JOB_RE = re.compile(r"^job\.(j\d{6})\.json$")
_ADMIT_RE = re.compile(r"^admit\.(j\d{6})\.json$")
_LEASE_RE = re.compile(r"^lease\.(j\d{6})\.g(\d+)\.json$")
_RESULT_RE = re.compile(r"^result\.(j\d{6})\.json$")

DEFAULT_MAX_JOB_GENS = 3


@dataclass
class JobClaim:
    """One leased job: the record plus the lease that owns it."""

    job_id: str
    record: Dict[str, Any]
    gen: int
    lease_path: str
    claim_wall: float = field(default_factory=time.time)
    # ctt-slo: stamped (via note_dispatch) when execution actually starts
    # — claim_wall..dispatch_wall is the microbatch window-wait phase
    dispatch_wall: Optional[float] = None


class JobQueue:
    # ctt-events: how long cached result/lease classifications may serve
    # ``stats()`` before being re-probed.  Staleness in this window only
    # ever OVER-counts in_flight (a just-finished job still counted), so
    # admission under-admits briefly — the documented conservative
    # direction — and never overshoots a limit.
    STATS_TTL_S = 0.05

    def __init__(self, root: str, lease_s: Optional[float] = None,
                 daemon_id: Optional[str] = None, fleet=None,
                 max_job_gens: Optional[int] = None):
        # ctt-diskless: every file operation routes through the store
        # backend, so ``root`` may be a POSIX dir OR an object-store
        # prefix (``http(s)://``, ``s3://``) — listings then ride the
        # paginated continuation GETs, existence probes are HEADs, and
        # torn-record ageing falls back to Last-Modified
        self._backend = backend_for(root)
        self._join = self._backend.join
        self._remote = self._backend.is_remote
        self._backend.makedirs(root)
        self.dir = root
        # remote torn-record ageing depends on the STORE's wall clock
        # (Last-Modified); guard against skew by also tracking when THIS
        # process first observed each torn record — see _stamp_age_s
        self._torn_lock = threading.Lock()
        self._torn_seen: Dict[str, float] = {}
        try:
            self._clock_skew = float(
                os.getenv("CTT_SCHED_CLOCK_SKEW_S") or 0.0
            )
        except (TypeError, ValueError):
            self._clock_skew = 0.0
        try:
            self.lease_s = float(lease_s) if lease_s else 0.0
        except (TypeError, ValueError):
            self.lease_s = 0.0
        if self.lease_s <= 0:
            self.lease_s = obs_heartbeat.interval_s()
        self.stale_after_s = STALE_INTERVALS * self.lease_s
        self.daemon_id = daemon_id
        self.fleet = fleet  # serve.fleet.FleetView (or None: no fast path)
        try:
            self.max_job_gens = (
                int(max_job_gens) if max_job_gens is not None
                else DEFAULT_MAX_JOB_GENS
            )
        except (TypeError, ValueError):
            self.max_job_gens = DEFAULT_MAX_JOB_GENS
        # <= 0 disables the budget (unbounded retries, the pre-fleet rule)

        # -- dense-seq stats index (ctt-events) ------------------------------
        # Sustained high-rate submission runs ``stats()`` under the submit
        # lock for EVERY request (two-phase admission) plus per heartbeat
        # and gauge publish; the full ``_scan()`` there is O(every job +
        # result + lease file ever written), which grows without bound
        # over a daemon's life.  Job ids are a dense sequence (publish_once
        # probing guarantees job.jN exists only after job.jN-1 does), so
        # new-record discovery is O(new) forward probes from the frontier,
        # and the unfinished set — bounded by queue depth, not history —
        # carries everything stats needs (tenant, seq, running/queued).
        self._idx_lock = threading.Lock()
        self._idx_max_seq = 0
        # jid -> {"seq", "tenant", "running"} for records with no result
        # file seen yet (provisional records count until retracted —
        # conservative, same as the scan-based accounting)
        self._idx_unfinished: Dict[str, Dict[str, Any]] = {}
        self._idx_lease_gen: Dict[str, int] = {}  # highest gen seen per jid
        self._idx_refreshed = -1e30  # monotonic stamp of the last refresh

    def _now(self) -> float:
        # the injected-clock seam shared with runtime/queue.py: skewing
        # CTT_SCHED_CLOCK_SKEW_S shifts every staleness judgement this
        # reader makes, without touching the authoritative stamps writers
        # publish
        return time.time() + self._clock_skew  # ctt: noqa[CTT008] wall by design: lease stamps are cross-process wall times (mtime-ageing contract), not durations

    def _index_advance_locked(self) -> None:
        """Advance the dense-id frontier: probe job.j<seq+1>.json forward
        until the first missing record.  Exact (no TTL): density means a
        missing record proves nothing beyond it exists yet, and a record
        published before ours is always at a lower seq — the fleet
        recount stays sound on records."""
        while True:
            jid = f"j{self._idx_max_seq + 1:06d}"
            rec = self._record(jid)
            if rec is None:
                # distinguish "not published yet" (stop: the frontier)
                # from "present but unreadable" (advance with defaults —
                # a stalled frontier would hide every later job forever)
                if not self._backend.exists(
                    self._join(self.dir, f"job.{jid}.json")
                ):
                    return
                rec = {}
            self._idx_max_seq += 1
            if not self._backend.exists(
                self._join(self.dir, f"result.{jid}.json")
            ):
                self._idx_unfinished[jid] = {
                    "seq": int(rec.get("seq", self._idx_max_seq)),
                    "tenant": rec.get("tenant", "default"),
                    "running": False,
                }

    def _index_classify_locked(self, now_mono: float) -> None:
        """TTL-gated refresh of the unfinished set: drop jobs whose result
        landed (one exists() per unfinished job), reclassify the rest as
        running/queued from their highest-generation lease (lease gens are
        dense from 0, so discovery is forward exists()-probes from the
        cached gen).  Work is bounded by the admission queue depth."""
        if now_mono - self._idx_refreshed < self.STATS_TTL_S:
            return
        now = self._now()
        for jid in list(self._idx_unfinished):
            if self._backend.exists(
                self._join(self.dir, f"result.{jid}.json")
            ):
                del self._idx_unfinished[jid]
                self._idx_lease_gen.pop(jid, None)
                continue
            gen = self._idx_lease_gen.get(jid, -1)
            while self._backend.exists(
                self._join(self.dir, f"lease.{jid}.g{gen + 1}.json")
            ):
                gen += 1
            running = False
            if gen >= 0:
                self._idx_lease_gen[jid] = gen
                state, _ = self._lease_state(
                    self._join(self.dir, f"lease.{jid}.g{gen}.json"),
                    gen, now,
                )
                running = state == "live"
            self._idx_unfinished[jid]["running"] = running
        self._idx_refreshed = now_mono

    def _index_discard(self, job_id: str) -> None:
        """Drop a job this process just finished/retracted — its result is
        on disk, so the next refresh would drop it anyway; discarding now
        frees the admission headroom without waiting out the TTL."""
        with self._idx_lock:
            self._idx_unfinished.pop(job_id, None)
            self._idx_lease_gen.pop(job_id, None)

    # -- directory scan ------------------------------------------------------

    def _scan(self):
        """(jobs, admits, leases, results): job ids present, admit-marker
        presence, highest-generation lease path per job, and terminal-
        record presence."""
        jobs: List[str] = []
        admits: set = set()
        leases: Dict[str, tuple] = {}
        results: set = set()
        try:
            # backend-routed: POSIX os.listdir, or the paginated remote
            # continuation (?limit=&marker=) — a >1-page state dir scans
            # complete, never silently truncated
            names = self._backend.listdir(self.dir)
        except OSError:
            names = []
        for name in names:
            m = _JOB_RE.match(name)
            if m:
                jobs.append(m.group(1))
                continue
            m = _RESULT_RE.match(name)
            if m:
                results.add(m.group(1))
                continue
            m = _ADMIT_RE.match(name)
            if m:
                admits.add(m.group(1))
                continue
            m = _LEASE_RE.match(name)
            if m:
                jid, g = m.group(1), int(m.group(2))
                cur = leases.get(jid)
                if cur is None or g > cur[0]:
                    leases[jid] = (g, self._join(self.dir, name))
        return sorted(jobs), admits, leases, results

    def _read_json(self, path: str) -> Optional[dict]:
        try:
            rec = json.loads(self._backend.read_bytes(path).decode())
            return rec if isinstance(rec, dict) else None
        except (OSError, ValueError):
            # absent, transient remote trouble, or torn JSON: all read as
            # "no parseable record" — the mtime-ageing fallback covers torn
            return None

    def _record(self, job_id: str) -> Optional[dict]:
        return self._read_json(self._join(self.dir, f"job.{job_id}.json"))

    def _owner_dead(self, owner: Optional[str]) -> bool:
        """Fast-path liveness (ctt-fleet): True only on positive evidence
        from the owner's fleet heartbeat.  No view, no owner stamp, or an
        unknown verdict all mean False — fall back to the slow rule."""
        if not owner or self.fleet is None or owner == self.daemon_id:
            return False
        return self.fleet.is_dead(owner) is True

    def _observed_age_s(self, path: str) -> float:
        """Seconds since THIS process first saw ``path`` torn — monotonic,
        so immune to every wall clock involved."""
        now_mono = obs_trace.monotonic()
        with self._torn_lock:
            first = self._torn_seen.setdefault(path, now_mono)
            return max(0.0, now_mono - first)

    def _forget_torn(self, path: str) -> None:
        with self._torn_lock:
            self._torn_seen.pop(path, None)

    def _stamp_age_s(self, path: str, rec: Optional[dict],
                     now: float) -> float:
        stamp = None
        if rec is not None:
            try:
                stamp = float(rec["wall"])
            except (KeyError, TypeError, ValueError):
                stamp = None
        if stamp is None:
            # torn record: age from mtime, the runtime/queue.py convention
            # (POSIX getmtime, or Last-Modified from a HEAD on a remote
            # state dir)
            mtime = self._backend.mtime(path)
            if mtime is None:
                return 0.0
            age = max(0.0, now - mtime)
            if self._remote:
                # the remote mtime is stamped by the STORE's wall clock;
                # a store clock running behind would inflate the age and
                # expire a live lease early.  Cap by the locally-observed
                # torn window (monotonic): a record can never be older to
                # us than the time we have actually watched it be torn —
                # skew can only delay expiry (safe), never hasten it.
                age = min(age, self._observed_age_s(path))
            return age
        self._forget_torn(path)
        return max(0.0, now - stamp)

    def _lease_age_s(self, path: str, now: float) -> float:
        return self._stamp_age_s(path, self._read_json(path), now)

    def _lease_state(self, path: str, gen: int,
                     now: float) -> Tuple[str, bool]:
        """Classify one lease: (``"live"`` | ``"backoff"`` |
        ``"expired"``, owner-was-fleet-dead).  Expiry is EITHER the slow
        stale rule (no stamp for 3 x lease_s) OR the fleet fast path (the
        owner's heartbeat proves it gone); either way the takeover of
        generation ``gen`` must additionally wait out
        ``backoff_delay_s(gen)`` — the between-generation backoff that
        makes a poison job burn its budget at a decelerating rate."""
        rec = self._read_json(path)
        dead = self._owner_dead((rec or {}).get("daemon"))
        age = self._stamp_age_s(path, rec, now)
        if not dead and age <= self.stale_after_s:
            return "live", False
        if age <= backoff_delay_s(gen):
            return "backoff", dead
        return "expired", dead

    # -- submission ----------------------------------------------------------

    def submit(self, record: Dict[str, Any], admitted: bool = True) -> str:
        """Durably publish one job; returns its id.  Ids are a dense
        sequence (claim order ties break on it), allocated by probing the
        next free slot with the exclusive link — concurrent submitters
        cannot collide.  ``admitted=False`` publishes a *provisional*
        record (ctt-fleet two-phase admission): unclaimable until
        :meth:`admit` lands, retractable via :meth:`retract`."""
        with self._idx_lock:
            # O(new records) frontier probe, not the O(history) dir scan
            self._index_advance_locked()
            seq = self._idx_max_seq + 1
        while True:
            job_id = f"j{seq:06d}"
            rec = dict(record)
            rec.update({"id": job_id, "seq": seq, "submit_wall": time.time()})
            if self.daemon_id is not None:
                rec.setdefault("daemon", self.daemon_id)
            if not admitted:
                rec["admitted"] = False
            if publish_once(
                self._join(self.dir, f"job.{job_id}.json"),
                json.dumps(rec, sort_keys=True).encode(),
            ):
                with self._idx_lock:
                    self._index_advance_locked()
                obs_metrics.inc("serve.submissions")
                return job_id
            seq += 1

    def admit(self, job_id: str) -> bool:
        """Publish the admit marker for a provisional record (first
        writer wins; a duplicate admit is a no-op)."""
        return publish_once(
            self._join(self.dir, f"admit.{job_id}.json"),
            json.dumps({
                "id": job_id,
                "wall": time.time(),
                "daemon": self.daemon_id,
            }, sort_keys=True).encode(),
        )

    def retract(self, job_id: str, reason: str) -> bool:
        """Park a provisional record as a rejected terminal result (the
        429 path of two-phase admission, and the limbo reaper's verdict
        for a submitter that died between the two phases)."""
        published = publish_once(
            self._join(self.dir, f"result.{job_id}.json"),
            json.dumps({
                "id": job_id,
                "ok": False,
                "rejected": True,
                "error": reason,
                "gen": -1,
                "pid": os.getpid(),
                "daemon": self.daemon_id,
                "finished_wall": time.time(),
            }, sort_keys=True).encode(),
        )
        if published:
            self._index_discard(job_id)
        return published

    def _admitted(self, jid: str, rec: Optional[dict],
                  admits: set) -> bool:
        if rec is None:
            return False
        return rec.get("admitted", True) is not False or jid in admits

    def _reap_limbo(self, jid: str, rec: dict, now: float) -> bool:
        """Retract a provisional record whose submitter will never admit
        it: the submitting daemon is fleet-dead, or the record has
        outlived the stale window with neither marker nor result.  Until
        reaped it (conservatively) occupies admission headroom."""
        dead = self._owner_dead(rec.get("daemon"))
        try:
            age = max(0.0, now - float(rec.get("submit_wall", now)))
        except (TypeError, ValueError):
            age = 0.0
        if not dead and age <= self.stale_after_s:
            return False
        return self.retract(
            jid, "admission abandoned: submitter died between publishing "
                 "the record and the admit marker"
        )

    # -- claiming ------------------------------------------------------------

    def pending(self) -> List[dict]:
        """Admitted, unfinished jobs with no live (or in-backoff) lease,
        in claim order (-priority, seq)."""
        jobs, admits, leases, results = self._scan()
        now = self._now()
        out = []
        for jid in jobs:
            if jid in results:
                continue
            rec = self._record(jid)
            if rec is None or not self._admitted(jid, rec, admits):
                continue
            if jid in leases:
                state, _ = self._lease_state(
                    leases[jid][1], leases[jid][0], now
                )
                if state != "expired":
                    continue
            out.append(rec)
        out.sort(key=lambda r: (-int(r.get("priority", 0)), int(r["seq"])))
        return out

    def stats(self, before_seq: Optional[int] = None) -> Dict[str, Any]:
        """Queue accounting for admission + gauges: per-tenant and total
        unfinished (queued + running) job counts.  With ``before_seq``,
        only jobs submitted earlier in the dense sequence count — the
        fleet-admission recount: every submitter judges its own record
        against the same prefix order, so k daemons admitting
        concurrently cannot jointly overshoot a limit.  Provisional
        records count until admitted or retracted (conservative: they
        can under-admit briefly, never overshoot).

        Served from the dense-seq index (ctt-events): record discovery is
        an exact forward probe from the frontier, result/lease state
        refreshes under :data:`STATS_TTL_S` — so the per-submit recount is
        O(unfinished jobs), not an O(history) dir scan, and staleness can
        only over-count in_flight (under-admit), never overshoot."""
        with self._idx_lock:
            self._index_advance_locked()
            self._index_classify_locked(obs_trace.monotonic())
            per_tenant: Dict[str, int] = {}
            queued = running = 0
            for info in self._idx_unfinished.values():
                if before_seq is not None and info["seq"] >= before_seq:
                    continue
                tenant = info["tenant"]
                per_tenant[tenant] = per_tenant.get(tenant, 0) + 1
                if info["running"]:
                    running += 1
                else:
                    queued += 1
            return {
                "queued": queued,
                "running": running,
                "in_flight": queued + running,
                "per_tenant": per_tenant,
                "total_jobs": self._idx_max_seq,
            }

    def _lease_payload(self, job_id: str, gen: int,
                       claim_wall: float, released: bool = False,
                       dispatch_wall: Optional[float] = None) -> bytes:
        # the daemon id rides the very first (claim-time) stamp, not just
        # renewals: a daemon SIGKILLed inside the claim-to-first-renewal
        # window still leaves a lease peers can fast-path expire
        payload = {
            "job": job_id,
            "gen": gen,
            "owner_pid": os.getpid(),
            "daemon": self.daemon_id,
            "claim_wall": claim_wall,
            "wall": time.time(),
            "mono": obs_trace.monotonic(),
        }
        if dispatch_wall is not None:
            # ctt-slo phase wall: when this generation's execution began
            # (after any microbatch aggregation window) — rides every
            # later renewal so the stamp survives to the post-mortem
            payload["dispatch_wall"] = dispatch_wall
        if released:
            # voluntary give-back: wall=0 ages the lease past every
            # staleness and backoff window, so it classifies "expired"
            # the moment any daemon looks at it
            payload.update({"released": True, "wall": 0.0, "mono": 0.0})
        return json.dumps(payload).encode()

    def _released_gens(self, jid: str, gens: int) -> int:
        """Generations of ``jid`` that ended in a voluntary release
        rather than a death.  A released lease is a clean hand-back (a
        drain-suspended ingest stream, not a crash), so it does not
        count against the poison-job retry budget."""
        released = 0
        for g in range(gens):
            lease = self._read_json(
                self._join(self.dir, f"lease.{jid}.g{g}.json")
            )
            if lease is not None and lease.get("released"):
                released += 1
        return released

    def _quarantine(self, jid: str, gens: int, rec: dict) -> None:
        """Park a job that exhausted its retry budget: first-writer-wins
        failed result carrying every burned generation's last lease
        stamp, so the post-mortem (which daemons died on it, when) is in
        one durable record."""
        failure_log = []
        for g in range(gens):
            lease = self._read_json(
                self._join(self.dir, f"lease.{jid}.g{g}.json")
            )
            failure_log.append(lease or {"gen": g, "torn": True})
        published = publish_once(
            self._join(self.dir, f"result.{jid}.json"),
            json.dumps({
                "id": jid,
                "ok": False,
                "quarantined": True,
                "error": (
                    f"retry budget exhausted: {gens} generation(s) claimed "
                    "this job and none published a result (poison job)"
                ),
                "failure_log": failure_log,
                "gen": gens,
                "pid": os.getpid(),
                "daemon": self.daemon_id,
                "tenant": rec.get("tenant"),
                "finished_wall": time.time(),
            }, sort_keys=True).encode(),
        )
        if published:
            obs_metrics.inc("serve.jobs_quarantined")

    def _candidates(self) -> List[Tuple[dict, int, bool]]:
        """Claimable jobs in claim order: ``(record, next_gen,
        fleet_reclaim)`` triples.  Enumerates once (one dir scan) for
        both the single-claim path and the ctt-microbatch multi-claim;
        limbo records encountered along the way are reaped here."""
        jobs, admits, leases, results = self._scan()
        now = self._now()
        candidates: List[Tuple[dict, int, bool]] = []
        for jid in jobs:
            if jid in results:
                continue
            rec = self._record(jid)
            if rec is None:
                continue
            if not self._admitted(jid, rec, admits):
                # win or lose, the job is terminal either way: retract()
                # inside the reaper already branches on the publish race
                self._reap_limbo(jid, rec, now)  # ctt: noqa[CTT203] terminal both ways
                continue
            gen, reclaim = 0, False
            if jid in leases:
                state, dead = self._lease_state(
                    leases[jid][1], leases[jid][0], now
                )
                if state != "expired":
                    continue
                gen, reclaim = leases[jid][0] + 1, dead
            candidates.append((rec, gen, reclaim))
        candidates.sort(
            key=lambda c: (-int(c[0].get("priority", 0)), int(c[0]["seq"]))
        )
        return candidates

    def _claim_candidate(self, rec: dict, gen: int,
                         reclaim: bool) -> Optional[JobClaim]:
        """Attempt one exclusive lease on a candidate.  None means either
        the retry budget parked the job (quarantine) or the publish_once
        raced away to a peer — the caller moves on either way."""
        jid = rec["id"]
        if (self.max_job_gens > 0
                and gen - self._released_gens(jid, gen)
                >= self.max_job_gens):
            self._quarantine(jid, gen, rec)
            return None
        claim_wall = time.time()
        path = self._join(self.dir, f"lease.{jid}.g{gen}.json")
        if publish_once(path, self._lease_payload(jid, gen, claim_wall)):
            if gen > 0:
                obs_metrics.inc("serve.leases_requeued")
                if reclaim:
                    # fleet fast path: recovered from a heartbeat-
                    # proven dead peer, not mere lease staleness
                    obs_metrics.inc("serve.jobs_reclaimed")
            return JobClaim(
                job_id=jid, record=rec, gen=gen, lease_path=path,
                claim_wall=claim_wall,
            )
        return None

    def claim_next(self) -> Optional[JobClaim]:
        """Lease the highest-priority claimable job: unleased first; a
        job whose lease went stale — or whose owner's fleet heartbeat
        proves it dead (the fast path) — requeues at gen+1.  A job whose
        *burned* generations (claims that died, not voluntary releases)
        would reach ``max_job_gens`` is quarantined instead of claimed;
        daemons never crash on a poison job, the job parks."""
        for rec, gen, reclaim in self._candidates():
            claim = self._claim_candidate(rec, gen, reclaim)
            if claim is not None:
                return claim
        return None

    def claim_batch(self, predicate, max_n: int) -> List[JobClaim]:
        """ctt-microbatch multi-claim: lease up to ``max_n`` claimable
        jobs for which ``predicate(record, next_gen)`` holds, in claim
        order (-priority, seq), over ONE directory scan.  Every member
        gets its own ordinary ``publish_once`` lease — exactly the
        single-claim artifact, so exactly-once execution, peer failover,
        renewal, and quarantine accounting are untouched; the *batch* is
        purely the caller's in-memory grouping and never exists on disk."""
        claims: List[JobClaim] = []
        if max_n <= 0:
            return claims
        for rec, gen, reclaim in self._candidates():
            if len(claims) >= max_n:
                break
            try:
                if not predicate(rec, gen):
                    continue
            except Exception:
                continue
            claim = self._claim_candidate(rec, gen, reclaim)
            if claim is not None:
                claims.append(claim)
        return claims

    def count_matching(self, predicate) -> int:
        """Lease-free count of claimable jobs matching
        ``predicate(record, next_gen)`` — the aggregation window's
        early-fill probe (close the window as soon as enough batchmates
        are queued instead of sleeping out the deadline)."""
        n = 0
        for rec, gen, _ in self._candidates():
            try:
                if predicate(rec, gen):
                    n += 1
            except Exception:
                continue
        return n

    def renew(self, claim: JobClaim) -> None:
        self._backend.write_bytes(
            claim.lease_path,
            self._lease_payload(
                claim.job_id, claim.gen, claim.claim_wall,
                dispatch_wall=claim.dispatch_wall,
            ),
        )

    def note_dispatch(self, claim: JobClaim) -> None:
        """ctt-slo: stamp the moment this generation's execution actually
        starts (after any aggregation window) into the lease — the
        ``dispatch_wall`` phase wall ``obs journey`` reads back from
        disk.  Also re-stamps the lease (a free renewal)."""
        claim.dispatch_wall = time.time()
        try:
            self.renew(claim)
        except OSError:
            # best-effort, the renewal convention: the wall still rides
            # the claim in memory and lands in the result record
            pass

    def admit_wall(self, job_id: str) -> Optional[float]:
        """Wall stamp of the fleet admit marker (None when absent/torn) —
        the admission→claim boundary of the phase breakdown."""
        rec = self._read_json(self._join(self.dir, f"admit.{job_id}.json"))
        if rec is None:
            return None
        try:
            return float(rec["wall"])
        except (KeyError, TypeError, ValueError):
            return None

    def release(self, claim: JobClaim) -> None:
        """Voluntarily hand a claimed job back (drain suspend of a
        long-lived ingest stream).  The lease is re-stamped with
        ``released: true`` and ``wall: 0`` — it classifies "expired"
        immediately, skipping both the staleness window and the requeue
        backoff, so any peer (or this daemon, post-drain) claims gen+1
        at once and resumes from the persisted carry.  Released
        generations are excluded from the quarantine budget."""
        self._backend.write_bytes(
            claim.lease_path,
            self._lease_payload(
                claim.job_id, claim.gen, claim.claim_wall, released=True
            ),
        )

    def complete(self, claim: JobClaim, result: Dict[str, Any]) -> bool:
        """Publish the terminal record (first writer wins — a requeued
        duplicate of a slow-but-alive predecessor loses cleanly)."""
        rec = dict(result)
        wall = time.time()
        rec.update({
            "id": claim.job_id,
            "gen": claim.gen,
            "pid": os.getpid(),
            "daemon": self.daemon_id,
            "finished_wall": wall,
            # ctt-slo phase walls: the winning generation's claim /
            # execution-start / publish stamps ride the terminal record,
            # so the per-job phase breakdown reconstructs from it alone
            # even after the leases are gone
            "claimed_wall": claim.claim_wall,
            "published_wall": wall,
        })
        if claim.dispatch_wall is not None:
            rec["dispatch_wall"] = claim.dispatch_wall
        published = publish_once(
            self._join(self.dir, f"result.{claim.job_id}.json"),
            json.dumps(rec, sort_keys=True).encode(),
        )
        if published:
            self._index_discard(claim.job_id)
        return published

    # -- read-side -----------------------------------------------------------

    def get(self, job_id: str) -> Optional[Dict[str, Any]]:
        """Full job state: record + derived state + result (if any)."""
        rec = self._record(job_id)
        if rec is None:
            return None
        result = self._read_json(
            self._join(self.dir, f"result.{job_id}.json")
        )
        if result is not None:
            state = "done" if result.get("ok") else "failed"
        else:
            _, _, leases, _ = self._scan()
            now = self._now()
            if job_id in leases and self._lease_state(
                leases[job_id][1], leases[job_id][0], now
            )[0] == "live":
                state = "running"
            else:
                state = "queued"
        return {"id": job_id, "state": state, "record": rec,
                "result": result}

    def list(self) -> List[Dict[str, Any]]:
        jobs, _, _, _ = self._scan()
        out = []
        for jid in jobs:
            st = self.get(jid)
            if st is not None:
                out.append({
                    "id": jid, "state": st["state"],
                    "tenant": st["record"].get("tenant"),
                    "priority": st["record"].get("priority", 0),
                    "workflow": st["record"].get("workflow"),
                })
        return out
