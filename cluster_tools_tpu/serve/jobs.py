"""ctt-serve durable job queue: the ctt-steal lease idiom at job grain.

``runtime/queue.py`` arbitrates *block batches* inside one dispatch with
an immutable manifest; the daemon needs the same guarantees for *jobs*
that arrive over time — so this module reuses the exact primitives
(``publish_once`` exclusive links, atomically re-stamped leases, the
``STALE_INTERVALS`` staleness rule, first-writer-wins results) over a
growing directory instead of a fixed manifest:

    <state_dir>/jobs/
      job.<id>.json          the submission record (published exactly once)
      lease.<id>.g<g>.json   generation-g execution ownership, re-stamped
                             every ``lease_s`` by the running daemon; a
                             stamp older than 3 x lease_s means the owner
                             died mid-job — the next daemon on the same
                             state dir claims gen g+1 (requeue)
      result.<id>.json       terminal record, first writer wins

Everything a client submitted is therefore durable: daemon death loses
nothing (queued jobs sit untouched, a leased job's stale lease requeues),
and a SIGTERM drain only has to finish in-flight work — the disk is the
queue.  Claim order is (-priority, submission sequence): priorities are
literally claim order, as the lease substrate makes natural.
"""

from __future__ import annotations

import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..obs import heartbeat as obs_heartbeat
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..runtime.queue import STALE_INTERVALS, publish_once
from ..utils.store import atomic_write_bytes

__all__ = ["JobClaim", "JobQueue"]

_JOB_RE = re.compile(r"^job\.(j\d{6})\.json$")
_LEASE_RE = re.compile(r"^lease\.(j\d{6})\.g(\d+)\.json$")
_RESULT_RE = re.compile(r"^result\.(j\d{6})\.json$")


@dataclass
class JobClaim:
    """One leased job: the record plus the lease that owns it."""

    job_id: str
    record: Dict[str, Any]
    gen: int
    lease_path: str
    claim_wall: float = field(default_factory=time.time)


class JobQueue:
    def __init__(self, root: str, lease_s: Optional[float] = None):
        os.makedirs(root, exist_ok=True)
        self.dir = root
        try:
            self.lease_s = float(lease_s) if lease_s else 0.0
        except (TypeError, ValueError):
            self.lease_s = 0.0
        if self.lease_s <= 0:
            self.lease_s = obs_heartbeat.interval_s()
        self.stale_after_s = STALE_INTERVALS * self.lease_s

    # -- directory scan ------------------------------------------------------

    def _scan(self):
        """(jobs, leases, results): job ids present, highest-generation
        lease path per job, and terminal-record presence."""
        jobs: List[str] = []
        leases: Dict[str, tuple] = {}
        results: set = set()
        try:
            names = os.listdir(self.dir)
        except OSError:
            names = []
        for name in names:
            m = _JOB_RE.match(name)
            if m:
                jobs.append(m.group(1))
                continue
            m = _RESULT_RE.match(name)
            if m:
                results.add(m.group(1))
                continue
            m = _LEASE_RE.match(name)
            if m:
                jid, g = m.group(1), int(m.group(2))
                cur = leases.get(jid)
                if cur is None or g > cur[0]:
                    leases[jid] = (g, os.path.join(self.dir, name))
        return sorted(jobs), leases, results

    def _read_json(self, path: str) -> Optional[dict]:
        try:
            with open(path) as f:
                rec = json.load(f)
            return rec if isinstance(rec, dict) else None
        except (OSError, json.JSONDecodeError):
            return None

    def _record(self, job_id: str) -> Optional[dict]:
        return self._read_json(os.path.join(self.dir, f"job.{job_id}.json"))

    def _lease_age_s(self, path: str, now: float) -> float:
        rec = self._read_json(path)
        stamp = None
        if rec is not None:
            try:
                stamp = float(rec["wall"])
            except (KeyError, TypeError, ValueError):
                stamp = None
        if stamp is None:
            # torn lease: age from mtime, the runtime/queue.py convention
            try:
                stamp = os.path.getmtime(path)
            except OSError:
                return 0.0
        return max(0.0, now - stamp)

    # -- submission ----------------------------------------------------------

    def submit(self, record: Dict[str, Any]) -> str:
        """Durably publish one job; returns its id.  Ids are a dense
        sequence (claim order ties break on it), allocated by probing the
        next free slot with the exclusive link — concurrent submitters
        cannot collide."""
        jobs, _, _ = self._scan()
        seq = (int(jobs[-1][1:]) + 1) if jobs else 1
        while True:
            job_id = f"j{seq:06d}"
            rec = dict(record)
            rec.update({"id": job_id, "seq": seq, "submit_wall": time.time()})
            if publish_once(
                os.path.join(self.dir, f"job.{job_id}.json"),
                json.dumps(rec, sort_keys=True).encode(),
            ):
                obs_metrics.inc("serve.submissions")
                return job_id
            seq += 1

    # -- claiming ------------------------------------------------------------

    def pending(self) -> List[dict]:
        """Unfinished jobs with no live lease, in claim order
        (-priority, seq)."""
        jobs, leases, results = self._scan()
        now = time.time()
        out = []
        for jid in jobs:
            if jid in results:
                continue
            if jid in leases and (
                self._lease_age_s(leases[jid][1], now) <= self.stale_after_s
            ):
                continue
            rec = self._record(jid)
            if rec is not None:
                out.append(rec)
        out.sort(key=lambda r: (-int(r.get("priority", 0)), int(r["seq"])))
        return out

    def stats(self) -> Dict[str, Any]:
        """Queue accounting for admission + gauges: per-tenant and total
        unfinished (queued + running) job counts."""
        jobs, leases, results = self._scan()
        now = time.time()
        per_tenant: Dict[str, int] = {}
        queued = running = 0
        for jid in jobs:
            if jid in results:
                continue
            rec = self._record(jid) or {}
            tenant = rec.get("tenant", "default")
            per_tenant[tenant] = per_tenant.get(tenant, 0) + 1
            if jid in leases and (
                self._lease_age_s(leases[jid][1], now) <= self.stale_after_s
            ):
                running += 1
            else:
                queued += 1
        return {
            "queued": queued,
            "running": running,
            "in_flight": queued + running,
            "per_tenant": per_tenant,
            "total_jobs": len(jobs),
        }

    def _lease_payload(self, job_id: str, gen: int,
                       claim_wall: float) -> bytes:
        return json.dumps({
            "job": job_id,
            "gen": gen,
            "owner_pid": os.getpid(),
            "claim_wall": claim_wall,
            "wall": time.time(),
            "mono": obs_trace.monotonic(),
        }).encode()

    def claim_next(self) -> Optional[JobClaim]:
        """Lease the highest-priority claimable job: unleased first; a
        job whose lease went stale (a daemon died mid-job) requeues at
        gen+1 — restart recovery, the runtime/queue.py expiry rule."""
        _, leases, _ = self._scan()
        for rec in self.pending():
            jid = rec["id"]
            gen = 0
            if jid in leases:
                # stale lease (pending() already aged it): take over
                gen = leases[jid][0] + 1
            claim_wall = time.time()
            path = os.path.join(self.dir, f"lease.{jid}.g{gen}.json")
            if publish_once(path, self._lease_payload(jid, gen, claim_wall)):
                if gen > 0:
                    obs_metrics.inc("serve.leases_requeued")
                return JobClaim(
                    job_id=jid, record=rec, gen=gen, lease_path=path,
                    claim_wall=claim_wall,
                )
            # claim raced away; fall through to the next candidate
        return None

    def renew(self, claim: JobClaim) -> None:
        atomic_write_bytes(
            claim.lease_path,
            self._lease_payload(claim.job_id, claim.gen, claim.claim_wall),
        )

    def complete(self, claim: JobClaim, result: Dict[str, Any]) -> bool:
        """Publish the terminal record (first writer wins — a requeued
        duplicate of a slow-but-alive predecessor loses cleanly)."""
        rec = dict(result)
        rec.update({
            "id": claim.job_id,
            "gen": claim.gen,
            "pid": os.getpid(),
            "finished_wall": time.time(),
        })
        return publish_once(
            os.path.join(self.dir, f"result.{claim.job_id}.json"),
            json.dumps(rec, sort_keys=True).encode(),
        )

    # -- read-side -----------------------------------------------------------

    def get(self, job_id: str) -> Optional[Dict[str, Any]]:
        """Full job state: record + derived state + result (if any)."""
        rec = self._record(job_id)
        if rec is None:
            return None
        result = self._read_json(
            os.path.join(self.dir, f"result.{job_id}.json")
        )
        if result is not None:
            state = "done" if result.get("ok") else "failed"
        else:
            _, leases, _ = self._scan()
            now = time.time()
            if job_id in leases and (
                self._lease_age_s(leases[job_id][1], now)
                <= self.stale_after_s
            ):
                state = "running"
            else:
                state = "queued"
        return {"id": job_id, "state": state, "record": rec,
                "result": result}

    def list(self) -> List[Dict[str, Any]]:
        jobs, _, _ = self._scan()
        out = []
        for jid in jobs:
            st = self.get(jid)
            if st is not None:
                out.append({
                    "id": jid, "state": st["state"],
                    "tenant": st["record"].get("tenant"),
                    "priority": st["record"].get("priority", 0),
                    "workflow": st["record"].get("workflow"),
                })
        return out
