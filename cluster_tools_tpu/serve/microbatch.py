"""ctt-microbatch runner: many member jobs, ONE stacked device dispatch.

The ``stack_payloads``/``unstack_results`` contract (runtime/executor.py,
ctt-hbm) aggregates *block batches* of one job into one device program.
This module lifts the same contract one grain up: the serve daemon hands
it several already-claimed member jobs with the same
``protocol.microbatch_signature`` — each with its OWN task instance,
lease, and result record — and the runner executes their volume passes
as one stacked read → ONE dispatch → per-member writes:

  * :func:`plan_member` replays exactly the setup half of
    ``BlockTask._run_blocks_phase`` (config merge, blocking, block list,
    done-status probe) and declines anything the stacked path cannot own
    byte-identically — multi-host topology, empty block lists (e.g. the
    resegment table-only mode), partially-done resumes, tasks without
    the split protocol.  Declined members run the ordinary solo
    ``build()`` path in the daemon, so ineligibility is never a failure.
  * :func:`run_stacked` isolates faults at the member grain: prepare and
    read errors (including ``executor.block`` fault-site hits — the same
    per-block chaos seam the solo executors check) drop only that
    member; a failure of the stacked compute itself fails every member.
    Either way the daemon re-dispatches failed members individually
    (``serve.microbatch_splits``), so one poisoned job burns its own
    retry budget and its batchmates still publish ok results.

The batch never exists on disk: member status files, leases, and results
are the ordinary per-job artifacts, written per member — a peer daemon
observing the state dir mid-batch sees N independent leased jobs, and a
member failover behaves exactly like today's single-job failover.
"""

from __future__ import annotations

import json
import traceback
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from .. import faults
from ..obs import trace as obs_trace
from ..runtime import config as cfg
from ..runtime.executor import stacked_dispatch
from ..utils.blocking import Blocking

__all__ = ["MemberPlan", "plan_member", "stack_key", "run_stacked"]

# the split batch protocol + the stack contract — all five or solo
STACK_METHODS = (
    "read_batch", "compute_batch", "write_batch",
    "stack_payloads", "unstack_results",
)


@dataclass
class MemberPlan:
    """One member job's resolved volume pass: everything
    ``BlockTask._run_blocks_phase`` would have computed before its first
    dispatch, held so the stacked runner can read/write per member while
    dispatching once."""

    task: Any
    blocking: Blocking
    config: Dict[str, Any]
    block_ids: List[int]
    error: Optional[str] = None
    seconds: float = 0.0


def plan_member(task) -> Optional[MemberPlan]:
    """Resolve one member task's dispatch plan, or None when the stacked
    path must not own it (the solo ``build()`` path runs it instead)."""
    gconf = task.global_config()
    _, num = cfg.process_topology(gconf)
    if num > 1:
        # multi-host barrier protocol: per-process shards + peer waits —
        # strictly the solo lifecycle's business
        return None
    for name in STACK_METHODS:
        if getattr(task, name, None) is None:
            return None
    tconf = task.get_task_config()
    config = {**gconf, **tconf}
    blocking = Blocking(tuple(task.get_shape()), task.get_block_shape(gconf))
    block_ids = task.get_block_list(blocking, gconf)
    if not block_ids:
        # nothing to stack (e.g. resegment write_volume: false runs its
        # whole job in prepare/finalize)
        return None
    if task.output().read().get("done"):
        # partial progress from a prior generation: the resumable solo
        # path owns done-set arithmetic and retries
        return None
    return MemberPlan(
        task=task, blocking=blocking, config=config, block_ids=block_ids,
    )


def stack_key(plan: MemberPlan) -> Tuple:
    """Members stack only when one device program serves them all: same
    task class, same block geometry, and the same merged runtime config
    (a member whose config_dir carried stray pre-existing keys falls out
    into its own group and runs solo — never silently mis-stacked)."""
    try:
        conf = json.dumps(plan.config, sort_keys=True, default=repr)
    except (TypeError, ValueError):
        conf = repr(sorted(plan.config))
    return (
        type(plan.task).__name__,
        tuple(plan.blocking.block_shape),
        getattr(plan.task, "hierarchy_path", None),
        conf,
    )


def run_stacked(
    plans: List[MemberPlan],
) -> Tuple[List[MemberPlan], List[MemberPlan]]:
    """Execute member plans as one stacked dispatch; returns
    ``(ok, failed)`` plans (failed carry ``plan.error``).  Per-member
    prepare/read/write failures isolate to that member; a stacked
    compute failure fails all — the caller re-dispatches failed members
    individually either way."""
    payloads, survivors, failed = [], [], []
    for plan in plans:
        t0 = obs_trace.monotonic()
        try:
            plan.task.prepare(plan.blocking, plan.config)
            for bid in plan.block_ids:
                # the solo executors' per-block chaos seam, checked at
                # the member grain: a fail/kill fault aimed at a block id
                # only this member owns fires here — before its payload
                # can join the stack
                faults.check("executor.block", id=bid)
            with obs_trace.span(
                "stage_read", kind="host_io", task=plan.task.identifier,
                blocks=len(plan.block_ids), block_ids=list(plan.block_ids),
            ):
                payloads.append(plan.task.read_batch(
                    plan.block_ids, plan.blocking, plan.config
                ))
        except Exception:
            plan.error = traceback.format_exc()
            failed.append(plan)
            continue
        plan.seconds += obs_trace.monotonic() - t0
        survivors.append(plan)
    if not survivors:
        return [], failed

    leader = survivors[0]
    counts = [len(p.block_ids) for p in survivors]
    all_ids = [b for p in survivors for b in p.block_ids]
    t0 = obs_trace.monotonic()
    try:
        payload = (
            leader.task.stack_payloads(payloads, leader.blocking,
                                       leader.config)
            if len(survivors) > 1 else payloads[0]
        )
        result = stacked_dispatch(
            leader.task, leader.task.compute_batch, payload,
            leader.blocking, leader.config, all_ids,
            fused=len(survivors) > 1,
        )
        results = (
            leader.task.unstack_results(result, counts, leader.blocking,
                                        leader.config)
            if len(survivors) > 1 else [result]
        )
    except Exception:
        tb = traceback.format_exc()
        for plan in survivors:
            plan.error = tb
        return [], failed + survivors
    compute_share = (obs_trace.monotonic() - t0) / len(survivors)

    ok = []
    for plan, res in zip(survivors, results):
        t0 = obs_trace.monotonic()
        try:
            with obs_trace.span(
                "stage_write", kind="host_io", task=plan.task.identifier,
                blocks=len(plan.block_ids),
                block_ids=list(plan.block_ids),
            ):
                plan.task.write_batch(res, plan.blocking, plan.config)
            plan.task.finalize(plan.blocking, plan.config, plan.block_ids)
            plan.seconds += compute_share + (obs_trace.monotonic() - t0)
            # the member's ordinary completion record: same schema as the
            # solo lifecycle's, so resumes/status readers can't tell a
            # batched member from a solo run
            plan.task._write_status(
                plan.task.output(), plan.block_ids, set(plan.block_ids),
                [], [plan.seconds], True,
            )
        except Exception:
            plan.error = traceback.format_exc()
            failed.append(plan)
            continue
        ok.append(plan)
    return ok, failed
