"""CLI: ``python -m cluster_tools_tpu.serve`` — run the serving daemon.

    python -m cluster_tools_tpu.serve --state-dir DIR [--port P]
        [--host H] [--concurrency N] [--max-queue-depth N]
        [--tenant-quota N] [--lease-s S] [--drain-timeout-s S]
        [--max-job-gens N] [--daemon-id ID]
        [--microbatch-window-s S] [--microbatch-max-jobs N]

The daemon binds loopback (ephemeral port by default), publishes its
endpoint to ``<state_dir>/serve.json``, and serves until SIGTERM/SIGINT,
which triggers a drain: in-flight jobs finish, queued jobs stay durable
in ``<state_dir>/jobs/`` for the next daemon over the same state dir.
Run SEVERAL against one state dir for a fault-tolerant fleet (ctt-fleet):
they share the queue, enforce admission limits jointly, and fail over a
dead peer's jobs within one heartbeat staleness window.  Flags override
``<state_dir>/serve.config`` which overrides
``runtime.config.DEFAULT_SERVE_CONFIG``.

The state dir may be an **object-store prefix** (``http(s)://`` or
``s3://``, ctt-diskless): every shared-state file — queue records,
leases, beats, endpoint, config — then rides signed store requests and
the daemon holds zero POSIX shared state.  To autoscale such a fleet,
run ``python -m cluster_tools_tpu.serve.supervisor`` over the same
prefix: it acts on :func:`serve.fleet.scale_advice`, spawning and
draining daemons between a floor and a ceiling.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m cluster_tools_tpu.serve",
        description="ctt-serve: persistent workflow serving daemon "
        "(warm mesh/compile/chunk caches across submissions)",
    )
    parser.add_argument("--state-dir", required=True,
                        help="endpoint record, job queue, and default "
                        "trace dir")
    parser.add_argument("--host", default=None)
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--concurrency", type=int, default=None)
    parser.add_argument("--max-queue-depth", type=int, default=None)
    parser.add_argument("--tenant-quota", type=int, default=None)
    parser.add_argument("--lease-s", type=float, default=None)
    parser.add_argument("--drain-timeout-s", type=float, default=None)
    parser.add_argument("--max-job-gens", type=int, default=None,
                        help="per-job retry budget: lease generations "
                        "before quarantine (<= 0 = unbounded)")
    parser.add_argument("--daemon-id", default=None,
                        help="fleet identity (default <host>-<pid>-<n>)")
    parser.add_argument("--microbatch-window-s", type=float, default=None,
                        help="cross-tenant aggregation window: hold a "
                        "claimed job this long to coalesce same-signature "
                        "queued jobs into one stacked dispatch (0 = "
                        "per-job dispatch)")
    parser.add_argument("--microbatch-max-jobs", type=int, default=None,
                        help="most member jobs per stacked dispatch")
    args = parser.parse_args(argv)

    from .server import ServeDaemon

    daemon = ServeDaemon(args.state_dir, config={
        "host": args.host,
        "port": args.port,
        "concurrency": args.concurrency,
        "max_queue_depth": args.max_queue_depth,
        "tenant_quota": args.tenant_quota,
        "lease_s": args.lease_s,
        "drain_timeout_s": args.drain_timeout_s,
        "max_job_gens": args.max_job_gens,
        "daemon_id": args.daemon_id,
        "microbatch_window_s": args.microbatch_window_s,
        "microbatch_max_jobs": args.microbatch_max_jobs,
    })
    daemon.install_signal_handlers()
    endpoint = daemon.start()
    print(f"[serve] listening on http://{endpoint['host']}:"
          f"{endpoint['port']} (state dir {args.state_dir})", flush=True)
    print(json.dumps(endpoint, sort_keys=True), flush=True)
    return daemon.run()


if __name__ == "__main__":
    sys.exit(main())
