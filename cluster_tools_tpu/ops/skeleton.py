"""Object skeletonization: TEASAR-style geodesic path skeletons.

Replaces elf.skeleton (reference skeletons/skeletonize.py:157-163, thinning /
teasar via skeletor).  The algorithm here is the TEASAR family (Sato et al.):

  1. root = the object voxel with maximal Euclidean DT (deepest interior);
  2. geodesic BFS distance field from the root over the 26-connected object;
  3. repeatedly: take the unvisited voxel farthest (geodesically) from the
     root, backtrace its shortest path to the already-extracted skeleton,
     append the path, and mark every voxel within ``mask_scale * DT`` of the
     new path as visited;
  4. stop when all object voxels are covered.

Output is a skeleton *graph*: node coordinates [n, 3] (voxel units) and edges
[m, 2] into the node list — the same (nodes, edges) contract as elf.skeleton.

The per-object work is a sparse graph traversal over ragged data — host numpy
(scipy BFS), like the reference's; the dense DT it consumes comes from the
device kernel (ops/dt.py).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def _geodesic_field(obj: np.ndarray, root_flat: int):
    """BFS distances + predecessors from root over the 26-connected mask."""
    from collections import deque

    shape = obj.shape
    flat = obj.reshape(-1)
    dist = np.full(flat.size, -1, dtype=np.int64)
    pred = np.full(flat.size, -1, dtype=np.int64)
    strides = []
    for dz in (-1, 0, 1):
        for dy in (-1, 0, 1):
            for dx in (-1, 0, 1):
                if dz == dy == dx == 0:
                    continue
                strides.append((dz, dy, dx))

    coords = np.unravel_index(np.arange(flat.size), shape)
    dist[root_flat] = 0
    frontier = np.array([root_flat], dtype=np.int64)
    while frontier.size:
        z = coords[0][frontier]
        y = coords[1][frontier]
        x = coords[2][frontier]
        nxt = []
        for dz, dy, dx in strides:
            nz, ny, nx_ = z + dz, y + dy, x + dx
            ok = (
                (nz >= 0) & (nz < shape[0])
                & (ny >= 0) & (ny < shape[1])
                & (nx_ >= 0) & (nx_ < shape[2])
            )
            nb = (nz[ok] * shape[1] + ny[ok]) * shape[2] + nx_[ok]
            src = frontier[ok]
            fresh = flat[nb] & (dist[nb] < 0)
            nb, src = nb[fresh], src[fresh]
            # dedupe within the wave (first writer wins)
            uniq, first = np.unique(nb, return_index=True)
            dist[uniq] = dist[src[first]] + 1
            pred[uniq] = src[first]
            nxt.append(uniq)
        frontier = np.unique(np.concatenate(nxt)) if nxt else np.array([], np.int64)
    return dist, pred


def skeletonize(
    obj: np.ndarray,
    resolution=None,
    mask_scale: float = 3.0,
    mask_min_radius: float = 2.0,
    max_paths: int = 512,
) -> Tuple[np.ndarray, np.ndarray]:
    """Skeletonize a binary object → (nodes [n,3] float voxel coords,
    edges [m,2] int node indices)."""
    obj = np.ascontiguousarray(obj.astype(bool))
    if obj.sum() == 0:
        return np.zeros((0, 3)), np.zeros((0, 2), dtype=np.int64)
    if obj.sum() == 1:
        node = np.argwhere(obj)[0]
        return node[None].astype(float), np.zeros((0, 2), dtype=np.int64)

    from .dt import distance_transform

    import jax.numpy as jnp

    dt = np.asarray(distance_transform(jnp.asarray(obj)))
    root_flat = int(np.argmax(dt.reshape(-1)))

    dist, pred = _geodesic_field(obj, root_flat)
    inside = np.nonzero(obj.reshape(-1))[0]
    shape = obj.shape

    covered = np.zeros(obj.size, dtype=bool)
    covered[~obj.reshape(-1)] = True

    node_index = {}  # flat voxel -> node id
    nodes = []
    edges = []

    def add_node(fl):
        nid = node_index.get(fl)
        if nid is None:
            nid = len(nodes)
            node_index[fl] = nid
            nodes.append(np.unravel_index(fl, shape))
        return nid

    on_skeleton = np.zeros(obj.size, dtype=bool)

    def cover_path(path_flat):
        """Mark voxels within mask_scale*DT of each path voxel as covered.
        Per-ball O(ball) coordinates — no full-volume meshgrid."""
        pz, py, px = np.unravel_index(np.asarray(path_flat), shape)
        radius = np.maximum(
            mask_scale * dt.reshape(-1)[np.asarray(path_flat)], mask_min_radius
        )
        for z, y, x, r in zip(pz, py, px, radius):
            ri = int(np.ceil(r))
            sl = (
                slice(max(0, z - ri), min(shape[0], z + ri + 1)),
                slice(max(0, y - ri), min(shape[1], y + ri + 1)),
                slice(max(0, x - ri), min(shape[2], x + ri + 1)),
            )
            bz = np.arange(sl[0].start, sl[0].stop)[:, None, None] - z
            by = np.arange(sl[1].start, sl[1].stop)[None, :, None] - y
            bx = np.arange(sl[2].start, sl[2].stop)[None, None, :] - x
            ball = (bz * bz + by * by + bx * bx) <= r * r
            view = covered.reshape(shape)[sl]
            view[ball] = True

    add_node(root_flat)
    covered_root = False
    for _ in range(max_paths):
        cand = inside[~covered[inside]]
        if cand.size == 0:
            break
        far = cand[np.argmax(dist[cand])]
        if dist[far] < 0:  # disconnected fragment (shouldn't happen per CC)
            covered[far] = True
            continue
        # backtrace to the existing skeleton (or the root)
        path = [int(far)]
        cur = int(far)
        while pred[cur] >= 0 and not on_skeleton[cur]:
            cur = int(pred[cur])
            path.append(cur)
        # register nodes + edges along the path
        prev_id = None
        for fl in path:
            nid = add_node(fl)
            if prev_id is not None:
                edges.append((prev_id, nid))
            prev_id = nid
        on_skeleton[np.asarray(path)] = True
        cover_path(path)
        if not covered_root:
            covered_root = True

    nodes = np.asarray(nodes, dtype=float)
    edges = (
        np.unique(np.sort(np.asarray(edges, dtype=np.int64), axis=1), axis=0)
        if edges
        else np.zeros((0, 2), dtype=np.int64)
    )
    if resolution is not None:
        nodes = nodes * np.asarray(resolution, dtype=float)[None]
    return nodes, edges
