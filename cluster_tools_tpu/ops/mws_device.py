"""Device (TPU) mutex watershed via mutually-best-edge parallel greedy.

The reference reaches MWS through affogato's sequential
Kruskal-with-mutex-constraints C++ (reference mutex_watershed/mws_blocks.py:11;
SURVEY.md §7 hard-parts #2).  The data-parallel formulation used here:

Under a strict total priority order (weight descending, ties by input index —
the host solver's stable sort, ops/mws.py::_mws_python), an edge ``e = (A, B)``
that is the highest-priority ACTIVE edge of BOTH its endpoint clusters can be
decided immediately, exactly as the sequential algorithm would decide it:
every higher-priority unprocessed edge is non-incident to A and B, and no
non-incident edge can change A/B's membership (a merge into A would be an
incident edge) or their mutex relation (a mutex between A and B needs an edge
incident to both).  Mutually-best edges form a matching on clusters (each
cluster has ONE best edge), so all of them apply in the same round:

  * attractive + not mutexed  → merge the two clusters;
  * attractive + mutexed      → discard (the sequential ``continue``);
  * repulsive                 → record the mutex, discard.

Progress: the globally highest active edge is always mutually best, so every
round processes ≥ 1 edge.  Repulsive edges additionally retire in BATCHES:
a repulsive edge stronger than one side's strongest active attractive edge
becomes a mutex immediately (that cluster's future merges are all weaker —
cluster picks decrease monotonically — so the early mutex can never wrongly
block a stronger attractive merge).  NOT the naive MSF shortcut — "maximum
spanning forest then cut repulsive edges" is WRONG for MWS (mutexes do not
propagate through chains of repulsive forest edges; a minimal counterexample
lives in tests/test_mws_device.py::test_msf_shortcut_would_be_wrong).

Round count is data-dependent: monotone attractive chains (spatially smooth
affinities) serialize — ~n_clusters-deep in the worst case.  The kernel is
exact and dispatch-efficient per round, but the host C++ solver remains the
production default for per-block solves; this is the TPU formulation for
chip-resident pipelines and a base for future chain-contraction work.

Mutex bookkeeping is implicit and shape-static: a processed repulsive edge IS
a mutex between the clusters of its endpoints — merges re-root its endpoints,
so inheritance (mutexes follow merged clusters) falls out of the ``comp``
lookup.  The per-round mutex membership test for candidate merges is a
sort-join over (min-comp, max-comp, tag) rows — O(m log m) segment-free work
per round, fully static shapes, no sequential edge loop.  Rounds are
data-dependent (while_loop); random-priority graphs converge in roughly
O(log n) rounds.

This is the TPU-native formulation; the per-block pipeline still defaults to
the host C++ (flip with CTT_MWS_MODE=device / force_mws_mode("device")).
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np


def _next_pow2(m: int) -> int:
    return 1 << max(int(m - 1).bit_length(), 4)


@partial(jax.jit, static_argnames=("n_nodes",))
def _mws_parallel_greedy(uv, weights, attractive, n_nodes: int):
    import jax.numpy as jnp
    from jax import lax

    m = uv.shape[0]
    u, v = uv[:, 0], uv[:, 1]
    idx = jnp.arange(m, dtype=jnp.int32)
    nodes = jnp.arange(n_nodes, dtype=jnp.int32)
    big = jnp.int32(m)

    def cond(state):
        comp, processed = state
        return (~processed & (comp[u] != comp[v])).any()

    def body(state):
        comp, processed = state
        cu, cv = comp[u], comp[v]
        processed = processed | (cu == cv)  # intra-cluster edges are no-ops
        # batched repulsive retirement: a repulsive edge stronger than one
        # side's strongest ACTIVE ATTRACTIVE edge can become a mutex NOW —
        # that cluster's future merges are all weaker (cluster picks are
        # monotonically decreasing), so the early mutex can never wrongly
        # block a stronger attractive merge.  Retires whole piles of
        # parallel repulsive edges per round instead of one per cluster.
        w_attr = jnp.where(~processed & attractive, weights, -jnp.inf)
        alpha = (
            jnp.full((n_nodes,), -jnp.inf, weights.dtype)
            .at[cu].max(w_attr)
            .at[cv].max(w_attr)
        )
        retire = (
            ~processed & ~attractive
            & ((weights > alpha[cu]) | (weights > alpha[cv]))
        )
        processed = processed | retire
        active = ~processed
        # per-cluster best active incident edge under the strict
        # (weight, -index) order: scatter-max weight, then scatter-min index
        # among weight-achievers
        w_act = jnp.where(active, weights, -jnp.inf)
        seg_w = (
            jnp.full((n_nodes,), -jnp.inf, weights.dtype)
            .at[cu].max(w_act)
            .at[cv].max(w_act)
        )
        cand_u = jnp.where(active & (w_act == seg_w[cu]), idx, big)
        cand_v = jnp.where(active & (w_act == seg_w[cv]), idx, big)
        best = (
            jnp.full((n_nodes,), big, jnp.int32)
            .at[cu].min(cand_u)
            .at[cv].min(cand_v)
        )
        mutual = active & (best[cu] == idx) & (best[cv] == idx)

        # mutex membership for the mutual attractive candidates: sort-join
        # of mutex rows (processed repulsive edges, keyed by their CURRENT
        # cluster pair — inheritance under merges for free) against query
        # rows.  Stale intra mutex rows key as (A, A) and can never match a
        # query's (A, B), A < B.
        a_key = jnp.minimum(cu, cv)
        b_key = jnp.maximum(cu, cv)
        is_mutex = processed & ~attractive
        is_query = mutual & attractive
        A2 = jnp.concatenate([a_key, a_key])
        B2 = jnp.concatenate([b_key, b_key])
        tag = jnp.concatenate(
            [
                jnp.where(is_mutex, jnp.int32(0), jnp.int32(2)),
                jnp.where(is_query, jnp.int32(1), jnp.int32(2)),
            ]
        )
        payload = jnp.concatenate([jnp.full((m,), big, jnp.int32), idx])
        sA, sB, sT, sP = lax.sort((A2, B2, tag, payload), num_keys=3)
        hit = (
            (sA[1:] == sA[:-1]) & (sB[1:] == sB[:-1])
            & (sT[:-1] == 0) & (sT[1:] == 1)
        )
        hit = jnp.concatenate([jnp.zeros((1,), bool), hit])
        mutexed = (
            jnp.zeros((m + 1,), jnp.int32)
            .at[jnp.where(sT == 1, sP, big)].max(hit.astype(jnp.int32))
        )[:m] > 0

        merge_e = mutual & attractive & ~mutexed
        # merged, mutex-blocked, and repulsive mutual edges are all decided
        processed = processed | mutual

        # apply the merge matching (each cluster in ≤ 1 mutual edge):
        # larger cluster id points to smaller — depth-1, no chains
        parent = jnp.concatenate([nodes, jnp.zeros((1,), jnp.int32)])
        src = jnp.where(merge_e, b_key, jnp.int32(n_nodes))
        parent = parent.at[src].set(jnp.where(merge_e, a_key, 0))
        comp = parent[comp]
        return comp, processed

    comp, _ = lax.while_loop(
        cond, body, (nodes, jnp.zeros((m,), dtype=bool))
    )
    return comp


def mutex_watershed_device(
    n_nodes: int,
    uv: np.ndarray,
    weights: np.ndarray,
    attractive: np.ndarray,
) -> np.ndarray:
    """Drop-in device counterpart of ``native.mutex_watershed`` /
    ``_mws_python``: root (canonical cluster id) per node.

    Edges are padded to the next power of two (self-loops at node 0, never
    active) so repeated solves of similar-size blocks reuse the jit cache.
    """
    if n_nodes >= np.iinfo(np.int32).max:
        raise ValueError("device MWS needs an int32-addressable node space")
    import jax.numpy as jnp

    m = int(uv.shape[0])
    mp = _next_pow2(max(m, 1))
    uv32 = np.zeros((mp, 2), dtype=np.int32)
    uv32[:m] = uv
    w = np.full(mp, -1.0, dtype=np.float32)
    w[:m] = weights
    at = np.zeros(mp, dtype=bool)
    at[:m] = np.asarray(attractive).astype(bool)
    labels = _mws_parallel_greedy(
        jnp.asarray(uv32), jnp.asarray(w), jnp.asarray(at), n_nodes=int(n_nodes)
    )
    return np.asarray(labels, dtype=np.int64)
