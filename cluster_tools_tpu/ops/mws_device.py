"""Device (TPU) mutex watershed via mutually-best-edge parallel greedy.

The reference reaches MWS through affogato's sequential
Kruskal-with-mutex-constraints C++ (reference mutex_watershed/mws_blocks.py:11;
SURVEY.md §7 hard-parts #2).  The data-parallel formulation used here:

Under a strict total priority order (weight descending, ties by input index —
the host solver's stable sort, ops/mws.py::_mws_python), an edge ``e = (A, B)``
that is the highest-priority ACTIVE edge of BOTH its endpoint clusters can be
decided immediately, exactly as the sequential algorithm would decide it:
every higher-priority unprocessed edge is non-incident to A and B, and no
non-incident edge can change A/B's membership (a merge into A would be an
incident edge) or their mutex relation (a mutex between A and B needs an edge
incident to both).  Mutually-best edges form a matching on clusters (each
cluster has ONE best edge), so all of them apply in the same round:

  * attractive + not mutexed  → merge the two clusters;
  * attractive + mutexed      → discard (the sequential ``continue``);
  * repulsive                 → record the mutex, discard.

Progress: the globally highest active edge is always mutually best, so every
round processes ≥ 1 edge.  Repulsive edges additionally retire in BATCHES:
a repulsive edge that PRECEDES one side's strongest active attractive edge
in the strict (weight desc, index asc) priority order becomes a mutex
immediately (that cluster's future merges all come later in the order —
cluster picks descend monotonically — so the early mutex can never wrongly
block a merge the sequential algorithm would have done first).  NOT the naive MSF shortcut — "maximum
spanning forest then cut repulsive edges" is WRONG for MWS (mutexes do not
propagate through chains of repulsive forest edges; a minimal counterexample
lives in tests/test_mws_device.py::test_msf_shortcut_would_be_wrong).

Chain contraction (log-depth rounds on smooth data): beyond the mutual
matching, a cluster X whose best active edge ``e = (X, Y)`` is attractive
merges along it in the SAME round — even when ``e`` is not Y's best —
provided X is *mutex-immune*: no repulsive edge incident to X's cluster
(active or already processed) is stronger than ``e``.  Sequential
justification: at ``e``'s turn in the priority order, X's cluster is
unchanged (every X-incident edge is weaker than ``e``), and a mutex
involving X would need a processed — hence stronger — X-incident repulsive
edge, which immunity rules out; early-retired mutexes recorded via the
OTHER side cannot key against (X, Ycl) either, because reaching Ycl would
need a best-of-cluster merge chain through an edge weaker than the retired
mutex while ``e`` (stronger) is still pending on Ycl — contradicting the
best-of-cluster rule.  Immunity is tested under the full lexicographic
(weight desc, index asc) order — scatter-max weight plus scatter-min index
among the achievers — so equal-weight repulsive neighbors that
sequentially come later do not revoke it.  The eligible edges form a
forest on clusters (each cluster
has one best edge; acyclic because the strict (weight, -index) order
descends along chains), applied with log-depth pointer jumping, so
monotone attractive chains — which previously serialized one merge per
round — contract in one round (measured: 1024-node chain, 1023 rounds -> 1).

Doomed-pair batch discard (the round-collapse rule for boundary-heavy
data): the mutex join queries EVERY active inter-cluster edge, and any
edge — either sign — whose current cluster pair already carries a mutex is
discarded immediately.  Correctness: mutexes persist and follow merges
(clusters only grow; the (min, max) cluster key re-roots with ``comp``),
so at that edge's sequential turn the mutex still exists — an attractive
edge would be skipped, a repulsive one would record a redundant mutex for
the same pair; neither has any other side effect.  The load-bearing
invariant behind "still exists at its sequential turn" is a WEIGHT BOUND,
not mere persistence: the mutex edge must PRECEDE the discarded edge in
the sequential (weight desc, index asc) order.  That holds because every
merge edge joining a cluster grown from the mutexed pair was mutual-best
at its round (or mutex-immune, which is strictly stronger), so along any
merge chain the joining weights are bounded by the mutex edge's weight —
hence every ACTIVE edge now incident to the mutexed cluster pair,
including the discarded one, is no heavier than the mutex edge and
sequentially comes after it.  Kernel edits that relax the mutual-best /
immunity admission (e.g. admitting locally-best-only merges) would break
this bound and with it the discard rule, even though mutex persistence
itself would still hold.  Without this rule the
near-boundary regime drained one mutexed mutual pair per round (measured
on the bench's bimodal affinity problems: 2k nodes/6.8k edges 1164 -> 33
rounds; 8k nodes/28k edges 3344 -> 70 rounds, 160 s -> 1.8 s warm on the
CPU fallback).  The join is the same 2m-row sort — the rule is free.

Mutex bookkeeping is implicit and shape-static: a processed repulsive edge IS
a mutex between the clusters of its endpoints — merges re-root its endpoints,
so inheritance (mutexes follow merged clusters) falls out of the ``comp``
lookup.  The per-round mutex membership test for candidate merges is a
sort-join over (min-comp, max-comp, tag) rows — O(m log m) segment-free work
per round, fully static shapes, no sequential edge loop.  Rounds are
data-dependent (while_loop); random-priority graphs converge in roughly
O(log n) rounds.

This is the TPU-native formulation; the per-block pipeline still defaults to
the host C++ (flip with CTT_MWS_MODE=device / force_mws_mode("device")).
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np


def _next_pow2(m: int) -> int:
    return 1 << max(int(m - 1).bit_length(), 4)


@partial(jax.jit, static_argnames=("n_nodes", "enable_chain"))
def _mws_parallel_greedy(uv, weights, attractive, n_nodes: int,
                         enable_chain: bool = True):
    import jax.numpy as jnp
    from jax import lax

    m = uv.shape[0]
    u, v = uv[:, 0], uv[:, 1]
    idx = jnp.arange(m, dtype=jnp.int32)
    nodes = jnp.arange(n_nodes, dtype=jnp.int32)
    big = jnp.int32(m)

    def cond(state):
        comp, processed, _ = state
        return (~processed & (comp[u] != comp[v])).any()

    def body(state):
        comp, processed, rounds = state
        cu, cv = comp[u], comp[v]
        processed = processed | (cu == cv)  # intra-cluster edges are no-ops
        # batched repulsive retirement: a repulsive edge that PRECEDES one
        # side's strongest active attractive edge in the strict
        # (weight desc, index asc) order can become a mutex NOW — that
        # cluster's future merges all come later in the order, so the early
        # mutex can never wrongly block a merge the sequential algorithm
        # would have done first.  Retires whole piles of parallel repulsive
        # edges per round instead of one per cluster.  The tie-break is
        # lexicographic (alpha weight scatter-max + index scatter-min among
        # achievers), so equal-weight attractive/repulsive interleavings
        # retire at full rate instead of one mutual pair per round.
        is_attr_act = ~processed & attractive
        w_attr = jnp.where(is_attr_act, weights, -jnp.inf)
        alpha = (
            jnp.full((n_nodes,), -jnp.inf, weights.dtype)
            .at[cu].max(w_attr)
            .at[cv].max(w_attr)
        )
        alpha_i = (
            jnp.full((n_nodes,), big, jnp.int32)
            .at[cu].min(
                jnp.where(is_attr_act & (weights == alpha[cu]), idx, big))
            .at[cv].min(
                jnp.where(is_attr_act & (weights == alpha[cv]), idx, big))
        )

        def _precedes(side):
            a_w, a_i = alpha[side], alpha_i[side]
            return (weights > a_w) | ((weights == a_w) & (idx < a_i))

        retire = (
            ~processed & ~attractive & (_precedes(cu) | _precedes(cv))
        )
        processed = processed | retire
        active = ~processed
        # per-cluster best active incident edge under the strict
        # (weight, -index) order: scatter-max weight, then scatter-min index
        # among weight-achievers
        w_act = jnp.where(active, weights, -jnp.inf)
        seg_w = (
            jnp.full((n_nodes,), -jnp.inf, weights.dtype)
            .at[cu].max(w_act)
            .at[cv].max(w_act)
        )
        cand_u = jnp.where(active & (w_act == seg_w[cu]), idx, big)
        cand_v = jnp.where(active & (w_act == seg_w[cv]), idx, big)
        best = (
            jnp.full((n_nodes,), big, jnp.int32)
            .at[cu].min(cand_u)
            .at[cv].min(cand_v)
        )
        mutual = active & (best[cu] == idx) & (best[cv] == idx)

        # mutex membership for the mutual attractive candidates: sort-join
        # of mutex rows (processed repulsive edges, keyed by their CURRENT
        # cluster pair — inheritance under merges for free) against query
        # rows.  Stale intra mutex rows key as (A, A) and can never match a
        # query's (A, B), A < B.
        a_key = jnp.minimum(cu, cv)
        b_key = jnp.maximum(cu, cv)
        is_mutex = processed & ~attractive
        # query EVERY active inter-cluster edge, not just best-edge
        # candidates: any active edge whose current cluster pair already
        # has a recorded mutex is DOOMED — mutexes persist and follow
        # merges (clusters only grow; the pair key re-roots with comp), so
        # at that edge's sequential turn the mutex still blocks it
        # (attractive: skipped; repulsive: records a redundant mutex for
        # the same pair).  Discarding them all per round collapses the
        # drain-one-mutual-discard-per-round tail: measured on the bench's
        # bimodal 8x16x16 affinity problem (2048 nodes, 6784 edges),
        # 1164 rounds -> 33.  Same join size (2m rows) — no extra cost.
        is_query = active & (cu != cv)
        A2 = jnp.concatenate([a_key, a_key])
        B2 = jnp.concatenate([b_key, b_key])
        tag = jnp.concatenate(
            [
                jnp.where(is_mutex, jnp.int32(0), jnp.int32(2)),
                jnp.where(is_query, jnp.int32(1), jnp.int32(2)),
            ]
        )
        payload = jnp.concatenate([jnp.full((m,), big, jnp.int32), idx])
        sA, sB, sT, sP = lax.sort((A2, B2, tag, payload), num_keys=3)
        # a (A, B) run may hold SEVERAL query rows (best-of-A and best-of-B
        # edges of the same cluster pair); tags sort mutex(0) < query(1), so
        # "run contains a mutex row" == "the run's first row is a mutex row".
        # Propagate that over the whole run (cummax of run-start positions +
        # gather) so every query row in the run sees the flag — not just the
        # one adjacent to a mutex row.
        idx2 = jnp.arange(2 * m, dtype=jnp.int32)
        run_start = jnp.concatenate(
            [
                jnp.ones((1,), bool),
                (sA[1:] != sA[:-1]) | (sB[1:] != sB[:-1]),
            ]
        )
        start_pos = lax.cummax(jnp.where(run_start, idx2, 0))
        hit = (sT == 1) & (sT[start_pos] == 0)
        mutexed = (
            jnp.zeros((m + 1,), jnp.int32)
            .at[jnp.where(sT == 1, sP, big)].max(hit.astype(jnp.int32))
        )[:m] > 0

        merge_e = mutual & attractive & ~mutexed
        # merged, mutex-blocked, and repulsive mutual edges are all decided;
        # so is every doomed edge of an already-mutexed cluster pair
        processed = processed | mutual | (is_query & mutexed)

        # chain contraction: a cluster whose best edge is attractive and
        # which is mutex-immune (no incident repulsive edge, active or
        # processed, at least as strong) merges along its best edge even
        # without mutuality — see the module docstring for the proof.
        # beta[c]: strongest repulsive edge still incident to cluster c
        # under the strict (weight desc, index asc) order — weight
        # scatter-max, then index scatter-min among the weight-achievers
        # (intra-cluster rows are stale mutexes and excluded)
        is_rep = ~attractive & (cu != cv)
        w_rep = jnp.where(is_rep, weights, -jnp.inf)
        beta = (
            jnp.full((n_nodes,), -jnp.inf, weights.dtype)
            .at[cu].max(w_rep)
            .at[cv].max(w_rep)
        )
        beta_i = (
            jnp.full((n_nodes,), big, jnp.int32)
            .at[cu].min(jnp.where(is_rep & (weights == beta[cu]), idx, big))
            .at[cv].min(jnp.where(is_rep & (weights == beta[cv]), idx, big))
        )
        # X immune for its best edge e: every incident repulsive edge comes
        # AFTER e in the total order — (w_e, -i_e) strictly above the
        # strongest repulsive (beta, -beta_i)
        immune_u = (weights > beta[cu]) | (
            (weights == beta[cu]) & (idx < beta_i[cu])
        )
        immune_v = (weights > beta[cv]) | (
            (weights == beta[cv]) & (idx < beta_i[cv])
        )
        # e best-for-X (best[cu] == idx), attractive, not mutexed, X immune;
        # direction X -> Y.  ~mutexed is LOAD-BEARING here: the join now
        # queries every active edge, and a mutexed chain candidate must be
        # doomed-discarded (processed above), never chain-merged.
        enable = jnp.bool_(enable_chain)
        chain_u = (
            enable & active & attractive & ~mutexed
            & (best[cu] == idx) & immune_u
        )
        chain_v = (
            enable & active & attractive & ~mutexed
            & (best[cv] == idx) & immune_v
        )
        merge_u = chain_u & ~mutual  # mutual pairs keep b_key -> a_key
        merge_v = chain_v & ~mutual
        processed = processed | merge_u | merge_v

        # parent forest: mutual pairs point larger -> smaller; chain edges
        # point the immune side at its partner's cluster.  Each cluster has
        # at most one best edge, so the scatters never collide.
        parent = jnp.concatenate([nodes, jnp.zeros((1,), jnp.int32)])
        src = jnp.where(merge_e, b_key, jnp.int32(n_nodes))
        parent = parent.at[src].set(jnp.where(merge_e, a_key, 0))
        src_u = jnp.where(merge_u, cu, jnp.int32(n_nodes))
        parent = parent.at[src_u].set(jnp.where(merge_u, cv, 0))
        src_v = jnp.where(merge_v, cv, jnp.int32(n_nodes))
        parent = parent.at[src_v].set(jnp.where(merge_v, cu, 0))
        # collapse chains/trees to their roots by log-depth pointer jumping.
        # The parent graph is a strict forest: best-edge weights strictly
        # increase along a chain (an equal-weight continuation would be the
        # mutual pair, which points larger -> smaller and roots at the
        # smaller id), so p <- p[p] reaches every root in log2(n) steps.
        p = parent[:n_nodes]

        def jump(_, p):
            return p[p]

        p = lax.fori_loop(
            0, max(int(np.ceil(np.log2(max(n_nodes, 2)))) + 1, 1), jump, p
        )
        comp = p[comp]
        return comp, processed, rounds + 1

    comp, _, rounds = lax.while_loop(
        cond, body, (nodes, jnp.zeros((m,), dtype=bool), jnp.int32(0))
    )
    return comp, rounds


def _pad_problem(uv, weights, attractive):
    """Pad the edge lists to the next power of two so repeated solves of
    similar-size blocks reuse the jit cache.  Padding rows are repulsive
    self-loops at node 0 with weight −1 — intra-cluster from round one,
    never active.  The single staging path for the solver and the rounds
    diagnostic."""
    m = int(uv.shape[0])
    mp = _next_pow2(max(m, 1))
    uv32 = np.zeros((mp, 2), dtype=np.int32)
    uv32[:m] = uv
    w = np.full(mp, -1.0, dtype=np.float32)
    w[:m] = weights
    at = np.zeros(mp, dtype=bool)
    at[:m] = np.asarray(attractive).astype(bool)
    return uv32, w, at


def mutex_watershed_device(
    n_nodes: int,
    uv: np.ndarray,
    weights: np.ndarray,
    attractive: np.ndarray,
) -> np.ndarray:
    """Drop-in device counterpart of ``native.mutex_watershed`` /
    ``_mws_python``: root (canonical cluster id) per node."""
    if n_nodes >= np.iinfo(np.int32).max:
        raise ValueError("device MWS needs an int32-addressable node space")
    import jax.numpy as jnp

    uv32, w, at = _pad_problem(uv, weights, attractive)
    labels, _ = _mws_parallel_greedy(
        jnp.asarray(uv32), jnp.asarray(w), jnp.asarray(at), n_nodes=int(n_nodes)
    )
    return np.asarray(labels, dtype=np.int64)


def mutex_watershed_device_rounds(
    n_nodes: int,
    uv: np.ndarray,
    weights: np.ndarray,
    attractive: np.ndarray,
    enable_chain: bool = True,
) -> int:
    """Round count of the while_loop for the given problem — the convergence
    diagnostic behind the chain-contraction tests and bench.

    ``enable_chain=False`` runs the mutual-matching-only algorithm, kept
    measurable so the contraction win stays reproducible (and the legacy
    path covered) from the tests."""
    import jax.numpy as jnp

    uv32, w, at = _pad_problem(uv, weights, attractive)
    _, rounds = _mws_parallel_greedy(
        jnp.asarray(uv32), jnp.asarray(w), jnp.asarray(at),
        n_nodes=int(n_nodes), enable_chain=bool(enable_chain),
    )
    return int(rounds)
