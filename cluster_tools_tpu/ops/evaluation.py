"""Segmentation evaluation: Rand index / adapted Rand error / variation of
information from sparse contingency tables.

Replaces elf.evaluation / nifty.ground_truth (reference evaluation/measures.py:
90-158 — the parity metrics named in BASELINE.md).  All metrics take the sparse
contingency (ids_a, ids_b, counts) so they compose with the distributed overlap
machinery (per-block contingency tables merged by summation).
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from .segment import contingency_table


def merge_contingency_tables(tables):
    """Sum sparse (ids_a, ids_b, counts) tables from several blocks."""
    ia = np.concatenate([t[0] for t in tables])
    ib = np.concatenate([t[1] for t in tables])
    c = np.concatenate([t[2] for t in tables])
    pairs = np.stack([ia, ib], axis=1)
    uniq, inv = np.unique(pairs, axis=0, return_inverse=True)
    counts = np.zeros(uniq.shape[0], dtype=np.int64)
    np.add.at(counts, inv, c)
    return uniq[:, 0], uniq[:, 1], counts


def _marginals(ids_a, ids_b, counts):
    """Vectorized marginal sums (contingency tables can have millions of rows)."""
    ua, inv_a = np.unique(ids_a, return_inverse=True)
    ub, inv_b = np.unique(ids_b, return_inverse=True)
    a_sum = np.bincount(inv_a, weights=counts, minlength=ua.size)
    b_sum = np.bincount(inv_b, weights=counts, minlength=ub.size)
    return a_sum.astype(np.float64), b_sum.astype(np.float64)


def rand_scores(
    ids_a: np.ndarray, ids_b: np.ndarray, counts: np.ndarray
) -> Dict[str, float]:
    """Rand index, precision/recall over pairs, adapted Rand error.

    a = segmentation, b = ground truth (reference measures.py convention).
    """
    counts = counts.astype(np.float64)
    n = counts.sum()
    sum_ab = (counts**2).sum()
    sum_a, sum_b = _marginals(ids_a, ids_b, counts)
    sum_a2 = (sum_a**2).sum()
    sum_b2 = (sum_b**2).sum()

    # pair counts
    pairs_joint = (sum_ab - n) / 2.0
    pairs_a = (sum_a2 - n) / 2.0
    pairs_b = (sum_b2 - n) / 2.0
    total = n * (n - 1) / 2.0

    precision = pairs_joint / pairs_a if pairs_a > 0 else 1.0
    recall = pairs_joint / pairs_b if pairs_b > 0 else 1.0
    f_score = (
        2.0 * precision * recall / (precision + recall)
        if precision + recall > 0
        else 0.0
    )
    # Rand index over all pairs
    agree = pairs_joint + (total - pairs_a - pairs_b + pairs_joint)
    rand_index = agree / total if total > 0 else 1.0
    return {
        "rand_index": float(rand_index),
        "rand_precision": float(precision),
        "rand_recall": float(recall),
        "adapted_rand_error": float(1.0 - f_score),
    }


def vi_scores(
    ids_a: np.ndarray, ids_b: np.ndarray, counts: np.ndarray
) -> Dict[str, float]:
    """Variation of information: split (H(A|B)) and merge (H(B|A)) terms.

    vi-split penalizes over-segmentation of a w.r.t. b; vi-merge penalizes
    merges (reference measures.py:154-156 conventions: a = seg, b = gt →
    vi-split = H(seg|gt), vi-merge = H(gt|seg)).
    """
    counts = counts.astype(np.float64)
    n = counts.sum()
    p = counts / n
    sum_a, sum_b = _marginals(ids_a, ids_b, counts)
    pa = sum_a / n
    pb = sum_b / n
    h_ab = -(p * np.log(p)).sum() if p.size else 0.0  # joint entropy
    h_a = -(pa * np.log(pa)).sum() if pa.size else 0.0
    h_b = -(pb * np.log(pb)).sum() if pb.size else 0.0
    return {
        "vi_split": float(h_ab - h_b),  # H(A|B)
        "vi_merge": float(h_ab - h_a),  # H(B|A)
        "vi": float(2 * h_ab - h_a - h_b),
    }


def evaluate_segmentation(
    seg: np.ndarray, gt: np.ndarray, ignore_gt_zero: bool = True
) -> Dict[str, float]:
    """Single-volume convenience wrapper: full metric dict."""
    ia, ib, counts = contingency_table(seg, gt)
    if ignore_gt_zero:
        keep = ib != 0
        ia, ib, counts = ia[keep], ib[keep], counts[keep]
    out = rand_scores(ia, ib, counts)
    out.update(vi_scores(ia, ib, counts))
    return out


def object_vi(
    seg: np.ndarray, gt: np.ndarray, ignore_gt_zero: bool = True
) -> Dict[int, Tuple[float, float]]:
    """Per-ground-truth-object (vi_split, vi_merge) scores
    (reference object_vi.py:26 via elf)."""
    ia, ib, counts = contingency_table(seg, gt)
    if ignore_gt_zero:
        keep = ib != 0
        ia, ib, counts = ia[keep], ib[keep], counts[keep]
    return object_vi_from_contingency(ia, ib, counts)


def object_vi_from_contingency(
    ia: np.ndarray, ib: np.ndarray, counts: np.ndarray
) -> Dict[int, Tuple[float, float]]:
    """Per-gt-object VI from a merged (seg id, gt id, count) table — the
    distributed path (reference object_vi.py:100-118)."""
    counts = counts.astype(np.float64)
    # seg marginals (global)
    seg_sizes: Dict[int, float] = {}
    for a, c in zip(ia, counts):
        seg_sizes[int(a)] = seg_sizes.get(int(a), 0.0) + c
    scores: Dict[int, Tuple[float, float]] = {}
    for b in np.unique(ib):
        sel = ib == b
        c = counts[sel]
        size_b = c.sum()
        p = c / size_b
        # split: entropy of seg labels within this gt object
        split = float(-(p * np.log(p)).sum())
        # merge: how much of each intersecting seg segment lies outside b
        merge = 0.0
        for a, cc in zip(ia[sel], c):
            frac = cc / seg_sizes[int(a)]
            if frac < 1.0:
                merge -= (cc / size_b) * np.log(frac)
        scores[int(b)] = (split, float(merge))
    return scores


def same_partition(a: np.ndarray, b: np.ndarray) -> bool:
    """True iff two label volumes induce the same partition of the foreground
    (ids may differ; the grouping and the foreground mask must not).

    The bijection test: the number of distinct (a, b) co-occurring id pairs
    must equal the number of distinct ids on each side.  Shared oracle for
    tests and the driver dryrun — a partition-identity check, stricter than
    Rand/VoI parity.
    """
    if a.shape != b.shape:
        return False
    if not ((a > 0) == (b > 0)).all():
        return False
    fg = b > 0
    if not fg.any():
        return True
    pairs = np.unique(np.stack([a[fg], b[fg]], axis=1), axis=0)
    return len(pairs) == len(np.unique(a[fg])) == len(np.unique(b[fg]))
