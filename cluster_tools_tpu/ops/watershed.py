"""Seeded watershed and seed detection as XLA programs.

Replaces vigra.analysis.watershedsNew / localMaxima3D and
elf.segmentation.watershed (reference watershed/watershed.py:164-250).

Seeded watershed is inherently a priority-flood; the TPU formulation is the
equivalent *lexicographic shortest-path relaxation*: every voxel takes the label
of the seed reachable with the lexicographically smallest path cost

    ( pass height = max h along the path,  hop count,  seed label )

via the Bellman–Ford-style sweep

    state'(p) = lexmin over neighbors q of ( max(alt(q), h(p)), dist(q)+1, label(q) )

run inside ``lax.while_loop`` with pure shift/select ops, seeds pinned.  The state
is *recomputed from neighbors every sweep* (never kept), so each fixpoint state is
witnessed by a current neighbor; the hop-count component makes witness chains
strictly decreasing in dist → acyclic → every voxel is connected to its seed
through its own label (no "ghost label" fragments, no plateau cycles).  Converges
in O(longest flood path) data-parallel sweeps.  Ties resolve to the smaller label
id; voxel-exact boundaries can differ from vigra's sequential flood order, which
is why parity is defined on Rand/VoI, not voxel equality (SURVEY.md §7 #1).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .cc import connected_components, neighbor_offsets, _shift
from .filters import gaussian, maximum_filter, normalize

_BIG = jnp.float32(3.0e38)


@partial(jax.jit, static_argnames=("connectivity", "max_iter", "per_slice"))
def seeded_watershed(
    hmap: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    connectivity: int = 1,
    max_iter: int = 0,
    per_slice: bool = False,
) -> jnp.ndarray:
    """Flood ``seeds`` (int32, 0 = unlabeled) over height map ``hmap``.

    Voxels outside ``mask`` stay 0 and do not conduct floods.  ``max_iter=0``
    iterates to the fixpoint.  ``per_slice`` floods each z-slice independently
    (the reference's 2d watershed mode, watershed.py:120-137).
    """
    hmap = hmap.astype(jnp.float32)
    if mask is None:
        mask = jnp.ones(hmap.shape, dtype=bool)
    else:
        mask = mask.astype(bool)
    seeds = jnp.where(mask, seeds.astype(jnp.int32), 0)
    offsets = neighbor_offsets(hmap.ndim, connectivity, per_slice)
    is_seed = seeds > 0

    big_dist = jnp.int32(np.iinfo(np.int32).max - 1)
    label0 = seeds
    alt0 = jnp.where(is_seed, hmap, _BIG)
    dist0 = jnp.where(is_seed, 0, big_dist)

    def cond(state):
        _, _, _, changed, it = state
        return changed if max_iter == 0 else changed & (it < max_iter)

    def body(state):
        label, alt, dist, _, it = state
        # recompute purely from neighbors — own state is NOT a candidate, so
        # stale ("ghost") states cannot survive once their witness disappears
        best_alt = jnp.where(is_seed, alt0, _BIG)
        best_dist = jnp.where(is_seed, dist0, big_dist)
        best_label = jnp.where(is_seed, seeds, 0)
        for off in offsets:
            n_label = _shift(label, off, jnp.int32(0))
            n_alt = _shift(alt, off, _BIG)
            n_dist = _shift(dist, off, big_dist)
            valid = n_label > 0
            cand_alt = jnp.where(valid, jnp.maximum(n_alt, hmap), _BIG)
            cand_dist = jnp.where(valid, n_dist + 1, big_dist)
            better = (
                (cand_alt < best_alt)
                | ((cand_alt == best_alt) & (cand_dist < best_dist))
                | (
                    (cand_alt == best_alt)
                    & (cand_dist == best_dist)
                    & valid
                    & ((best_label == 0) | (n_label < best_label))
                )
            )
            better = better & ~is_seed
            best_alt = jnp.where(better, cand_alt, best_alt)
            best_dist = jnp.where(better, cand_dist, best_dist)
            best_label = jnp.where(better, n_label, best_label)
        best_label = jnp.where(mask, best_label, 0)
        best_alt = jnp.where(mask, best_alt, _BIG)
        best_dist = jnp.where(mask, best_dist, big_dist)
        changed = jnp.any(
            (best_label != label) | (best_alt != alt) | (best_dist != dist)
        )
        return best_label, best_alt, best_dist, changed, it + 1

    label, _, _, _, _ = lax.while_loop(
        cond, body, (label0, alt0, dist0, jnp.bool_(True), jnp.int32(0))
    )
    return label


@partial(jax.jit, static_argnames=("sigma", "per_slice"))
def dt_seeds(
    dt: jnp.ndarray, sigma: float = 2.0, per_slice: bool = False
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Seeds from a distance transform: smooth → local maxima (plateaus merged by
    full-connectivity CC over the maxima mask) → consecutive labels.

    Mirrors reference ``_make_seeds`` (watershed.py:180-208): gaussian(dt) then
    localMaxima with allowAtBorder/allowPlateaus.  ``per_slice`` detects maxima
    and labels seeds within each z-slice independently (2d seed mode).
    """
    if sigma and sigma > 0:
        # per-slice mode smooths within slices only (reference 2d seed path)
        sig = (0.0,) + (sigma,) * (dt.ndim - 1) if per_slice else sigma
        smoothed = gaussian(dt, sig)
    else:
        smoothed = dt
    window = (1,) + (3,) * (dt.ndim - 1) if per_slice else 3
    local_max = (maximum_filter(smoothed, window) == smoothed) & (dt > 0)
    seeds, n = connected_components(
        local_max, connectivity=dt.ndim, per_slice=per_slice
    )
    return seeds, n


@partial(
    jax.jit,
    static_argnames=(
        "threshold",
        "apply_dt_2d",
        "apply_ws_2d",
        "pixel_pitch",
        "sigma_seeds",
        "sigma_weights",
        "alpha",
        "size_filter",
        "invert_input",
    ),
)
def dt_watershed(
    input_: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    threshold: float = 0.25,
    apply_dt_2d: bool = True,
    apply_ws_2d: bool = True,
    pixel_pitch: Optional[Tuple[float, ...]] = None,
    sigma_seeds: float = 2.0,
    sigma_weights: float = 2.0,
    alpha: float = 0.8,
    size_filter: int = 25,
    invert_input: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The full per-block DT-watershed — one fused XLA program.

    threshold → distance transform (2d or 3d) → smoothed-maxima seeds → height
    map α·input + (1-α)·(1-dt) → seeded flood → size filter.  Mirrors the
    reference hot loop ``_ws_block`` (watershed.py:286-344) minus IO and offsets
    (applied host-side).  Returns ``(labels int32, n_seeds)``.

    NB: the reference's optional seed non-maximum-suppression
    (nifty.filters.nonMaximumDistanceSuppression, watershed.py:22) is not
    implemented; plateau-merged maxima over-seed slightly, the size filter and
    downstream agglomeration absorb the difference.
    """
    from .dt import _distance_transform, distance_transform_2d_stack

    if pixel_pitch is not None and apply_dt_2d:
        # mirror the reference's assertion (watershed.py:149-153): anisotropic
        # pitch only applies to the 3d distance transform
        raise ValueError("pixel_pitch requires apply_dt_2d=False")

    x = input_.astype(jnp.float32)
    if invert_input:
        x = 1.0 - x
    fg = x < threshold
    if mask is not None:
        fg = fg & mask.astype(bool)

    if apply_dt_2d and x.ndim == 3:
        dt = distance_transform_2d_stack(fg, pixel_pitch=None)
    else:
        dt = _distance_transform(fg, pixel_pitch)

    per_slice_seeds = apply_ws_2d and x.ndim == 3
    seeds, n_seeds = dt_seeds(dt, sigma_seeds, per_slice=per_slice_seeds)
    hmap = make_hmap(x, dt, alpha, sigma_weights, per_slice=per_slice_seeds)
    labels = seeded_watershed(hmap, seeds, mask=fg, per_slice=per_slice_seeds)
    if size_filter > 0:
        num_segments = int(np.prod(x.shape)) // 2 + 2
        labels = apply_size_filter(
            labels, hmap, size_filter, num_segments, mask=fg,
            per_slice=per_slice_seeds,
        )
    return labels, n_seeds


@partial(jax.jit, static_argnames=("alpha", "sigma", "per_slice"))
def make_hmap(
    input_: jnp.ndarray,
    dt: jnp.ndarray,
    alpha: float,
    sigma: float = 0.0,
    per_slice: bool = False,
) -> jnp.ndarray:
    """Height map α·input + (1-α)·(1 - normalize(dt))
    (reference ``_make_hmap``, watershed.py:164-170).  ``per_slice`` normalizes
    the distances and smooths within each z-slice (2d mode)."""
    dtn = jax.vmap(normalize)(dt) if per_slice else normalize(dt)
    hmap = alpha * input_ + (1.0 - alpha) * (1.0 - dtn)
    if sigma and sigma > 0:
        sig = (0.0,) + (sigma,) * (dt.ndim - 1) if per_slice else sigma
        hmap = gaussian(hmap, sig)
    return hmap


@partial(
    jax.jit,
    static_argnames=("size_filter", "num_segments", "connectivity", "per_slice"),
)
def apply_size_filter(
    labels: jnp.ndarray,
    hmap: jnp.ndarray,
    size_filter: int,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    connectivity: int = 1,
    per_slice: bool = False,
) -> jnp.ndarray:
    """Remove segments smaller than ``size_filter`` voxels and re-flood the freed
    voxels from the surviving segments (reference ``_apply_watershed``
    size-filter step, watershed.py:242-250).

    ``num_segments`` is the *exclusive* upper bound on label values, i.e.
    max_label + 1 (pass ``n + 1`` for labels 1..n from dt_seeds)."""
    counts = jnp.bincount(labels.reshape(-1), length=num_segments)
    too_small = counts[labels] < size_filter
    kept = jnp.where(too_small, 0, labels)
    return seeded_watershed(
        hmap, kept, mask=mask, connectivity=connectivity, per_slice=per_slice
    )


def fit_to_hmap(
    objs: np.ndarray,
    hmap: np.ndarray,
    erode_by: int,
    erode_3d: bool = True,
) -> np.ndarray:
    """Refit (possibly resampled) objects to a boundary height map: erode each
    object, then re-grow all of them with a seeded watershed on a DT-blended
    height map (reference volume_utils.fit_to_hmap:336-357).

    Host wrapper: labels are compacted to int32 for the device flood and mapped
    back, so uint64 ids survive.  The per-object erosion is the min==max window
    test (a voxel is interior iff its whole window carries one label); the
    background seed is the eroded background.  Returns the refit uint64 labels.
    """
    from .dt import distance_transform
    from .filters import minimum_filter

    uniq = np.unique(objs)
    if uniq[0] != 0:
        uniq = np.concatenate([[0], uniq])
    local = np.searchsorted(uniq, objs).astype(np.int32)
    bg_id = np.int32(uniq.size)

    size = 2 * int(erode_by) + 1
    win = size if erode_3d else (1, size, size)
    labels = jnp.asarray(local)
    mn = minimum_filter(labels, win)
    mx = maximum_filter(labels, win)
    interior = (mn == mx) & (labels > 0)
    seeds = jnp.where(interior, labels, 0)
    seeds = jnp.where(mx == 0, bg_id, seeds)

    h = normalize(jnp.asarray(hmap, jnp.float32))
    dt = distance_transform(h > 0.3)
    h = 0.8 * h + 0.2 * (1.0 - normalize(dt))

    fitted_local = np.array(seeded_watershed(h, seeds))
    fitted_local[fitted_local == bg_id] = 0
    return uniq[fitted_local].astype(np.uint64)
