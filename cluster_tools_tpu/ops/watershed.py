"""Seeded watershed and seed detection as XLA programs.

Replaces vigra.analysis.watershedsNew / localMaxima3D and
elf.segmentation.watershed (reference watershed/watershed.py:164-250).

Seeded watershed is inherently a priority-flood; the TPU formulation is the
equivalent *lexicographic shortest-path relaxation*: every voxel takes the label
of the seed reachable with the lexicographically smallest path cost

    ( pass height = max h along the path,  hop count,  seed label )

via the Bellman–Ford-style sweep

    state'(p) = lexmin over neighbors q of ( max(alt(q), h(p)), dist(q)+1, label(q) )

run inside ``lax.while_loop`` with pure shift/select ops, seeds pinned.  The state
is *recomputed from neighbors every sweep* (never kept), so each fixpoint state is
witnessed by a current neighbor; the hop-count component makes witness chains
strictly decreasing in dist → acyclic → every voxel is connected to its seed
through its own label (no "ghost label" fragments, no plateau cycles).  Converges
in O(longest flood path) data-parallel sweeps.  Ties resolve to the smaller label
id; voxel-exact boundaries can differ from vigra's sequential flood order, which
is why parity is defined on Rand/VoI, not voxel equality (SURVEY.md §7 #1).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .cc import connected_components, neighbor_offsets, _shift
from .filters import gaussian, maximum_filter, normalize

_BIG = jnp.float32(3.0e38)


@partial(jax.jit, static_argnames=("connectivity", "max_iter"))
def seeded_watershed(
    hmap: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    connectivity: int = 1,
    max_iter: int = 0,
) -> jnp.ndarray:
    """Flood ``seeds`` (int32, 0 = unlabeled) over height map ``hmap``.

    Voxels outside ``mask`` stay 0 and do not conduct floods.  ``max_iter=0``
    iterates to the fixpoint.
    """
    hmap = hmap.astype(jnp.float32)
    if mask is None:
        mask = jnp.ones(hmap.shape, dtype=bool)
    else:
        mask = mask.astype(bool)
    seeds = jnp.where(mask, seeds.astype(jnp.int32), 0)
    offsets = neighbor_offsets(hmap.ndim, connectivity)
    is_seed = seeds > 0

    big_dist = jnp.int32(np.iinfo(np.int32).max - 1)
    label0 = seeds
    alt0 = jnp.where(is_seed, hmap, _BIG)
    dist0 = jnp.where(is_seed, 0, big_dist)

    def cond(state):
        _, _, _, changed, it = state
        return changed if max_iter == 0 else changed & (it < max_iter)

    def body(state):
        label, alt, dist, _, it = state
        # recompute purely from neighbors — own state is NOT a candidate, so
        # stale ("ghost") states cannot survive once their witness disappears
        best_alt = jnp.where(is_seed, alt0, _BIG)
        best_dist = jnp.where(is_seed, dist0, big_dist)
        best_label = jnp.where(is_seed, seeds, 0)
        for off in offsets:
            n_label = _shift(label, off, jnp.int32(0))
            n_alt = _shift(alt, off, _BIG)
            n_dist = _shift(dist, off, big_dist)
            valid = n_label > 0
            cand_alt = jnp.where(valid, jnp.maximum(n_alt, hmap), _BIG)
            cand_dist = jnp.where(valid, n_dist + 1, big_dist)
            better = (
                (cand_alt < best_alt)
                | ((cand_alt == best_alt) & (cand_dist < best_dist))
                | (
                    (cand_alt == best_alt)
                    & (cand_dist == best_dist)
                    & valid
                    & ((best_label == 0) | (n_label < best_label))
                )
            )
            better = better & ~is_seed
            best_alt = jnp.where(better, cand_alt, best_alt)
            best_dist = jnp.where(better, cand_dist, best_dist)
            best_label = jnp.where(better, n_label, best_label)
        best_label = jnp.where(mask, best_label, 0)
        best_alt = jnp.where(mask, best_alt, _BIG)
        best_dist = jnp.where(mask, best_dist, big_dist)
        changed = jnp.any(
            (best_label != label) | (best_alt != alt) | (best_dist != dist)
        )
        return best_label, best_alt, best_dist, changed, it + 1

    label, _, _, _, _ = lax.while_loop(
        cond, body, (label0, alt0, dist0, jnp.bool_(True), jnp.int32(0))
    )
    return label


@partial(jax.jit, static_argnames=("sigma",))
def dt_seeds(dt: jnp.ndarray, sigma: float = 2.0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Seeds from a distance transform: smooth → local maxima (plateaus merged by
    full-connectivity CC over the maxima mask) → consecutive labels.

    Mirrors reference ``_make_seeds`` (watershed.py:180-208): gaussian(dt) then
    localMaxima with allowAtBorder/allowPlateaus.
    """
    smoothed = gaussian(dt, sigma) if sigma and sigma > 0 else dt
    local_max = (maximum_filter(smoothed, 3) == smoothed) & (dt > 0)
    seeds, n = connected_components(local_max, connectivity=dt.ndim)
    return seeds, n


@partial(jax.jit, static_argnames=("alpha", "sigma"))
def make_hmap(
    input_: jnp.ndarray, dt: jnp.ndarray, alpha: float, sigma: float = 0.0
) -> jnp.ndarray:
    """Height map α·input + (1-α)·(1 - normalize(dt))
    (reference ``_make_hmap``, watershed.py:164-170)."""
    dtn = normalize(dt)
    hmap = alpha * input_ + (1.0 - alpha) * (1.0 - dtn)
    if sigma and sigma > 0:
        hmap = gaussian(hmap, sigma)
    return hmap


@partial(jax.jit, static_argnames=("size_filter", "num_segments", "connectivity"))
def apply_size_filter(
    labels: jnp.ndarray,
    hmap: jnp.ndarray,
    size_filter: int,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    connectivity: int = 1,
) -> jnp.ndarray:
    """Remove segments smaller than ``size_filter`` voxels and re-flood the freed
    voxels from the surviving segments (reference ``_apply_watershed``
    size-filter step, watershed.py:242-250).

    ``num_segments`` is the *exclusive* upper bound on label values, i.e.
    max_label + 1 (pass ``n + 1`` for labels 1..n from dt_seeds)."""
    counts = jnp.bincount(labels.reshape(-1), length=num_segments)
    too_small = counts[labels] < size_filter
    kept = jnp.where(too_small, 0, labels)
    return seeded_watershed(hmap, kept, mask=mask, connectivity=connectivity)
