"""Seeded watershed and seed detection as XLA programs.

Replaces vigra.analysis.watershedsNew / localMaxima3D and
elf.segmentation.watershed (reference watershed/watershed.py:164-250).

Seeded watershed is inherently a priority-flood; the TPU formulation is the
equivalent *lexicographic shortest-path relaxation*: every voxel takes the label
of the seed reachable with the lexicographically smallest path cost

    ( pass height = max h along the path,  hop count,  seed label )

The default 6-connectivity path runs *directional raster sweeps* (the chamfer /
Gauss–Seidel scheme) along ±z, ±y, ±x, so each sweep carries flood fronts
across the whole axis instead of one voxel — the outer ``lax.while_loop`` then
converges in O(#bends of the steepest path) rounds (typically < 10) instead of
O(longest flood path) sweeps.  Each sweep's carry chain evaluates either
sequentially (``lax.scan``, work-bound backends) or in log depth
(``lax.associative_scan`` over closed transfer-function compositions,
dispatch-bound TPUs) — ops/_backend.py picks, both compute the identical
fixpoint (tested).  Monotone label-correcting relaxation is exact: every state
is witnessed by a real path from a seed (induction over updates), states only
decrease, and the unique fixpoint is the lexicographic minimum over all paths —
the same fixpoint the neighbor-sweep kernel (``_seeded_watershed_sweep``, kept
for connectivity > 1) reaches.  Ties resolve to the smaller label id;
voxel-exact boundaries can differ from vigra's sequential flood order, which is
why parity is defined on Rand/VoI, not voxel equality (SURVEY.md §7 #1).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import _backend
from .cc import (
    _canonical_offsets,
    _shift,
    _tile_grid,
    connected_components,
    neighbor_offsets,
    parse_tile_spec,
    resolve_coarse_tile,
    tile_crossing_take,
    tile_stack,
    tile_unstack,
)
from .filters import gaussian, maximum_filter, normalize

# numpy scalar, NOT jnp: a module-level jnp constant would initialize the
# device backend at import time (breaking imports in processes without a
# usable accelerator, e.g. batch-scheduler workers)
_BIG = np.float32(3.0e38)


def _axis_views(arrs, axis, reverse):
    """Move ``axis`` to the front (flipped when ``reverse``) for a raster scan."""

    def mv(x):
        x = jnp.moveaxis(x, axis, 0)
        return jnp.flip(x, axis=0) if reverse else x

    return tuple(mv(x) for x in arrs)


def _axis_unview(x, axis, reverse):
    if reverse:
        x = jnp.flip(x, axis=0)
    return jnp.moveaxis(x, 0, axis)


def _sweep_altitude_assoc(alt, hmap, is_seed, mask, axis, reverse):
    """Gauss–Seidel raster sweep of the flood-altitude field along one axis:
    A'(p) = min(A(p), max(A(prev plane), h(p))).

    The carry chain is a composition of per-element *clamp* transfers
    c → min(u, max(c, l)), a family closed under composition
    (u₂₁ = min(u₂, max(u₁, l₂)), l₂₁ = max(l₁, l₂)) — so the whole
    sequential sweep evaluates exactly via ``lax.associative_scan`` in
    log(n) full-array steps instead of n sequential plane steps (the scan
    version is dispatch-bound on TPU: 256 tiny steps per sweep)."""
    h_v, a_v, sd_v, mk_v = _axis_views((hmap, alt, is_seed, mask), axis, reverse)

    # per-element transfer (u, l): carry' = min(u, max(carry, l))
    #   outside mask: constant _BIG (doesn't conduct)
    #   seed:         constant a (its own fixed altitude)
    #   interior:     min(a_old, max(carry, h))
    conduct = mk_v & ~sd_v
    u = jnp.where(mk_v, a_v, _BIG)
    l = jnp.where(conduct, h_v, u)

    def combine(f, g):  # f earlier, g later along the sweep
        uf, lf = f
        ug, lg = g
        return jnp.minimum(ug, jnp.maximum(uf, lg)), jnp.maximum(lf, lg)

    u_inc, _ = lax.associative_scan(combine, (u, l), axis=0)
    # exclusive prefix applied to the initial carry _BIG gives just u
    carry_in = jnp.concatenate(
        [jnp.full_like(u_inc[:1], _BIG), u_inc[:-1]], axis=0
    )
    n_alt = jnp.where(
        conduct, jnp.minimum(a_v, jnp.maximum(carry_in, h_v)), a_v
    )
    return _axis_unview(n_alt, axis, reverse)



def _sweep_altitude_seq(alt, hmap, is_seed, mask, axis, reverse):
    """Sequential-carry variant of the altitude sweep (``lax.scan`` over
    planes).  O(n) work but n dependent steps — faster on work-bound
    backends (XLA-CPU), slower on dispatch-latency-bound TPUs, where
    ``_sweep_altitude_assoc`` wins."""
    h_v, a_v, sd_v, mk_v = _axis_views((hmap, alt, is_seed, mask), axis, reverse)
    plane_shape = h_v.shape[1:]

    def step(carry, x):
        h, o_alt, sd, mk = x
        cand = jnp.maximum(carry, h)
        better = mk & ~sd & (cand < o_alt)
        n_alt = jnp.where(better, cand, o_alt)
        # voxels outside the mask must not conduct: carry _BIG past them
        return jnp.where(mk, n_alt, _BIG), n_alt

    _, alts = lax.scan(step, jnp.full(plane_shape, _BIG), (h_v, a_v, sd_v, mk_v))
    return _axis_unview(alts, axis, reverse)


def _sweep_assign_seq(dist, label, alt, hmap, is_seed, mask, axis, reverse):
    """Sequential-carry variant of the assignment sweep (see
    ``_sweep_altitude_seq`` for the backend trade-off)."""
    big_dist = jnp.int32(np.iinfo(np.int32).max - 1)
    h_v, a_v, d_v, l_v, sd_v, mk_v = _axis_views(
        (hmap, alt, dist, label, is_seed, mask), axis, reverse
    )
    plane_shape = h_v.shape[1:]

    def step(carry, x):
        c_alt, c_dist, c_lab = carry
        h, o_alt, o_dist, o_lab, sd, mk = x
        edge_ok = o_alt == jnp.maximum(c_alt, h)
        cand_dist = c_dist + 1
        valid = (c_lab > 0) & mk & ~sd & edge_ok
        better = valid & (
            (cand_dist < o_dist)
            | ((cand_dist == o_dist) & ((o_lab == 0) | (c_lab < o_lab)))
        )
        n_dist = jnp.where(better, cand_dist, o_dist)
        n_lab = jnp.where(better, c_lab, o_lab)
        return (
            jnp.where(mk, o_alt, _BIG),
            n_dist,
            jnp.where(mk, n_lab, 0),
        ), (n_dist, n_lab)

    init = (
        jnp.full(plane_shape, _BIG),
        jnp.full(plane_shape, big_dist),
        jnp.zeros(plane_shape, jnp.int32),
    )
    _, (dists, labs) = lax.scan(step, init, (h_v, a_v, d_v, l_v, sd_v, mk_v))
    return (
        _axis_unview(dists, axis, reverse),
        _axis_unview(labs, axis, reverse),
    )


def _use_assoc() -> bool:
    return _backend.use_assoc()


def _minlex(d1, l1, d2, l2):
    """Min over (dist, label) lexicographic order where label 0 = +inf
    (the original sweep's tie-breaking: smaller hop count, then smaller
    label; unlabeled states never win)."""
    take1 = (l1 > 0) & ((l2 == 0) | (d1 < d2) | ((d1 == d2) & (l1 < l2)))
    return jnp.where(take1, d1, d2), jnp.where(take1, l1, l2)


def _sweep_assign_assoc(dist, label, alt, hmap, is_seed, mask, axis, reverse):
    """Gauss–Seidel raster sweep of the (hops, label) assignment along one
    axis, restricted to optimal-prefix edges q→p (A(p) == max(A(q), h(p))).

    The carry chain composes per-element transfers
        f(d, l) = minlex((D, L), (d + k, l) if pass ∧ l>0 else ∞)
    which are closed under composition (pass' = pass_f ∧ pass_g,
    k' = k_f + k_g, const' = minlex(const_g, const_f + k_g if pass_g)),
    so the sweep evaluates exactly via ``lax.associative_scan`` in log(n)
    full-array steps.  The edge-feasibility test A(p) == max(A(q), h(p))
    only involves the *fixed* altitudes of adjacent elements, so it is
    per-element data, not part of the recurrence state."""
    big_dist = jnp.int32(np.iinfo(np.int32).max - 1)
    h_v, a_v, d_v, l_v, sd_v, mk_v = _axis_views(
        (hmap, alt, dist, label, is_seed, mask), axis, reverse
    )

    # previous element's (masked) altitude — data, shifted along the axis
    alt_masked = jnp.where(mk_v, a_v, _BIG)
    prev_alt = jnp.concatenate(
        [jnp.full_like(alt_masked[:1], _BIG), alt_masked[:-1]], axis=0
    )
    edge_ok = a_v == jnp.maximum(prev_alt, h_v)
    can_update = mk_v & ~sd_v & edge_ok

    # per-element transfer: constant part = own pre-sweep state (masked to
    # (big, 0) outside the mask so it never conducts), pass-through iff the
    # optimal-prefix edge into this element exists
    const_d = jnp.where(mk_v, d_v, big_dist)
    const_l = jnp.where(mk_v, l_v, 0)
    step = jnp.ones_like(d_v)

    def combine(f, g):  # f earlier, g later
        fd, fl, fk, fp = f
        gd, gl, gk, gp = g
        cand_d = fd + gk
        cand_l = jnp.where(gp, fl, 0)
        d, l = _minlex(gd, gl, cand_d, cand_l)
        return d, l, fk + gk, fp & gp

    d_inc, l_inc, _, _ = lax.associative_scan(
        combine, (const_d, const_l, step, can_update), axis=0
    )
    # exclusive prefix applied to the initial carry (big, 0): the pass-through
    # candidate has l=0, so the result is just the composed constant part
    carry_d = jnp.concatenate(
        [jnp.full_like(d_inc[:1], big_dist), d_inc[:-1]], axis=0
    )
    carry_l = jnp.concatenate(
        [jnp.zeros_like(l_inc[:1]), l_inc[:-1]], axis=0
    )

    cand_dist = carry_d + 1
    better = can_update & (carry_l > 0) & (
        (cand_dist < d_v)
        | ((cand_dist == d_v) & ((l_v == 0) | (carry_l < l_v)))
    )
    n_dist = jnp.where(better, cand_dist, d_v)
    n_lab = jnp.where(better, carry_l, l_v)
    return (
        _axis_unview(n_dist, axis, reverse),
        _axis_unview(n_lab, axis, reverse),
    )


def _flood_scan_impl(
    hmap, seeds, mask, max_iter, per_slice, tile, warm=None
):
    """Directional-sweep flood (6-connectivity), two monotone phases:

      1. flood altitude A(p) = min over paths of (max h along path) by ±axis
         raster relaxation — a min–max problem where Gauss–Seidel sweeps are
         exact, converging in O(#bends of the steepest path) rounds;
      2. (hops, label) BFS over optimal-prefix edges (A(p) == max(A(q), h(p)))
         with min-label tie-breaking — also monotone under sweeps.

    The split matters: the combined (alt, hops, label) relaxation is NOT
    monotone (max() can keep a stale alt while hops/label change beneath it),
    which is why the neighbor-sweep kernel recomputes states from scratch.
    Each phase alone is monotone, so every fixpoint state has an exact witness
    chain → regions are connected, labels reach their seeds.

    ``tile`` (ctt-cc hierarchy reuse) warm-starts each phase from a
    tile-local fixpoint on independent ``tile_stack``-ed tiles, so the
    global loops only resolve cross-tile structure and their round count
    drops to O(#cross-tile bends) while the fixpoint stays bit-identical
    (tests/test_cc_coarse.py asserts both).  Exactness is an
    over-approximation argument per phase: a warm state below the fixpoint
    could never be corrected upward (relaxation only decreases), so each
    warm state must be witnessed by a REAL feasible path —

      * phase 1: in-tile relaxations are a subset of the global ones, so
        tile-local altitudes are min-max passes of real paths (≥ fixpoint),
        and a sweep-stable over-approximation with pinned seeds IS the
        fixpoint (induction along an optimal path);
      * phase 2 MUST warm-start against the GLOBAL altitude field, after
        global phase 1: any path of globally-feasible edges
        (A(p) == max(A(q), h(p))) is prefix-optimal, so in-tile (hops,
        label) states over those edges are ≥ the fixpoint.  Running tile
        phase 2 against the TILE-local altitudes instead would be wrong:
        a tile path can be pass-optimal without being prefix-optimal, and
        its smaller hop count would survive to a different label
        tie-break.

    ``warm`` injects an externally computed altitude warm state under the
    same phase-1 witness contract (the tiled Pallas flood,
    ops/pallas_flood.py — alt only, for exactly the phase-2 reason above).

    Returns ``(label, alt, stats)`` with int32 round counters
    ``flood_tile_iters`` / ``flood_alt_iters`` / ``flood_assign_iters``.
    """
    hmap = hmap.astype(jnp.float32)
    seeds = jnp.where(mask, seeds.astype(jnp.int32), 0)
    is_seed = seeds > 0
    big_dist = jnp.int32(np.iinfo(np.int32).max - 1)
    ndim = hmap.ndim
    axes = tuple(range(ndim))
    if per_slice:
        axes = axes[1:]  # z-slices independent: never sweep across axis 0

    if _use_assoc():
        _sweep_altitude = _sweep_altitude_assoc
        _sweep_assign = _sweep_assign_assoc
    else:
        _sweep_altitude = _sweep_altitude_seq
        _sweep_assign = _sweep_assign_seq

    def cond(state):
        return state[-2] if max_iter == 0 else state[-2] & (state[-1] < max_iter)

    tile_iters = jnp.int32(0)
    alt0 = jnp.where(is_seed, hmap, _BIG)
    label0 = seeds
    dist0 = jnp.where(is_seed, 0, big_dist)

    if warm is not None:
        alt0 = jnp.minimum(alt0, warm)  # injected phase-1 warm altitudes

    shape = hmap.shape
    h_t = m_t = sd_t = None
    t_axes = tuple(a + 1 for a in axes)
    if tile is not None:
        h_t = tile_stack(hmap, tile, _BIG)
        m_t = tile_stack(mask, tile, False)
        sd_t = tile_stack(is_seed, tile, False)

        # -- tile-local phase-1 warm start ---------------------------------
        def t_alt_body(state):
            alt, _, it = state
            prev = alt
            for axis in t_axes:
                for reverse in (False, True):
                    alt = _sweep_altitude(alt, h_t, sd_t, m_t, axis, reverse)
            return alt, jnp.any(alt != prev), it + 1

        alt_t, _, it_a = lax.while_loop(
            cond, t_alt_body,
            (tile_stack(alt0, tile, _BIG), jnp.bool_(True), jnp.int32(0)),
        )
        alt0 = tile_unstack(alt_t, shape, tile)
        tile_iters = tile_iters + it_a

    # -- phase 1: altitude ---------------------------------------------------
    def alt_body(state):
        alt, _, it = state
        prev = alt
        for axis in axes:
            for reverse in (False, True):
                alt = _sweep_altitude(alt, hmap, is_seed, mask, axis, reverse)
        return alt, jnp.any(alt != prev), it + 1

    alt, _, alt_iters = lax.while_loop(
        cond, alt_body, (alt0, jnp.bool_(True), jnp.int32(0))
    )

    if tile is not None:
        # -- tile-local phase-2 warm start against the GLOBAL altitude -----
        # (see the docstring: tile-local altitudes would break exactness)
        a_t = tile_stack(alt, tile, _BIG)

        def t_asg_body(state):
            dist, label, _, it = state
            prev_d, prev_l = dist, label
            for axis in t_axes:
                for reverse in (False, True):
                    dist, label = _sweep_assign(
                        dist, label, a_t, h_t, sd_t, m_t, axis, reverse
                    )
            changed = jnp.any((dist != prev_d) | (label != prev_l))
            return dist, label, changed, it + 1

        dist_t, label_t, _, it_s = lax.while_loop(
            cond, t_asg_body,
            (
                tile_stack(dist0, tile, big_dist),
                tile_stack(label0, tile, 0),
                jnp.bool_(True),
                jnp.int32(0),
            ),
        )
        dist0 = tile_unstack(dist_t, shape, tile)
        label0 = tile_unstack(label_t, shape, tile)
        tile_iters = tile_iters + it_s

    # -- phase 2: assignment -------------------------------------------------
    def assign_body(state):
        dist, label, _, it = state
        prev_d, prev_l = dist, label
        for axis in axes:
            for reverse in (False, True):
                dist, label = _sweep_assign(
                    dist, label, alt, hmap, is_seed, mask, axis, reverse
                )
        changed = jnp.any((dist != prev_d) | (label != prev_l))
        return dist, label, changed, it + 1

    _, label, _, asg_iters = lax.while_loop(
        cond,
        assign_body,
        (dist0, label0, jnp.bool_(True), jnp.int32(0)),
    )
    stats = {
        "flood_tile_iters": tile_iters,
        "flood_alt_iters": alt_iters,
        "flood_assign_iters": asg_iters,
    }
    return jnp.where(mask, label, 0), alt, stats


@partial(jax.jit, static_argnames=("max_iter", "per_slice", "tile"))
def _seeded_watershed_scan(
    hmap: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: jnp.ndarray,
    max_iter: int = 0,
    per_slice: bool = False,
    tile: Optional[Tuple[int, ...]] = None,
) -> jnp.ndarray:
    """Flood labels of ``_flood_scan_impl`` (the documented kernel)."""
    return _flood_scan_impl(hmap, seeds, mask, max_iter, per_slice, tile)[0]


@partial(jax.jit, static_argnames=("per_slice", "tile"))
def flood_with_stats(
    hmap: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: jnp.ndarray,
    per_slice: bool = False,
    tile: Optional[Tuple[int, ...]] = None,
):
    """``(labels, alt, stats)`` of the sweep flood — the bench/CI hook for
    the hierarchical-flood round contract (stats carries the tile/global
    fixpoint round counters; ops/cc.py is the CC analog)."""
    return _flood_scan_impl(hmap, seeds, mask, 0, per_slice, tile)


_FLOOD_TILE_ENV = "CTT_FLOOD_TILE"


def resolve_flood_tile(shape, coarse_tile=None):
    """Flood warm-start tile precedence: explicit ``coarse_tile`` >
    CTT_FLOOD_TILE env / chip_modes.json pin > None (= no tile warm start —
    unlike CC the flood default stays flat, because the production floods
    converge in <10 global rounds and the warm start pays off only where a
    global round is expensive relative to tile rounds; the ws e2e bench
    records both round counts so a chip pin can opt in)."""
    if coarse_tile is None:
        pin = _backend.pinned_value(_FLOOD_TILE_ENV)
        if pin is None:
            return None
        tile = parse_tile_spec(pin, len(shape))
        if tile is None:
            import warnings

            warnings.warn(
                f"invalid {_FLOOD_TILE_ENV}={pin!r}; tile warm start off",
                RuntimeWarning,
                stacklevel=2,
            )
            return None
        return tuple(max(1, min(int(t), int(s))) for t, s in zip(tile, shape))
    return resolve_coarse_tile(shape, coarse_tile)


@partial(jax.jit, static_argnames=("connectivity", "per_slice", "tile"))
def flood_merge_table(
    labels: jnp.ndarray,
    heights: jnp.ndarray,
    tile: Tuple[int, ...],
    connectivity: int = 1,
    per_slice: bool = False,
):
    """Tile-face region-merge table of a flooded labeling: for every
    adjacency (p, p+off) crossing a tile face, the label pair and the edge's
    saddle height max(heights[p], heights[p+off]).  Returns static-shape
    ``(a, b, saddle)`` flat arrays; slots that are not a real inter-region
    edge (background, same label, non-crossing) carry ``(0, 0, _BIG)``.

    This is the ctt-cc hierarchy hook for multi-threshold hierarchical
    segmentation (arXiv:2410.08946's merge-tree shape): thresholding
    ``saddle`` and resolving ``(a, b)`` with ops.unionfind.merge_value_table
    yields the segmentation at any coarser level WITHOUT re-flooding —
    deliberately returned raw (min-reduction per pair is the later PR's
    job).  Pass the flood's height map for basin saddles, or its altitude
    field (``flood_with_stats``) for seed-relative pass heights."""
    shape = labels.shape
    grid = _tile_grid(shape, tile)
    a_parts, b_parts, s_parts = [], [], []
    for off in _canonical_offsets(len(shape), connectivity, per_slice):
        if all(o == 0 or grid[ax] == 1 for ax, o in enumerate(off)):
            continue
        nei_l = _shift(labels, off, jnp.int32(0))
        nei_h = _shift(heights, off, _BIG)
        for slabs in tile_crossing_take(
            (labels, nei_l, heights, nei_h), off, tile, grid
        ):
            a_v, b_v, h_a, h_b = slabs
            ok = (a_v > 0) & (b_v > 0) & (a_v != b_v)
            a_parts.append(jnp.where(ok, a_v, 0))
            b_parts.append(jnp.where(ok, b_v, 0))
            s_parts.append(jnp.where(ok, jnp.maximum(h_a, h_b), _BIG))
    if not a_parts:
        z = jnp.zeros((0,), jnp.int32)
        return z, z, jnp.zeros((0,), jnp.float32)
    return (
        jnp.concatenate(a_parts),
        jnp.concatenate(b_parts),
        jnp.concatenate(s_parts),
    )


def seeded_watershed_hier(
    hmap: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    coarse_tile=None,
    per_slice: bool = False,
):
    """Hierarchical seeded flood: tile-warm-started sweep flood (labels are
    bit-identical to ``seeded_watershed``) plus the tile-face merge table of
    the result over the height map — ``(labels, (a, b, saddle), stats)``.
    The merge table + stats are the multi-threshold-segmentation and bench
    hooks; ``coarse_tile`` defaults through CTT_FLOOD_TILE then the CC
    default tile (this entry point always tiles — it IS the hierarchy)."""
    mask_arr = (
        jnp.ones(hmap.shape, dtype=bool) if mask is None
        else mask.astype(bool)
    )
    tile = resolve_flood_tile(hmap.shape, coarse_tile)
    if tile is None:
        tile = resolve_coarse_tile(hmap.shape, None)
    labels, _, stats = flood_with_stats(
        hmap, seeds, mask_arr, per_slice=per_slice, tile=tile
    )
    table = flood_merge_table(
        labels, hmap.astype(jnp.float32), tile, per_slice=per_slice
    )
    return labels, table, stats


@partial(
    jax.jit,
    static_argnames=("connectivity", "max_iter", "per_slice", "coarse_tile"),
)
def seeded_watershed(
    hmap: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    connectivity: int = 1,
    max_iter: int = 0,
    per_slice: bool = False,
    coarse_tile: Optional[Tuple[int, ...]] = None,
) -> jnp.ndarray:
    """Flood ``seeds`` (int32, 0 = unlabeled) over height map ``hmap``.

    Voxels outside ``mask`` stay 0 and do not conduct floods.  ``max_iter=0``
    iterates to the fixpoint.  ``per_slice`` floods each z-slice independently
    (the reference's 2d watershed mode, watershed.py:120-137).
    ``coarse_tile`` (or a CTT_FLOOD_TILE pin) warm-starts the sweep flood
    from tile-local fixpoints — identical labels, fewer global rounds (see
    ``_flood_scan_impl``); only the fixpoint scan path tiles (``max_iter``
    caps count global rounds, so a warm start would change their meaning).
    """
    if mask is None:
        mask_arr = jnp.ones(hmap.shape, dtype=bool)
    else:
        mask_arr = mask.astype(bool)
    if connectivity == 1:
        tile = resolve_flood_tile(hmap.shape, coarse_tile)
        if max_iter == 0:
            from .pallas_flood import (
                flood_slices,
                flood_tiles_warm,
                pallas_flood_available,
                pallas_flood_tiled_available,
            )

            if pallas_flood_available(hmap.shape, per_slice):
                # whole-slice flood in VMEM (opt-in, CTT_FLOOD_MODE=pallas)
                return flood_slices(hmap, seeds, mask_arr)
            if tile is not None and pallas_flood_tiled_available(
                hmap.shape, per_slice, tile
            ):
                # tile-local altitude fixpoints in VMEM as the phase-1 warm
                # state; the XLA loops finish the cross-tile structure
                warm = flood_tiles_warm(hmap, seeds, mask_arr, tile[1:])
                return _flood_scan_impl(
                    hmap, seeds, mask_arr, 0, per_slice, tile, warm=warm
                )[0]
            return _seeded_watershed_scan(
                hmap, seeds, mask_arr, per_slice=per_slice, tile=tile
            )
        return _seeded_watershed_scan(
            hmap, seeds, mask_arr, max_iter=max_iter, per_slice=per_slice
        )
    return _seeded_watershed_sweep(
        hmap, seeds, mask_arr, connectivity, max_iter, per_slice
    )


@partial(jax.jit, static_argnames=("connectivity", "max_iter", "per_slice"))
def _seeded_watershed_sweep(
    hmap: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: jnp.ndarray,
    connectivity: int = 1,
    max_iter: int = 0,
    per_slice: bool = False,
) -> jnp.ndarray:
    """Neighbor-sweep Bellman–Ford flood (any connectivity): one-voxel
    propagation per sweep, recomputed from neighbors (see module docstring)."""
    hmap = hmap.astype(jnp.float32)
    if mask is None:
        mask = jnp.ones(hmap.shape, dtype=bool)
    else:
        mask = mask.astype(bool)
    seeds = jnp.where(mask, seeds.astype(jnp.int32), 0)
    offsets = neighbor_offsets(hmap.ndim, connectivity, per_slice)
    is_seed = seeds > 0

    big_dist = jnp.int32(np.iinfo(np.int32).max - 1)
    label0 = seeds
    alt0 = jnp.where(is_seed, hmap, _BIG)
    dist0 = jnp.where(is_seed, 0, big_dist)

    def cond(state):
        _, _, _, changed, it = state
        return changed if max_iter == 0 else changed & (it < max_iter)

    def body(state):
        label, alt, dist, _, it = state
        # recompute purely from neighbors — own state is NOT a candidate, so
        # stale ("ghost") states cannot survive once their witness disappears
        best_alt = jnp.where(is_seed, alt0, _BIG)
        best_dist = jnp.where(is_seed, dist0, big_dist)
        best_label = jnp.where(is_seed, seeds, 0)
        for off in offsets:
            n_label = _shift(label, off, jnp.int32(0))
            n_alt = _shift(alt, off, _BIG)
            n_dist = _shift(dist, off, big_dist)
            valid = n_label > 0
            cand_alt = jnp.where(valid, jnp.maximum(n_alt, hmap), _BIG)
            cand_dist = jnp.where(valid, n_dist + 1, big_dist)
            better = (
                (cand_alt < best_alt)
                | ((cand_alt == best_alt) & (cand_dist < best_dist))
                | (
                    (cand_alt == best_alt)
                    & (cand_dist == best_dist)
                    & valid
                    & ((best_label == 0) | (n_label < best_label))
                )
            )
            better = better & ~is_seed
            best_alt = jnp.where(better, cand_alt, best_alt)
            best_dist = jnp.where(better, cand_dist, best_dist)
            best_label = jnp.where(better, n_label, best_label)
        best_label = jnp.where(mask, best_label, 0)
        best_alt = jnp.where(mask, best_alt, _BIG)
        best_dist = jnp.where(mask, best_dist, big_dist)
        changed = jnp.any(
            (best_label != label) | (best_alt != alt) | (best_dist != dist)
        )
        return best_label, best_alt, best_dist, changed, it + 1

    label, _, _, _, _ = lax.while_loop(
        cond, body, (label0, alt0, dist0, jnp.bool_(True), jnp.int32(0))
    )
    return label


@partial(jax.jit, static_argnames=("per_slice", "pixel_pitch"))
def suppress_seeds(
    maxima: jnp.ndarray,
    dt: jnp.ndarray,
    per_slice: bool = False,
    pixel_pitch: Optional[Tuple[float, ...]] = None,
) -> jnp.ndarray:
    """Distance-based non-maximum suppression of seed maxima, as one separable
    XLA program (the role of nifty.filters.nonMaximumDistanceSuppression in
    the reference seed path, watershed.py:22,200-204).

    A maximum p is suppressed iff a stronger maximum q covers it with its
    parabola: dt(q)² − ‖p−q‖² > dt(p)².  The cover field
    G(p) = max_q over maxima of (dt(q)² − ‖p−q‖²) is a separable max-parabola
    transform — the same tiled min-plus kernel as the EDT with the sign
    flipped — so the whole test is O(n·side) fully-parallel work, no pairwise
    point matrix and no data-dependent point extraction.

    Equal maxima never suppress each other (the inequality is strict), so
    plateaus survive intact and are merged by the CC pass downstream.  The
    greedy sequential semantics of the reference differ in chains of
    overlapping maxima (a suppressed point cannot suppress others there);
    parity is defined on Rand/VoI, not seed identity (SURVEY.md §7 #1).

    ``pixel_pitch`` keeps the units consistent with an anisotropic distance
    transform: dt values are then in physical units, so ‖p−q‖ must be too.
    """
    from .dt import _parabola_pass

    pitch = (1.0,) * dt.ndim if pixel_pitch is None else tuple(pixel_pitch)
    d = dt.astype(jnp.float32)
    d2 = d * d
    f = jnp.where(maxima, -d2, _BIG)  # min-form: G = -min(-f + dist²)
    axes = tuple(range(dt.ndim))
    if per_slice:
        axes = axes[1:]
    g = f
    for axis in axes:
        g = jnp.moveaxis(g, axis, -1)
        g = _parabola_pass(g, pitch[axis], 32)
        g = jnp.moveaxis(g, -1, axis)
    cover = -g
    return maxima & (cover <= d2 * (1.0 + 1e-5) + 1e-5)


@partial(jax.jit, static_argnames=("sigma", "per_slice", "nms", "pixel_pitch"))
def dt_seeds(
    dt: jnp.ndarray,
    sigma: float = 2.0,
    per_slice: bool = False,
    nms: bool = False,
    pixel_pitch: Optional[Tuple[float, ...]] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Seeds from a distance transform: smooth → local maxima (plateaus merged by
    full-connectivity CC over the maxima mask) → consecutive labels.

    Mirrors reference ``_make_seeds`` (watershed.py:180-208): gaussian(dt) then
    localMaxima with allowAtBorder/allowPlateaus.  ``per_slice`` detects maxima
    and labels seeds within each z-slice independently (2d seed mode).
    ``nms`` additionally suppresses maxima dominated by stronger nearby maxima
    (reference ``non_maximum_suppression`` config knob, watershed.py:182-204).
    """
    if sigma and sigma > 0:
        # per-slice mode smooths within slices only (reference 2d seed path)
        sig = (0.0,) + (sigma,) * (dt.ndim - 1) if per_slice else sigma
        smoothed = gaussian(dt, sig)
    else:
        smoothed = dt
    window = (1,) + (3,) * (dt.ndim - 1) if per_slice else 3
    local_max = (maximum_filter(smoothed, window) == smoothed) & (dt > 0)
    if nms:
        local_max = suppress_seeds(
            local_max, dt, per_slice=per_slice, pixel_pitch=pixel_pitch
        )
    seeds, n = connected_components(
        local_max, connectivity=dt.ndim, per_slice=per_slice
    )
    return seeds, n


@partial(
    jax.jit,
    static_argnames=(
        "threshold",
        "apply_dt_2d",
        "apply_ws_2d",
        "pixel_pitch",
        "sigma_seeds",
        "sigma_weights",
        "alpha",
        "size_filter",
        "invert_input",
        "non_maximum_suppression",
    ),
)
def dt_watershed(
    input_: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    threshold: float = 0.25,
    apply_dt_2d: bool = True,
    apply_ws_2d: bool = True,
    pixel_pitch: Optional[Tuple[float, ...]] = None,
    sigma_seeds: float = 2.0,
    sigma_weights: float = 2.0,
    alpha: float = 0.8,
    size_filter: int = 25,
    invert_input: bool = False,
    non_maximum_suppression: bool = False,
    valid: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The full per-block DT-watershed — one fused XLA program.

    threshold → distance transform (2d or 3d) → smoothed-maxima seeds
    (optionally NMS-suppressed, see ``suppress_seeds``) → height map
    α·input + (1-α)·(1-dt) → seeded flood → size filter.  Mirrors the
    reference hot loop ``_ws_block`` (watershed.py:286-344) minus IO and offsets
    (applied host-side).  Returns ``(labels int32, n_seeds)``.

    ``valid`` marks real voxels of an edge-replicate-padded block (clipped
    at volume borders, padded to the static batch shape).  The replicated
    data keeps the DT/seed/hmap fields border-faithful, but the flood and the
    size filter are restricted to ``valid``: labels never occupy padding, so
    segment voxel counts match the clipped computation — replicated copies of
    a small border fragment must not carry it over ``size_filter``.
    """
    from .dt import _distance_transform, distance_transform_2d_stack

    if pixel_pitch is not None and apply_dt_2d:
        # mirror the reference's assertion (watershed.py:149-153): anisotropic
        # pitch only applies to the 3d distance transform
        raise ValueError("pixel_pitch requires apply_dt_2d=False")

    from .pallas_dtws import pallas_dt_watershed, pallas_dtws_available

    if pallas_dtws_available(
        input_.shape, apply_dt_2d, apply_ws_2d, pixel_pitch,
        non_maximum_suppression, sigma_seeds, sigma_weights,
    ):
        # CTT_DTWS_MODE=pallas: the whole per-slice pipeline as ONE fused
        # VMEM kernel per slice — bitwise-identical labels (tested)
        return pallas_dt_watershed(
            input_, mask=mask, valid=valid, threshold=threshold,
            sigma_seeds=sigma_seeds, sigma_weights=sigma_weights,
            alpha=alpha, size_filter=size_filter, invert_input=invert_input,
        )

    x = input_.astype(jnp.float32)
    if invert_input:
        x = 1.0 - x
    fg = x < threshold
    if mask is not None:
        fg = fg & mask.astype(bool)

    if apply_dt_2d and x.ndim == 3:
        dt = distance_transform_2d_stack(fg, pixel_pitch=None)
    else:
        dt = _distance_transform(fg, pixel_pitch)

    per_slice_seeds = apply_ws_2d and x.ndim == 3
    seeds, n_seeds = dt_seeds(
        dt, sigma_seeds, per_slice=per_slice_seeds,
        nms=non_maximum_suppression, pixel_pitch=pixel_pitch,
    )
    hmap = make_hmap(x, dt, alpha, sigma_weights, per_slice=per_slice_seeds)
    flood_mask = fg if valid is None else fg & valid.astype(bool)
    labels = seeded_watershed(
        hmap, seeds, mask=flood_mask, per_slice=per_slice_seeds
    )
    if size_filter > 0:
        num_segments = int(np.prod(x.shape)) // 2 + 2
        labels = apply_size_filter(
            labels, hmap, size_filter, num_segments, mask=flood_mask,
            per_slice=per_slice_seeds,
        )
    return labels, n_seeds


@partial(
    jax.jit,
    static_argnames=(
        "threshold",
        "apply_dt_2d",
        "apply_ws_2d",
        "pixel_pitch",
        "sigma_seeds",
        "sigma_weights",
        "alpha",
        "size_filter",
        "invert_input",
        "non_maximum_suppression",
        "num_segments",
    ),
)
def two_pass_flood(
    input_: jnp.ndarray,
    written: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    valid: Optional[jnp.ndarray] = None,
    threshold: float = 0.25,
    apply_dt_2d: bool = True,
    apply_ws_2d: bool = True,
    pixel_pitch: Optional[Tuple[float, ...]] = None,
    sigma_seeds: float = 2.0,
    sigma_weights: float = 2.0,
    alpha: float = 0.8,
    size_filter: int = 25,
    invert_input: bool = False,
    non_maximum_suppression: bool = False,
    num_segments: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pass 2 of the checkerboard two-pass watershed as one fused XLA program
    (reference two_pass_watershed.py:96-99 + ``_apply_watershed_with_seeds``,
    watershed.py:128).

    ``written`` carries the already-written pass-1 neighbor labels compacted to
    1..k (0 = unwritten); this block's own DT seeds are appended *above* k on
    device, so the per-block seed count never becomes a static trace value —
    one compile serves every block, and the whole pass-2 pipeline (threshold →
    DT → seeds → hmap → flood → size filter) is a single dispatch, vmappable
    over a stacked block batch.  Returns ``(labels, k)``: flood labels where
    1..k continue written neighbor ids and values > k are new seeds in the
    block's own namespace (the host maps both back to global ids).

    ``num_segments`` (static) bounds the size-filter bincount length; the
    caller can pass a tight bound (own-seed CC ids ≤ N/2 plus written halo-
    shell voxels), default is the always-safe 2·N + 2.
    """
    from .dt import _distance_transform, distance_transform_2d_stack

    if pixel_pitch is not None and apply_dt_2d:
        # mirror dt_watershed / the reference assertion (watershed.py:149-153)
        raise ValueError("pixel_pitch requires apply_dt_2d=False")

    x = input_.astype(jnp.float32)
    if invert_input:
        x = 1.0 - x
    fg = x < threshold
    if mask is not None:
        # reference pass-2 masking (two_pass_watershed.py:236-241):
        # masked-out input is set above threshold = background for the DT
        fg = fg & mask.astype(bool)

    if apply_dt_2d and x.ndim == 3:
        dt = distance_transform_2d_stack(fg, pixel_pitch=None)
    else:
        dt = _distance_transform(fg, pixel_pitch)

    per_slice = apply_ws_2d and x.ndim == 3
    written = written.astype(jnp.int32)
    k = written.max()
    if per_slice:
        # 2d path parity: no own maxima at written voxels — the reference
        # zeroes the dt there before seed-making AND hmap construction
        # (two_pass_watershed.py:144-146)
        dt = jnp.where(written > 0, 0.0, dt)
    own_seeds, _ = dt_seeds(
        dt, sigma_seeds, per_slice=per_slice,
        nms=non_maximum_suppression, pixel_pitch=pixel_pitch,
    )
    seeds = jnp.where(
        written > 0, written, jnp.where(own_seeds > 0, own_seeds + k, 0)
    )
    hmap = make_hmap(x, dt, alpha, sigma_weights, per_slice=per_slice)
    # flood/size-filter restricted to real voxels of a padded edge block —
    # see dt_watershed's ``valid`` note
    flood_mask = fg if valid is None else fg & valid.astype(bool)
    labels = seeded_watershed(hmap, seeds, mask=flood_mask, per_slice=per_slice)
    if size_filter > 0:
        if num_segments is None:
            # always-safe bound: k ≤ #written voxels and #own seeds ≤ #fg
            # voxels, which may overlap — labels ≥ the bincount length would
            # be silently dropped (= wrongly size-filtered)
            num_segments = 2 * int(np.prod(x.shape)) + 2
        # written (initial-seed) regions are exempt from the size filter —
        # continuation labels must survive however small their overlap with
        # this block is (reference run_watershed ``exclude=initial_seed_ids``,
        # two_pass_watershed.py:166-167,205-209)
        labels = apply_size_filter(
            labels, hmap, size_filter, num_segments, mask=flood_mask,
            per_slice=per_slice, protect_upto=k,
        )
    return labels, k


@partial(jax.jit, static_argnames=("alpha", "sigma", "per_slice"))
def make_hmap(
    input_: jnp.ndarray,
    dt: jnp.ndarray,
    alpha: float,
    sigma: float = 0.0,
    per_slice: bool = False,
) -> jnp.ndarray:
    """Height map α·input + (1-α)·(1 - normalize(dt))
    (reference ``_make_hmap``, watershed.py:164-170).  ``per_slice`` normalizes
    the distances and smooths within each z-slice (2d mode)."""
    dtn = jax.vmap(normalize)(dt) if per_slice else normalize(dt)
    hmap = alpha * input_ + (1.0 - alpha) * (1.0 - dtn)
    if sigma and sigma > 0:
        sig = (0.0,) + (sigma,) * (dt.ndim - 1) if per_slice else sigma
        hmap = gaussian(hmap, sig)
    return hmap


@partial(
    jax.jit,
    static_argnames=("size_filter", "num_segments", "connectivity", "per_slice"),
)
def apply_size_filter(
    labels: jnp.ndarray,
    hmap: jnp.ndarray,
    size_filter: int,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    connectivity: int = 1,
    per_slice: bool = False,
    protect_upto: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Remove segments smaller than ``size_filter`` voxels and re-flood the freed
    voxels from the surviving segments (reference ``_apply_watershed``
    size-filter step, watershed.py:242-250).

    ``num_segments`` is the *exclusive* upper bound on label values, i.e.
    max_label + 1 (pass ``n + 1`` for labels 1..n from dt_seeds).
    ``protect_upto`` (traced scalar) exempts labels ≤ it from the filter
    (the reference ``exclude=`` seam for two-pass continuation labels)."""
    counts = jnp.bincount(labels.reshape(-1), length=num_segments)
    too_small = counts[labels] < size_filter
    if protect_upto is not None:
        too_small = too_small & (labels > protect_upto)
    kept = jnp.where(too_small, 0, labels)
    return seeded_watershed(
        hmap, kept, mask=mask, connectivity=connectivity, per_slice=per_slice
    )


def fit_to_hmap(
    objs: np.ndarray,
    hmap: np.ndarray,
    erode_by: int,
    erode_3d: bool = True,
) -> np.ndarray:
    """Refit (possibly resampled) objects to a boundary height map: erode each
    object, then re-grow all of them with a seeded watershed on a DT-blended
    height map (reference volume_utils.fit_to_hmap:336-357).

    Host wrapper: labels are compacted to int32 for the device flood and mapped
    back, so uint64 ids survive.  The per-object erosion is the min==max window
    test (a voxel is interior iff its whole window carries one label); the
    background seed is the eroded background.  Returns the refit uint64 labels.
    """
    from .dt import distance_transform
    from .filters import minimum_filter

    uniq = np.unique(objs)
    if uniq[0] != 0:
        uniq = np.concatenate([[0], uniq])
    local = np.searchsorted(uniq, objs).astype(np.int32)
    bg_id = np.int32(uniq.size)

    size = 2 * int(erode_by) + 1
    win = size if erode_3d else (1, size, size)
    labels = jnp.asarray(local)
    mn = minimum_filter(labels, win)
    mx = maximum_filter(labels, win)
    interior = (mn == mx) & (labels > 0)
    seeds = jnp.where(interior, labels, 0)
    seeds = jnp.where(mx == 0, bg_id, seeds)

    h = normalize(jnp.asarray(hmap, jnp.float32))
    dt = distance_transform(h > 0.3)
    h = 0.8 * h + 0.2 * (1.0 - normalize(dt))

    fitted_local = np.array(seeded_watershed(h, seeds))
    fitted_local[fitted_local == bg_id] = 0
    return uniq[fitted_local].astype(np.uint64)
