"""Per-object surface meshes via naive surface nets.

Replaces elf.mesh.marching_cubes (reference meshes/compute_meshes.py:29).
Surface nets is the dual method: one vertex per grid cell that the surface
crosses (placed at the mean of the cell's edge crossings), one quad per
boundary face between adjacent crossing cells, triangulated.  It produces
watertight meshes on binary masks and vectorizes cleanly over numpy — no
256-case tables.

``smooth_mesh`` is simple laplacian smoothing (the reference forwards a
``smoothing_iterations`` knob to its marching cubes)."""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def marching_cubes(
    obj: np.ndarray,
    smoothing_iterations: int = 0,
    resolution=None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Binary mask → (verts [n,3], faces [m,3] int, normals [n,3]).

    Coordinates are voxel units (scaled by ``resolution`` when given), with
    the surface at the voxel boundary between foreground and background."""
    obj = np.pad(obj.astype(bool), 1)  # close the surface at volume borders

    # a cell = a 2x2x2 voxel neighborhood; it is "active" if mixed fg/bg
    c = obj
    corners = [
        c[:-1, :-1, :-1], c[1:, :-1, :-1], c[:-1, 1:, :-1], c[1:, 1:, :-1],
        c[:-1, :-1, 1:], c[1:, :-1, 1:], c[:-1, 1:, 1:], c[1:, 1:, 1:],
    ]
    inside_count = np.sum(np.stack(corners), axis=0)
    active = (inside_count > 0) & (inside_count < 8)
    if not active.any():
        return (
            np.zeros((0, 3)),
            np.zeros((0, 3), dtype=np.int64),
            np.zeros((0, 3)),
        )

    # vertex per active cell at the centroid of its inside corners' boundary:
    # the mean of all corner positions weighted toward the crossing gives a
    # smooth placement; the simple variant (cell center) is good enough and
    # laplacian smoothing below refines it
    cell_index = np.full(active.shape, -1, dtype=np.int64)
    az, ay, ax = np.nonzero(active)
    cell_index[az, ay, ax] = np.arange(az.size)
    # position: offset -1 compensates the pad; +0.5 centers the dual vertex
    verts = np.stack([az, ay, ax], axis=1).astype(float) + 0.5 - 1.0

    faces = []
    inside_refs = []  # per triangle: the inside voxel's position (pad coords)
    # for each axis, a face sits between voxel v and v+axis where fg changes;
    # the face's 4 dual vertices are the 4 cells sharing that voxel edge
    for axis in range(3):
        lo = [slice(None)] * 3
        hi = [slice(None)] * 3
        lo[axis] = slice(None, -1)
        hi[axis] = slice(1, None)
        sign_change = c[tuple(lo)] != c[tuple(hi)]
        # voxel-face at (z,y,x)→(z+1,y,x) etc; its surrounding cells are the
        # 4 cells adjacent in the two other axes
        fz, fy, fx = np.nonzero(sign_change)
        into = c[tuple(hi)][fz, fy, fx]  # True: the +axis voxel is inside
        other = [a for a in range(3) if a != axis]
        quads = []
        for d0 in (0, 1):
            for d1 in (0, 1):
                idx = [fz.copy(), fy.copy(), fx.copy()]
                idx[other[0]] -= d0
                idx[other[1]] -= d1
                for a in range(3):
                    idx[a] = np.clip(idx[a], 0, active.shape[a] - 1)
                quads.append(cell_index[tuple(idx)])
        q00, q01, q10, q11 = quads
        valid = (q00 >= 0) & (q01 >= 0) & (q10 >= 0) & (q11 >= 0)
        q00, q01, q10, q11 = (q[valid] for q in quads)
        fl = into[valid]
        # the inside voxel center in unpadded dual coordinates: the voxel at
        # (f + e_axis if into else f), center offset -1 for pad, +0 since
        # voxel centers sit at integer coords relative to dual verts - 0.5
        base = np.stack([fz, fy, fx], axis=1)[valid].astype(float)
        ref = base.copy()
        ref[fl, axis] += 1.0
        ref -= 1.0  # pad compensation (dual verts already subtract 1)
        t1 = np.stack([q00, q01, q11], 1)
        t2 = np.stack([q00, q11, q10], 1)
        faces.append(t1)
        faces.append(t2)
        inside_refs.append(ref)
        inside_refs.append(ref)
    faces = np.concatenate(faces, axis=0)
    inside_refs = np.concatenate(inside_refs, axis=0)
    # drop degenerate triangles (repeated vertices from edge-of-volume clips)
    ok = (
        (faces[:, 0] != faces[:, 1])
        & (faces[:, 1] != faces[:, 2])
        & (faces[:, 0] != faces[:, 2])
    )
    faces = faces[ok]
    inside_refs = inside_refs[ok]
    # orient every triangle outward: its normal must point away from the
    # inside voxel it was generated from
    v0, v1, v2 = verts[faces[:, 0]], verts[faces[:, 1]], verts[faces[:, 2]]
    fn = np.cross(v1 - v0, v2 - v0)
    centroid = (v0 + v1 + v2) / 3.0
    inward = (fn * (centroid - inside_refs)).sum(axis=1) < 0
    faces[inward] = faces[inward][:, ::-1]

    if smoothing_iterations:
        verts = smooth_mesh(verts, faces, smoothing_iterations)

    normals = vertex_normals(verts, faces)
    if resolution is not None:
        verts = verts * np.asarray(resolution, dtype=float)[None]
    return verts, faces, normals


def smooth_mesh(verts: np.ndarray, faces: np.ndarray, iterations: int):
    """Uniform laplacian smoothing over the face graph."""
    if faces.size == 0 or iterations <= 0:
        return verts
    nbr_a = np.concatenate([faces[:, 0], faces[:, 1], faces[:, 2]])
    nbr_b = np.concatenate([faces[:, 1], faces[:, 2], faces[:, 0]])
    for _ in range(iterations):
        acc = np.zeros_like(verts)
        cnt = np.zeros(len(verts))
        np.add.at(acc, nbr_a, verts[nbr_b])
        np.add.at(cnt, nbr_a, 1)
        np.add.at(acc, nbr_b, verts[nbr_a])
        np.add.at(cnt, nbr_b, 1)
        moved = cnt > 0
        verts = np.where(
            moved[:, None], 0.5 * verts + 0.5 * acc / np.maximum(cnt, 1)[:, None],
            verts,
        )
    return verts


def vertex_normals(verts: np.ndarray, faces: np.ndarray) -> np.ndarray:
    normals = np.zeros_like(verts)
    if faces.size == 0:
        return normals
    v0, v1, v2 = verts[faces[:, 0]], verts[faces[:, 1]], verts[faces[:, 2]]
    fn = np.cross(v1 - v0, v2 - v0)
    for i in range(3):
        np.add.at(normals, faces[:, i], fn)
    norm = np.linalg.norm(normals, axis=1, keepdims=True)
    return normals / np.maximum(norm, 1e-12)


# -- io (reference meshes via elf.mesh.io) ------------------------------------


def write_obj(path: str, verts, faces, normals=None) -> None:
    with open(path, "w") as f:
        for v in verts:
            f.write(f"v {v[0]} {v[1]} {v[2]}\n")
        if normals is not None:
            for n in normals:
                f.write(f"vn {n[0]} {n[1]} {n[2]}\n")
        for face in faces + 1:  # obj is 1-indexed
            if normals is not None:
                f.write(
                    f"f {face[0]}//{face[0]} {face[1]}//{face[1]} "
                    f"{face[2]}//{face[2]}\n"
                )
            else:
                f.write(f"f {face[0]} {face[1]} {face[2]}\n")


def read_obj(path: str):
    verts, normals, faces = [], [], []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            if parts[0] == "v":
                verts.append([float(p) for p in parts[1:4]])
            elif parts[0] == "vn":
                normals.append([float(p) for p in parts[1:4]])
            elif parts[0] == "f":
                faces.append([int(p.split("/")[0]) - 1 for p in parts[1:4]])
    return (
        np.asarray(verts),
        np.asarray(faces, dtype=np.int64),
        np.asarray(normals) if normals else None,
    )


def write_ply(path: str, verts, faces, normals=None) -> None:
    with open(path, "w") as f:
        f.write("ply\nformat ascii 1.0\n")
        f.write(f"element vertex {len(verts)}\n")
        f.write("property float x\nproperty float y\nproperty float z\n")
        if normals is not None:
            f.write("property float nx\nproperty float ny\nproperty float nz\n")
        f.write(f"element face {len(faces)}\n")
        f.write("property list uchar int vertex_indices\nend_header\n")
        for i, v in enumerate(verts):
            row = f"{v[0]} {v[1]} {v[2]}"
            if normals is not None:
                n = normals[i]
                row += f" {n[0]} {n[1]} {n[2]}"
            f.write(row + "\n")
        for face in faces:
            f.write(f"3 {face[0]} {face[1]} {face[2]}\n")


def write_numpy(path: str, verts, faces, normals=None) -> None:
    np.savez(path, verts=verts, faces=faces,
             normals=normals if normals is not None else np.zeros((0, 3)))
