"""Euclidean distance transform as an XLA program.

Replaces vigra.filters.distanceTransform (reference watershed/watershed.py:155-159,
distances/object_distances.py:112).

The squared EDT is separable over axes as a min-plus ("parabola") reduction:

    g_axis(i) = min_j [ f(j) + pitch² · (i-j)² ]

The first axis is seeded with exact 1d line distances (two directional scans);
every further axis applies the parabola reduction.  On TPU the reduction is
evaluated as a *tiled dense min-plus product* — a (i, j) cost tile broadcast +
min-reduce, scanned over j-tiles so peak memory stays bounded — instead of the
sequential lower-envelope algorithm (Felzenszwalb), which does not vectorize.
O(n²) work per axis but fully parallel on the VPU; block side lengths are ≤512
so the constant is small.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import _backend

# numpy scalar, NOT jnp: a module-level jnp constant would initialize the
# device backend at import time (see ops/watershed.py)
_BIG = np.float32(1e10)


def _line_scan_distance(bg: jnp.ndarray, pitch: float) -> jnp.ndarray:
    """Exact 1d distance (in `pitch` units) to the nearest True along the last
    axis.  On dispatch-bound backends the directional distance is index
    arithmetic over one native ``lax.cummax``:
    d_i = pitch · (i − max_{j ≤ i, bg_j} j) — log depth, one array through the
    scan.  Work-bound XLA-CPU keeps the sequential ``lax.scan``
    (ops/_backend.py picks)."""
    if _backend.use_assoc():

        def directional(b):
            n = b.shape[-1]
            iota = jnp.arange(n, dtype=jnp.float32)
            # index of the nearest True at or before i (-BIG when none yet)
            last_bg = lax.cummax(jnp.where(b, iota, -_BIG), axis=b.ndim - 1)
            return jnp.minimum((iota - last_bg) * pitch, _BIG)

    else:

        def directional(b):
            def step(carry, is_bg):
                d = jnp.where(is_bg, 0.0, carry + pitch)
                return d, d

            init = jnp.full(b.shape[:-1], _BIG, dtype=jnp.float32)
            _, ds = lax.scan(step, init, jnp.moveaxis(b, -1, 0))
            return jnp.moveaxis(ds, 0, -1)

    fwd = directional(bg)
    bwd = jnp.flip(directional(jnp.flip(bg, -1)), -1)
    return jnp.minimum(fwd, bwd)


def _parabola_pass(f: jnp.ndarray, pitch: float, tile: int) -> jnp.ndarray:
    """g(i) = min_j f(j) + (pitch·(i-j))² along the last axis, j-tiled."""
    n = f.shape[-1]
    n_pad = -n % tile
    fp = jnp.concatenate(
        [f, jnp.full(f.shape[:-1] + (n_pad,), _BIG, f.dtype)], axis=-1
    ) if n_pad else f
    n_t = fp.shape[-1] // tile
    i_idx = jnp.arange(n, dtype=jnp.float32)
    f_tiles = jnp.moveaxis(fp.reshape(f.shape[:-1] + (n_t, tile)), -2, 0)

    def step(carry, inputs):
        f_tile, j0 = inputs  # f_tile: (..., tile)
        j_idx = j0 + jnp.arange(tile, dtype=jnp.float32)
        # cost: (..., n_i, tile)
        diff = (i_idx[:, None] - j_idx[None, :]) * pitch
        cost = f_tile[..., None, :] + diff * diff
        carry = jnp.minimum(carry, cost.min(axis=-1))
        return carry, None

    init = jnp.full(f.shape[:-1] + (n,), _BIG, f.dtype)
    j0s = (jnp.arange(n_t) * tile).astype(jnp.float32)
    out, _ = lax.scan(step, init, (f_tiles, j0s))
    return out


def distance_transform(
    fg: jnp.ndarray,
    pixel_pitch: Optional[Sequence[float]] = None,
    tile: int = 32,
) -> jnp.ndarray:
    """Euclidean distance of each True voxel to the nearest False voxel.

    ``pixel_pitch`` gives per-axis anisotropic spacing (reference ws config
    ``pixel_pitch``, watershed.py:149-159).  Matches
    scipy.ndimage.distance_transform_edt(sampling=pixel_pitch).
    """
    if pixel_pitch is not None:
        pixel_pitch = tuple(float(p) for p in pixel_pitch)
    return _distance_transform(fg, pixel_pitch, tile)


@partial(jax.jit, static_argnames=("pixel_pitch", "tile"))
def _distance_transform(
    fg: jnp.ndarray,
    pixel_pitch: Optional[Sequence[float]] = None,
    tile: int = 32,
) -> jnp.ndarray:
    ndim = fg.ndim
    pitch = (1.0,) * ndim if pixel_pitch is None else tuple(float(p) for p in pixel_pitch)
    if len(pitch) != ndim:
        raise ValueError(f"pixel_pitch must have {ndim} entries")
    bg = ~fg.astype(bool)

    # axis 0 (as last): exact line distances, squared
    x = jnp.moveaxis(bg, 0, -1)
    g = _line_scan_distance(x, pitch[0]) ** 2
    g = jnp.moveaxis(g, -1, 0)

    for axis in range(1, ndim):
        g = jnp.moveaxis(g, axis, -1)
        g = _parabola_pass(g, pitch[axis], tile)
        g = jnp.moveaxis(g, -1, axis)
    return jnp.sqrt(jnp.minimum(g, _BIG)).astype(jnp.float32)


def distance_transform_2d_stack(
    fg: jnp.ndarray, pixel_pitch: Optional[Sequence[float]] = None, tile: int = 32
) -> jnp.ndarray:
    """Per-z-slice 2d distance transform (the reference's ``two_d`` watershed
    mode, watershed.py:140-150): vmap of the 2d kernel over the stack axis."""
    pitch = None if pixel_pitch is None else tuple(pixel_pitch)
    fn = partial(distance_transform, pixel_pitch=pitch, tile=tile)
    return jax.vmap(fn)(fg)
