"""Separable image filters as XLA programs.

Replaces the reference's fastfilters / vigra filter bank
(reference utils/volume_utils.py:13-18, apply_filter:80-94).  Separable kernels are
expressed as 1d convolutions applied axis by axis — XLA fuses the padding and the
convolutions; on TPU the inner convolution vectorizes on the VPU.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Sigma = Union[float, Sequence[float]]


def _per_axis(value, ndim: int):
    if np.isscalar(value):
        return (value,) * ndim
    if len(value) != ndim:
        raise ValueError(f"expected {ndim} per-axis values, got {value}")
    return tuple(value)


def _hashable(value):
    """Sequence config values (JSON lists) → tuples so they are valid static
    jit arguments."""
    return tuple(value) if isinstance(value, (list, np.ndarray)) else value


def _gauss_kernel(sigma: float, order: int = 0, truncate: float = 4.0) -> np.ndarray:
    radius = max(int(truncate * sigma + 0.5), 1)
    x = np.arange(-radius, radius + 1, dtype=np.float64)
    k = np.exp(-0.5 * (x / sigma) ** 2)
    k /= k.sum()
    if order == 1:  # first derivative of the gaussian
        k = k * (-x / sigma**2)
    elif order == 2:
        k = k * ((x**2 / sigma**4) - 1.0 / sigma**2)
    elif order != 0:
        raise ValueError(f"unsupported derivative order {order}")
    return k.astype(np.float32)


def _conv_along_axis(x: jnp.ndarray, kernel: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Convolve with a 1d kernel along one axis, symmetric boundary."""
    radius = kernel.shape[0] // 2
    moved = jnp.moveaxis(x, axis, -1)
    batch_shape = moved.shape[:-1]
    n = moved.shape[-1]
    flat = moved.reshape(-1, 1, n)
    # symmetric padding matches vigra/scipy's default 'reflect' boundary
    flat = jnp.pad(flat, ((0, 0), (0, 0), (radius, radius)), mode="symmetric")
    out = lax.conv_general_dilated(
        flat,
        kernel[::-1].reshape(1, 1, -1),
        window_strides=(1,),
        padding="VALID",
    )
    return jnp.moveaxis(out.reshape(*batch_shape, n), -1, axis)


@partial(jax.jit, static_argnames=("sigma", "truncate"))
def _gaussian(x: jnp.ndarray, sigma, truncate: float = 4.0) -> jnp.ndarray:
    x = x.astype(jnp.float32)
    sigmas = _per_axis(sigma, x.ndim)
    for axis, s in enumerate(sigmas):
        if s and s > 0:
            x = _conv_along_axis(x, jnp.asarray(_gauss_kernel(s, 0, truncate)), axis)
    return x


def gaussian(x: jnp.ndarray, sigma: Sigma, truncate: float = 4.0) -> jnp.ndarray:
    """Gaussian smoothing (vigra.gaussianSmoothing equivalent).

    ``sigma`` may be scalar or per-axis (anisotropic volumes use e.g.
    ``(sigma/aniso, sigma, sigma)`` — reference watershed.py:174-178).
    """
    return _gaussian(x, _hashable(sigma), truncate)


def _filter_identity(dtype: np.dtype, for_min: bool):
    """Identity element of min/max for the array's dtype."""
    if dtype == jnp.bool_:
        return jnp.asarray(True if for_min else False)
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        return jnp.asarray(info.max if for_min else info.min, dtype)
    return jnp.asarray(np.inf if for_min else -np.inf, dtype)


def _window_filter(x, init, select, ndim_sizes):
    """Shared min/max filter body via reduce_window."""
    dims = tuple(ndim_sizes)
    pads = tuple(d // 2 for d in dims)
    padded = jnp.pad(
        x, tuple((p, d - 1 - p) for p, d in zip(pads, dims)), mode="symmetric"
    )
    return lax.reduce_window(
        padded, init, select, window_dimensions=dims, window_strides=(1,) * x.ndim,
        padding="VALID",
    )


@partial(jax.jit, static_argnames=("size",))
def _minimum_filter(x: jnp.ndarray, size) -> jnp.ndarray:
    sizes = _per_axis(size, x.ndim)
    return _window_filter(x, _filter_identity(x.dtype, True), lax.min, sizes)


@partial(jax.jit, static_argnames=("size",))
def _maximum_filter(x: jnp.ndarray, size) -> jnp.ndarray:
    sizes = _per_axis(size, x.ndim)
    return _window_filter(x, _filter_identity(x.dtype, False), lax.max, sizes)


def minimum_filter(x: jnp.ndarray, size: Union[int, Sequence[int]]) -> jnp.ndarray:
    """Moving-window minimum (scipy.ndimage.minimum_filter equivalent —
    reference masking/minfilter.py:110-119)."""
    return _minimum_filter(x, _hashable(size))


def maximum_filter(x: jnp.ndarray, size: Union[int, Sequence[int]]) -> jnp.ndarray:
    return _maximum_filter(x, _hashable(size))


@jax.jit
def normalize(x: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """Min-max normalize to [0, 1] (reference volume_utils.py:98-105)."""
    x = x.astype(jnp.float32)
    lo = x.min()
    hi = x.max()
    return (x - lo) / jnp.maximum(hi - lo, eps)


def normalize_input(x: jnp.ndarray) -> jnp.ndarray:
    """uint8/uint16 inputs → [0,1] floats by dtype range; floats pass through
    min-max normalize (reference `cast_type` semantics in volume_utils)."""
    if x.dtype == jnp.uint8:
        return x.astype(jnp.float32) / 255.0
    if x.dtype == jnp.uint16:
        return x.astype(jnp.float32) / 65535.0
    return normalize(x)


@partial(jax.jit, static_argnames=("sigma", "axis", "truncate"))
def gaussian_derivative(
    x: jnp.ndarray, sigma: float, axis: int = 0, truncate: float = 4.0
) -> jnp.ndarray:
    """Gaussian derivative along one axis, plain smoothing along the others."""
    x = x.astype(jnp.float32)
    for ax in range(x.ndim):
        order = 1 if ax == axis else 0
        x = _conv_along_axis(x, jnp.asarray(_gauss_kernel(sigma, order, truncate)), ax)
    return x


def gradient_magnitude(x: jnp.ndarray, sigma: float) -> jnp.ndarray:
    """Gaussian gradient magnitude (vigra.gaussianGradientMagnitude equivalent)."""
    grads = [gaussian_derivative(x, sigma, axis=ax) for ax in range(x.ndim)]
    return jnp.sqrt(sum(g * g for g in grads))


def laplacian_of_gaussian(x: jnp.ndarray, sigma: float) -> jnp.ndarray:
    """Sum of unmixed second gaussian derivatives."""
    x = x.astype(jnp.float32)
    out = jnp.zeros_like(x)
    for ax in range(x.ndim):
        y = x
        for ax2 in range(x.ndim):
            order = 2 if ax2 == ax else 0
            y = _conv_along_axis(y, jnp.asarray(_gauss_kernel(sigma, order, 4.0)), ax2)
        out = out + y
    return out


def hessian_of_gaussian_eigenvalues(x: jnp.ndarray, sigma: float) -> jnp.ndarray:
    """Eigenvalues of the gaussian hessian, sorted descending; channels last.

    Part of the reference's filter bank for edge features
    (reference features/image_filter.py)."""
    x = x.astype(jnp.float32)
    ndim = x.ndim
    hess = [[None] * ndim for _ in range(ndim)]
    for i in range(ndim):
        for j in range(i, ndim):
            y = x
            for ax in range(ndim):
                order = (1 if ax == i else 0) + (1 if ax == j else 0)
                y = _conv_along_axis(y, jnp.asarray(_gauss_kernel(sigma, order, 4.0)), ax)
            hess[i][j] = hess[j][i] = y
    H = jnp.stack([jnp.stack(row, axis=-1) for row in hess], axis=-2)
    eigs = jnp.linalg.eigvalsh(H)
    return eigs[..., ::-1]


# name → callable(x, sigma), mirroring the reference's filter-name config strings
FILTERS = {
    "gaussianSmoothing": gaussian,
    "gaussianGradientMagnitude": gradient_magnitude,
    "laplacianOfGaussian": laplacian_of_gaussian,
    "hessianOfGaussianEigenvalues": hessian_of_gaussian_eigenvalues,
}


def apply_filter(x: jnp.ndarray, filter_name: str, sigma, apply_in_2d: bool = False):
    """Filter dispatch by name (reference volume_utils.py:80-94)."""
    fn = FILTERS[filter_name]
    if apply_in_2d:
        return jax.vmap(lambda sl: fn(sl, sigma))(x)
    return fn(x, sigma)


def filter_channels(filter_name: str, ndim: int = 3, apply_in_2d: bool = False) -> int:
    """Response channels of a named filter (hessian eigenvalues are
    per-dimension, channels-last in apply_filter's output)."""
    if filter_name == "hessianOfGaussianEigenvalues":
        return 2 if apply_in_2d else ndim
    return 1
