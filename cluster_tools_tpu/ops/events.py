"""ctt-events: batched per-frame event building for hybrid pixel detectors.

The inverse workload shape to everything else in this repo (arXiv:2412.11809):
instead of one huge 3D volume, millions of tiny independent 2D frames — each
frame holds a handful of particle-hit clusters ("events") that must be found
(connected components over the above-threshold mask) and summarized (size,
total energy/ToT, energy-weighted centroid, bounding box).

The coarse-CC tile kernel (ops/cc.py, arXiv:1712.09789) is already the right
engine: frames ARE tiles.  ``_event_kernel`` runs the per-tile min-label
fixpoint from ``_coarse_cc_core`` on an ``(n_frames, h, w)`` stack — same
axis sweeps, same double pointer-jump, same live-tile early exit — and drops
the tile-face union-find entirely, because frames never merge.  Per-cluster
properties reduce in ONE ``segment_sum``-family pass per dispatch: every
pixel computes a global segment id ``frame * max_clusters + (label - 1)``
(overflow pixels dump into one trash segment) so thousands of frames'
clusters reduce together.

Sustained streams see O(log n) compiles: the host wrapper pads the frame
count and the frame shape to the next power of two (mirroring ``_pad_pow2``
in ops/hier.py) and the cluster capacity grows in pow2 steps only when a
dispatch actually overflows it.  ``threshold`` is a traced scalar — sweeping
it never recompiles.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .cc import _shift, neighbor_offsets

__all__ = [
    "PROP_FIELDS",
    "build_events",
    "build_events_np",
    "event_table",
    "kernel_cache_size",
    "DEFAULT_MAX_CLUSTERS",
]

# columns of the per-cluster property rows, in order
PROP_FIELDS = (
    "size", "energy", "cy", "cx", "ymin", "ymax", "xmin", "xmax",
)
N_PROPS = len(PROP_FIELDS)

# starting per-frame cluster capacity; grows in pow2 steps on overflow
DEFAULT_MAX_CLUSTERS = 16

# per-connectivity high-water mark of the grown cluster capacity (see
# build_events: a starting-capacity hint, never a correctness input)
_CAP_HINT: dict = {}

# floor for the compacted active-pixel budget: small/sparse batches all
# share one compile bucket instead of splitting on every occupancy
MIN_ACTIVE_BUDGET = 4096


def _next_pow2(n: int) -> int:
    size = 1
    while size < max(int(n), 1):
        size *= 2
    return size


@partial(jax.jit,
         static_argnames=("connectivity", "max_clusters", "max_active"))
def _event_kernel(
    frames: jnp.ndarray,
    threshold: jnp.ndarray,
    connectivity: int,
    max_clusters: int,
    max_active: int,
):
    """One device dispatch over an ``(n, h, w)`` float32 frame stack.

    Returns ``(labels, counts, props)``: per-frame consecutive int32 labels
    (1..k in min-flat-index order, 0 on background — the scipy raster
    order), true per-frame cluster counts (NOT capped, so the host wrapper
    can detect capacity overflow), and ``(n, max_clusters, N_PROPS)``
    float32 property rows (rows past a frame's count are zero).
    ``max_active`` is the pow2 budget of above-threshold pixels in the
    whole batch (the host wrapper counts them exactly before dispatch):
    the property pass compacts to the active pixels and reduces over
    those, never over the dense voxel grid."""
    n, h, w = frames.shape
    ts = h * w
    sent_l = jnp.int32(ts)
    mask = frames > threshold

    iota = jnp.arange(ts, dtype=jnp.int32).reshape(h, w)
    init = jnp.where(mask, jnp.broadcast_to(iota, mask.shape), sent_l)

    offsets = neighbor_offsets(2, connectivity, False)

    def tjump(lab):
        flat = lab.reshape(n, ts)
        jumped = jnp.take_along_axis(
            flat, jnp.clip(flat, 0, ts - 1), axis=1
        ).reshape(lab.shape)
        return jnp.where(mask, jumped, sent_l)

    def neigh(lab):
        # one step of min-label propagation to every mask-adjacent
        # neighbor.  8-connectivity is the full 3x3 window, so the min
        # separates into a row pass then a column pass — 4 shifts
        # instead of 8 (off-mask pixels hold the sentinel, so they
        # contribute nothing, and the final where restores them)
        if connectivity >= 2:
            r = jnp.minimum(lab, jnp.minimum(
                _shift(lab, (0, 0, 1), sent_l),
                _shift(lab, (0, 0, -1), sent_l),
            ))
            best = jnp.minimum(r, jnp.minimum(
                _shift(r, (0, 1, 0), sent_l),
                _shift(r, (0, -1, 0), sent_l),
            ))
        else:
            best = lab
            for off in offsets:
                for sgn in (1, -1):
                    best = jnp.minimum(best, _shift(
                        lab, (0, sgn * off[0], sgn * off[1]), sent_l
                    ))
        return jnp.where(mask, best, sent_l)

    def one_round(lab):
        # three propagation sweeps then two pointer-doubling jumps:
        # every step is an elementwise shift/min or a gather — no
        # scans, so a round costs O(voxels) on any backend and the
        # fixpoint converges in O(log diameter) rounds for the compact
        # clusters detector frames actually contain (the while_loop
        # still guards arbitrary shapes).  The 3-sweep/2-jump mix
        # minimizes measured wall time per unit of label progress.
        return tjump(tjump(neigh(neigh(neigh(lab)))))

    def cond(state):
        return state[1]

    def body(state):
        lab, _ = state
        new = one_round(lab)
        return new, jnp.any(new != lab)

    lab, _ = lax.while_loop(cond, body, (init, jnp.bool_(True)))

    # per-frame consecutive labels: a component's representative is the
    # pixel whose local id equals its label (the min flat index); ranking
    # roots by cumsum gives 1-based labels in raster order of first
    # appearance — exactly scipy.ndimage.label's order
    flat = lab.reshape(n, ts)
    is_root = flat == jnp.arange(ts, dtype=jnp.int32)[None, :]
    rank = jnp.cumsum(is_root.astype(jnp.int32), axis=1)
    counts = rank[:, -1]
    safe = jnp.clip(flat, 0, ts - 1)
    labels = jnp.where(
        flat == sent_l,
        jnp.int32(0),
        jnp.take_along_axis(rank, safe, axis=1),
    ).reshape(n, h, w)

    # property pass over the COMPACTED active pixels: one O(voxels)
    # nonzero-compaction (static budget, pow2-bucketed like every other
    # shape here), then every reduction runs over max_active elements —
    # at detector occupancies that is 1-2 orders of magnitude less
    # scatter traffic than a dense segment pass
    cap = max_clusters
    total = n * ts
    sel = jnp.nonzero(
        mask.reshape(-1), size=max_active, fill_value=total
    )[0]
    valid = sel < total
    safe_sel = jnp.where(valid, sel, 0)
    lab_sel = labels.reshape(-1)[safe_sel]
    frame_sel = (safe_sel // ts).astype(jnp.int32)
    pix = (safe_sel % ts).astype(jnp.int32)
    yy = (pix // w).astype(jnp.float32)
    xx = (pix % w).astype(jnp.float32)
    e = frames.reshape(-1)[safe_sel]
    one = jnp.ones_like(e)

    # padded / over-cap entries dump into the trash segment at n * cap
    in_seg = valid & (lab_sel > 0) & (lab_sel <= cap)
    gid = jnp.where(
        in_seg, frame_sel * cap + (lab_sel - 1), jnp.int32(n * cap)
    )
    num_segments = n * cap + 1

    # ONE scatter-add pass for every summed property (stacked columns)
    # and one fused segment_min for the bbox (maxima as negated minima)
    sums = jax.ops.segment_sum(
        jnp.stack([one, e, yy * e, xx * e, yy, xx], axis=-1),
        gid, num_segments,
    )[:-1]
    size, energy, wy, wx, sy, sx = (sums[:, i] for i in range(6))
    big = jnp.float32(ts)
    pos = jnp.stack([yy, xx, -yy, -xx], axis=-1)
    mins = jax.ops.segment_min(
        jnp.where(in_seg[:, None], pos, big), gid, num_segments
    )[:-1]
    ymin, xmin = mins[:, 0], mins[:, 1]
    ymax, xmax = -mins[:, 2], -mins[:, 3]

    # energy-weighted centroid (the ToT center of gravity); zero-energy
    # clusters (possible at negative thresholds) fall back to the
    # unweighted pixel mean so the division stays finite
    denom = jnp.where(energy != 0, energy, jnp.float32(1.0))
    nsize = jnp.where(size > 0, size, jnp.float32(1.0))
    cy = jnp.where(energy != 0, wy / denom, sy / nsize)
    cx = jnp.where(energy != 0, wx / denom, sx / nsize)

    props = jnp.stack(
        [size, energy, cy, cx, ymin, ymax, xmin, xmax], axis=-1
    ).reshape(n, cap, N_PROPS)
    props = jnp.where(size.reshape(n, cap, 1) > 0, props, 0.0)
    return labels, counts, props


def kernel_cache_size() -> int:
    """Distinct compiled programs of the event kernel in this process —
    the pow2 bucketing makes this O(log n_frames) under a sustained
    stream; tests assert on it."""
    return int(_event_kernel._cache_size())


def _pad_frames(frames: np.ndarray, threshold: float) -> np.ndarray:
    """Pow2-pad all three axes with sub-threshold fill (strict ``>`` means
    the fill never masks in), so a sustained ragged stream reuses a
    handful of compiled shapes."""
    n, h, w = frames.shape
    pn, ph, pw = _next_pow2(n), _next_pow2(h), _next_pow2(w)
    if (pn, ph, pw) == (n, h, w):
        return frames
    out = np.full((pn, ph, pw), threshold, dtype=np.float32)
    out[:n, :h, :w] = frames
    return out


def build_events(
    frames,
    threshold: float = 0.0,
    connectivity: int = 2,
    max_clusters: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host wrapper: batched event building over a stack of frames.

    ``frames``: ``(n, h, w)`` (or one ``(h, w)`` frame).  Returns
    ``(labels, counts, props)`` cropped to the real frame count: uint32
    per-frame consecutive labels, int32 per-frame cluster counts, and
    ``(n, max_count, N_PROPS)`` float32 property rows (:data:`PROP_FIELDS`
    order; rows past ``counts[f]`` are zero).

    Dispatches ONE jitted program per pow2 shape bucket; the per-frame
    cluster capacity auto-grows (pow2 steps) and re-dispatches when a
    batch overflows it.  Emits the ``events.*`` obs counters — metric
    emission must stay outside jit (CTT001/CTT002), which is why the
    kernel itself cannot do it."""
    from ..obs import metrics as obs_metrics

    frames = np.asarray(frames, dtype=np.float32)
    if frames.ndim == 2:
        frames = frames[None]
    if frames.ndim != 3:
        raise ValueError(f"frames must be (n, h, w), got {frames.shape}")
    n, h, w = frames.shape
    if n == 0:
        return (
            np.zeros((0, h, w), np.uint32),
            np.zeros((0,), np.int32),
            np.zeros((0, 0, N_PROPS), np.float32),
        )
    padded = _pad_frames(frames, float(threshold))

    # ``max_clusters`` is a STARTING capacity, not a limit (overflow
    # regrows below); starting from the process-level hint means a warm
    # stream whose cluster density exceeded the default once pays the
    # regrow re-dispatch once, not on every batch
    cap = _next_pow2(max(
        max_clusters or DEFAULT_MAX_CLUSTERS,
        _CAP_HINT.get(int(connectivity), 1),
    ))
    # exact active-pixel count (cheap host-side reduction) sized up to a
    # pow2 budget with a floor, so the compacted property pass reduces
    # over the occupied pixels only while keeping compile buckets coarse
    active = int((padded > float(threshold)).sum())
    max_active = _next_pow2(max(active, MIN_ACTIVE_BUDGET))
    thr = jnp.float32(threshold)
    while True:
        labels, counts, props = _event_kernel(
            padded, thr, int(connectivity), cap, max_active
        )
        obs_metrics.inc("events.batches")
        observed = int(jnp.max(counts)) if counts.size else 0
        if observed <= cap:
            break
        # capacity overflow: grow to the next pow2 that fits and redo the
        # dispatch — rare (once per regime change), and the pow2 step
        # keeps the compile count logarithmic in the true cluster density
        cap = _next_pow2(observed)
    _CAP_HINT[int(connectivity)] = max(
        _CAP_HINT.get(int(connectivity), 1), cap
    )

    labels = np.asarray(labels)[:n, :h, :w].astype(np.uint32)
    counts = np.asarray(counts)[:n]
    max_count = int(counts.max()) if n else 0
    props = np.asarray(props)[:n, :max_count]
    obs_metrics.inc("events.frames", n)
    obs_metrics.inc("events.clusters", int(counts.sum()))
    return labels, counts, props


def event_table(counts: np.ndarray, props: np.ndarray) -> np.ndarray:
    """Flatten per-frame property rows into one ``(total_clusters, 1 +
    N_PROPS)`` float64 table with the frame index prepended — the row
    format the ragged per-block event datasets store."""
    rows = []
    for f, k in enumerate(np.asarray(counts)):
        k = int(k)
        if k == 0:
            continue
        block = np.empty((k, 1 + N_PROPS), np.float64)
        block[:, 0] = f
        block[:, 1:] = props[f, :k]
        rows.append(block)
    if not rows:
        return np.zeros((0, 1 + N_PROPS), np.float64)
    return np.concatenate(rows, axis=0)


def build_events_np(
    frames,
    threshold: float = 0.0,
    connectivity: int = 2,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """The host oracle: per-frame ``scipy.ndimage.label`` + numpy property
    reduction, same return contract as :func:`build_events`.  This is both
    the parity reference and the bench baseline (the per-frame host loop
    the batched dispatch is measured against)."""
    from scipy import ndimage

    frames = np.asarray(frames, dtype=np.float32)
    if frames.ndim == 2:
        frames = frames[None]
    n, h, w = frames.shape
    structure = ndimage.generate_binary_structure(2, connectivity)
    labels = np.zeros((n, h, w), np.uint32)
    counts = np.zeros((n,), np.int32)
    per_frame = []
    for f in range(n):
        lab, k = ndimage.label(frames[f] > threshold, structure=structure)
        labels[f] = lab
        counts[f] = k
        rows = np.zeros((k, N_PROPS), np.float32)
        for c in range(1, k + 1):
            ys, xs = np.nonzero(lab == c)
            e = frames[f][ys, xs].astype(np.float64)
            etot = float(e.sum())
            if etot != 0:
                cy, cx = float((ys * e).sum() / etot), float((xs * e).sum() / etot)
            else:
                cy, cx = float(ys.mean()), float(xs.mean())
            rows[c - 1] = (
                len(ys), etot, cy, cx,
                ys.min(), ys.max(), xs.min(), xs.max(),
            )
        per_frame.append(rows)
    max_count = int(counts.max()) if n else 0
    props = np.zeros((n, max_count, N_PROPS), np.float32)
    for f, rows in enumerate(per_frame):
        props[f, : len(rows)] = rows
    return labels, counts, props
