"""Region adjacency graph extraction and edge-feature accumulation.

Replaces nifty.distributed's graph/feature layer (SURVEY.md §2.10:
computeMergeableRegionGraph, extractBlockFeaturesFromBoundaryMaps,
mergeFeatureBlocks, Graph).

Design: face-pair extraction is vectorized (adjacent-voxel label pairs per
axis); uniquing and per-edge statistics run as sort-based host reductions
(np.lexsort + reduceat) — the data is ragged (edge lists vary per block), which
is exactly what the host handles while the device does the dense voxel work.

Edge features (10 per edge, the reference's default feature width —
block_edge_features.py:146-148):
  [mean, variance, min, q10, q25, q50, q75, q90, max, count]
accumulated over the boundary-map values sampled on both sides of each label
face.  Cross-block merging combines (count, mean, var, min, max) exactly and
quantiles by count-weighted mean (documented approximation — exact global
quantiles would require keeping all samples).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

N_FEATURES = 10


def block_edges(labels: np.ndarray, ignore_zero: bool = True) -> np.ndarray:
    """Unique adjacent label pairs (u < v) over face-neighbor voxels."""
    pairs = []
    for axis in range(labels.ndim):
        lo = np.moveaxis(labels, axis, 0)[:-1].reshape(-1)
        hi = np.moveaxis(labels, axis, 0)[1:].reshape(-1)
        sel = lo != hi
        if ignore_zero:
            sel &= (lo != 0) & (hi != 0)
        if sel.any():
            a, b = lo[sel], hi[sel]
            pairs.append(np.stack([np.minimum(a, b), np.maximum(a, b)], axis=1))
    if not pairs:
        return np.zeros((0, 2), dtype=labels.dtype)
    return np.unique(np.concatenate(pairs, axis=0), axis=0)


def _face_values(labels: np.ndarray, values: np.ndarray):
    """(u, v, sample) triples: for every face between two different labels, the
    boundary-map values on both sides of the face."""
    us, vs, samples = [], [], []
    for axis in range(labels.ndim):
        lab0 = np.moveaxis(labels, axis, 0)
        val0 = np.moveaxis(values, axis, 0)
        lo, hi = lab0[:-1].reshape(-1), lab0[1:].reshape(-1)
        vlo, vhi = val0[:-1].reshape(-1), val0[1:].reshape(-1)
        sel = (lo != hi) & (lo != 0) & (hi != 0)
        if not sel.any():
            continue
        a = np.minimum(lo[sel], hi[sel])
        b = np.maximum(lo[sel], hi[sel])
        # both side values are samples of the boundary evidence for this edge
        us.append(np.concatenate([a, a]))
        vs.append(np.concatenate([b, b]))
        samples.append(np.concatenate([vlo[sel], vhi[sel]]))
    if not us:
        return (
            np.zeros(0, dtype=labels.dtype),
            np.zeros(0, dtype=labels.dtype),
            np.zeros(0, dtype=np.float64),
        )
    return np.concatenate(us), np.concatenate(vs), np.concatenate(samples)


def boundary_edge_features(
    labels: np.ndarray, boundary_map: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-edge feature matrix over the label faces of one block.

    Returns ``(edges [m,2], features [m,10])`` with edges sorted lexicographically.
    """
    u, v, s = _face_values(labels, boundary_map.astype(np.float64))
    if u.size == 0:
        return np.zeros((0, 2), dtype=labels.dtype), np.zeros((0, N_FEATURES))
    order = np.lexsort((s, v, u))
    u, v, s = u[order], v[order], s[order]
    first = np.concatenate([[True], (u[1:] != u[:-1]) | (v[1:] != v[:-1])])
    starts = np.nonzero(first)[0]
    edges = np.stack([u[starts], v[starts]], axis=1)
    counts = np.diff(np.append(starts, u.size)).astype(np.float64)

    sums = np.add.reduceat(s, starts)
    sums2 = np.add.reduceat(s * s, starts)
    mean = sums / counts
    var = np.maximum(sums2 / counts - mean**2, 0.0)
    mins = np.minimum.reduceat(s, starts)
    maxs = np.maximum.reduceat(s, starts)
    # quantiles: values are sorted within each edge group (lexsort key order)
    qs = []
    for q in (0.1, 0.25, 0.5, 0.75, 0.9):
        pos = starts + np.minimum(
            (q * (counts - 1)).astype(np.int64), (counts - 1).astype(np.int64)
        )
        qs.append(s[pos])
    feats = np.stack([mean, var, mins, qs[0], qs[1], qs[2], qs[3], qs[4], maxs, counts], axis=1)
    return edges, feats


def affinity_edge_features(
    labels: np.ndarray, affs: np.ndarray, offsets: Sequence[Sequence[int]]
) -> Tuple[np.ndarray, np.ndarray]:
    """Edge features from an affinity map [C, *spatial] with per-channel offsets
    (reference extractBlockFeaturesFromAffinityMaps).  Samples the affinity
    value at the source voxel of each offset-crossing label pair."""
    offsets = np.asarray(offsets, dtype=np.int64)
    us, vs, samples = [], [], []
    for c, off in enumerate(offsets):
        src = tuple(
            slice(max(-o, 0), s - max(o, 0)) for o, s in zip(off, labels.shape)
        )
        dst = tuple(
            slice(max(o, 0), s - max(-o, 0)) for o, s in zip(off, labels.shape)
        )
        lo, hi = labels[src].reshape(-1), labels[dst].reshape(-1)
        val = affs[c][src].reshape(-1).astype(np.float64)
        sel = (lo != hi) & (lo != 0) & (hi != 0)
        if sel.any():
            us.append(np.minimum(lo[sel], hi[sel]))
            vs.append(np.maximum(lo[sel], hi[sel]))
            samples.append(val[sel])
    if not us:
        return np.zeros((0, 2), dtype=labels.dtype), np.zeros((0, N_FEATURES))
    u = np.concatenate(us)
    v = np.concatenate(vs)
    s = np.concatenate(samples)
    order = np.lexsort((s, v, u))
    u, v, s = u[order], v[order], s[order]
    first = np.concatenate([[True], (u[1:] != u[:-1]) | (v[1:] != v[:-1])])
    starts = np.nonzero(first)[0]
    edges = np.stack([u[starts], v[starts]], axis=1)
    counts = np.diff(np.append(starts, u.size)).astype(np.float64)
    sums = np.add.reduceat(s, starts)
    sums2 = np.add.reduceat(s * s, starts)
    mean = sums / counts
    var = np.maximum(sums2 / counts - mean**2, 0.0)
    mins = np.minimum.reduceat(s, starts)
    maxs = np.maximum.reduceat(s, starts)
    qs = []
    for q in (0.1, 0.25, 0.5, 0.75, 0.9):
        pos = starts + np.minimum(
            (q * (counts - 1)).astype(np.int64), (counts - 1).astype(np.int64)
        )
        qs.append(s[pos])
    feats = np.stack(
        [mean, var, mins, qs[0], qs[1], qs[2], qs[3], qs[4], maxs, counts], axis=1
    )
    return edges, feats


def merge_edge_features(
    edge_ids_list: Sequence[np.ndarray], feats_list: Sequence[np.ndarray], n_edges: int
) -> np.ndarray:
    """Merge per-block partial features into the global [n_edges, 10] matrix.

    count/mean/var/min/max merge exactly (parallel-variance formula); quantile
    columns merge by count-weighted average (approximation, see module doc).
    """
    out = np.zeros((n_edges, N_FEATURES))
    count = np.zeros(n_edges)
    mean = np.zeros(n_edges)
    m2 = np.zeros(n_edges)
    mins = np.full(n_edges, np.inf)
    maxs = np.full(n_edges, -np.inf)
    qsum = np.zeros((n_edges, 5))

    for ids, feats in zip(edge_ids_list, feats_list):
        if ids.size == 0:
            continue
        c = feats[:, 9]
        m = feats[:, 0]
        v = feats[:, 1]
        tot = count[ids] + c
        delta = m - mean[ids]
        m2[ids] += v * c + delta**2 * count[ids] * c / np.maximum(tot, 1)
        mean[ids] += delta * c / np.maximum(tot, 1)
        count[ids] = tot
        mins[ids] = np.minimum(mins[ids], feats[:, 2])
        maxs[ids] = np.maximum(maxs[ids], feats[:, 8])
        qsum[ids] += feats[:, 3:8] * c[:, None]

    nonzero = count > 0
    out[:, 0] = mean
    out[:, 1] = np.where(nonzero, m2 / np.maximum(count, 1), 0.0)
    out[:, 2] = np.where(nonzero, mins, 0.0)
    out[:, 3:8] = qsum / np.maximum(count, 1)[:, None]
    out[:, 8] = np.where(nonzero, maxs, 0.0)
    out[:, 9] = count
    return out