"""Region adjacency graph extraction and edge-feature accumulation.

Replaces nifty.distributed's graph/feature layer (SURVEY.md §2.10:
computeMergeableRegionGraph, extractBlockFeaturesFromBoundaryMaps,
mergeFeatureBlocks, Graph).

Design: face-pair extraction is vectorized (adjacent-voxel label pairs per
axis); uniquing and per-edge statistics run as sort-based host reductions
(np.lexsort + reduceat) — the data is ragged (edge lists vary per block), which
is exactly what the host handles while the device does the dense voxel work.

Edge features (10 per edge, the reference's default feature width —
block_edge_features.py:146-148):
  [mean, variance, min, q10, q25, q50, q75, q90, max, count]
accumulated over the boundary-map values sampled on both sides of each label
face.  Cross-block merging combines (count, mean, var, min, max) exactly;
quantiles merge through a per-edge ``HIST_BINS``-bin histogram sketch over the
normalized [0, 1] value range (block partials carry the bin counts), so the
merged quantile error is bounded by one bin width with linear interpolation —
the mergeable-sketch answer to the reference's exact
``ndist.mergeFeatureBlocks`` (merge_edge_features.py:141).  Partials without
histogram columns fall back to count-weighted quantile averaging.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional, Sequence, Tuple

import numpy as np

N_FEATURES = 10
HIST_BINS = 64
QUANTILES = (0.1, 0.25, 0.5, 0.75, 0.9)

# largest label id the single-int32-key sort packing can carry: the packed
# key u*PACK_SHIFT+v of the worst pair (PACK_MAX_ID, PACK_SHIFT-1) must stay
# strictly below the int32-max sentinel.  ONE definition — every pack,
# unpack, and gate site must agree or edge endpoints corrupt silently.
PACK_SHIFT = 65536
PACK_MAX_ID = 32766


def compact_valid_rows(u, v, s, max_samples, sentinel):
    """Static-capacity compaction of valid (u != sentinel) face rows BEFORE
    the dominant sort: only ~a quarter of the rows are real label-boundary
    samples at CREMI-like boundary densities, and sentinel rows cost the
    same to sort as real ones (measured on the 32x256x256 bench block, CPU
    fallback: 12.4M rows -> 3.5M valid; pack+sort 5.2 s -> the whole kernel
    lands near 1-core numpy).  A stable cumsum/scatter keeps row order;
    rows beyond the cap are dropped by scatter 'drop' mode — callers
    compare the pre-compaction valid count against the cap and raise
    rather than silently lose samples.  Shared by the single-device and
    the sharded (per-shard) kernels."""
    import jax.numpy as jnp

    valid0 = u != sentinel
    dest = jnp.where(
        valid0, jnp.cumsum(valid0.astype(jnp.int32)) - 1,
        jnp.int32(max_samples),
    )
    u = jnp.full((max_samples,), sentinel, u.dtype).at[dest].set(u, mode="drop")
    v = jnp.full((max_samples,), sentinel, v.dtype).at[dest].set(v, mode="drop")
    s = jnp.zeros((max_samples,), s.dtype).at[dest].set(s, mode="drop")
    return u, v, s


def pack_uv(u, v, sentinel):
    """Order-preserving single-int32 key for (u, v) pairs (u ≤ v ≤
    PACK_MAX_ID); sentinel rows stay the sentinel (sort last).

    Sentinel endpoints are masked to 0 BEFORE the multiply: packing the
    sentinel itself would overflow int32, and while XLA wraps
    deterministically, relying on wrap semantics would trip any future
    overflow checking."""
    import jax.numpy as jnp

    ok = u != sentinel
    packed = (
        jnp.where(ok, u, 0) * jnp.int32(PACK_SHIFT) + jnp.where(ok, v, 0)
    )
    return jnp.where(ok, packed, sentinel)


def unpack_uv(p, sentinel):
    """Inverse of ``pack_uv``: (u, v) per key, sentinel rows stay sentinel."""
    import jax.numpy as jnp

    ok = p != sentinel
    return (
        jnp.where(ok, p // jnp.int32(PACK_SHIFT), sentinel),
        jnp.where(ok, p % jnp.int32(PACK_SHIFT), sentinel),
    )


def block_edges(labels: np.ndarray, ignore_zero: bool = True) -> np.ndarray:
    """Unique adjacent label pairs (u < v) over face-neighbor voxels."""
    pairs = []
    for axis in range(labels.ndim):
        lo = np.moveaxis(labels, axis, 0)[:-1].reshape(-1)
        hi = np.moveaxis(labels, axis, 0)[1:].reshape(-1)
        sel = lo != hi
        if ignore_zero:
            sel &= (lo != 0) & (hi != 0)
        if sel.any():
            a, b = lo[sel], hi[sel]
            pairs.append(np.stack([np.minimum(a, b), np.maximum(a, b)], axis=1))
    if not pairs:
        return np.zeros((0, 2), dtype=labels.dtype)
    return np.unique(np.concatenate(pairs, axis=0), axis=0)


def _owner_mask(shape, owner_shape) -> Optional[np.ndarray]:
    """True where a voxel lies inside the owning (inner) block region.

    Blocks read a +1 upper halo so cross-block faces are seen; a face is
    *owned* by the block containing its lower voxel.  Without this mask the
    orthogonal faces inside the halo slabs are accumulated by both adjacent
    blocks, double-counting their samples in the merged features."""
    if owner_shape is None:
        return None
    owned = np.ones(shape, dtype=bool)
    for d, s in enumerate(owner_shape):
        owned[(slice(None),) * d + (slice(s, None),)] = False
    return owned


def _face_values(
    labels: np.ndarray, values: np.ndarray, owner_shape=None
):
    """(u, v, sample) triples: for every face between two different labels, the
    boundary-map values on both sides of the face.  A thin gather over
    ``face_sample_indices`` — the owned-face rule lives there, once."""
    u, v, ilo, ihi = face_sample_indices(labels, owner_shape)
    flat = values.reshape(-1)
    return (
        np.concatenate([u, u]),
        np.concatenate([v, v]),
        np.concatenate([flat[ilo], flat[ihi]]).astype(np.float64),
    )


def _edge_group_features(u, v, s, dtype, hist_bins: int = 0,
                         return_samples: bool = False):
    """Shared per-edge statistics over (u, v, sample) triples.

    Returns ``(edges [m,2], features [m,10])`` with edges sorted
    lexicographically — or ``(edges, features, hist [m,hist_bins] uint32)``
    when ``hist_bins > 0``: the per-edge histogram of the samples (assumed in
    [0, 1], clipped), the compact mergeable quantile sketch consumed by
    ``merge_edge_features``.  With ``return_samples`` the per-edge sorted
    sample vector (edge-major, spans given by the count column) is appended —
    the raw material of the exact cross-block quantile merge.
    """
    if u.size == 0:
        empty = (
            np.zeros((0, 2), dtype=dtype),
            np.zeros((0, N_FEATURES)),
        )
        if hist_bins:
            empty = empty + (np.zeros((0, hist_bins), dtype=np.uint32),)
        if return_samples:
            empty = empty + (np.zeros(0, dtype=np.float64),)
        return empty
    order = np.lexsort((s, v, u))
    u, v, s = u[order], v[order], s[order]
    first = np.concatenate([[True], (u[1:] != u[:-1]) | (v[1:] != v[:-1])])
    starts = np.nonzero(first)[0]
    edges = np.stack([u[starts], v[starts]], axis=1)
    counts = np.diff(np.append(starts, u.size)).astype(np.float64)

    sums = np.add.reduceat(s, starts)
    sums2 = np.add.reduceat(s * s, starts)
    mean = sums / counts
    var = np.maximum(sums2 / counts - mean**2, 0.0)
    mins = np.minimum.reduceat(s, starts)
    maxs = np.maximum.reduceat(s, starts)
    # quantiles: values are sorted within each edge group (lexsort key order)
    qs = []
    for q in QUANTILES:
        pos = starts + np.minimum(
            (q * (counts - 1)).astype(np.int64), (counts - 1).astype(np.int64)
        )
        qs.append(s[pos])
    cols = [mean, var, mins, *qs, maxs, counts]
    feats = np.stack(cols, axis=1)
    out = (edges, feats)
    if hist_bins:
        group = np.cumsum(first) - 1
        bins = np.clip((s * hist_bins).astype(np.int64), 0, hist_bins - 1)
        hist = np.bincount(
            group * hist_bins + bins, minlength=edges.shape[0] * hist_bins
        ).reshape(edges.shape[0], hist_bins).astype(np.uint32)
        out = out + (hist,)
    if return_samples:
        out = out + (s,)
    return out


def boundary_edge_features(
    labels: np.ndarray,
    boundary_map: np.ndarray,
    hist_bins: int = 0,
    owner_shape=None,
    return_samples: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-edge feature matrix over the label faces of one block.

    ``owner_shape`` restricts accumulation to faces owned by the inner block
    when ``labels`` carries a +1 upper halo (see ``_owner_mask``); with
    ``hist_bins > 0`` a third return carries the per-edge histogram sketch;
    with ``return_samples`` the last return is the per-edge sorted sample
    vector (exact quantile-merge partials)."""
    u, v, s = _face_values(
        labels, boundary_map.astype(np.float64), owner_shape
    )
    return _edge_group_features(
        u, v, s, labels.dtype, hist_bins, return_samples
    )


def face_sample_indices(labels: np.ndarray, owner_shape=None):
    """Face geometry computed once, shared across value channels.

    Returns ``(u, v, ilo, ihi)``: for every owned face between two different
    non-zero labels, the label pair (u < v) and the flat indices of the two
    face voxels into ``labels.ravel()``.  A channel's (u, v, sample) triples
    are then ``(cat(u, u), cat(v, v), cat(vals.flat[ilo], vals.flat[ihi]))`` —
    both sides of a face sample the boundary evidence, exactly as
    ``_face_values`` does."""
    owned = _owner_mask(labels.shape, owner_shape)
    flat_idx = np.arange(labels.size, dtype=np.int64).reshape(labels.shape)
    us, vs, ilos, ihis = [], [], [], []
    for axis in range(labels.ndim):
        lab0 = np.moveaxis(labels, axis, 0)
        idx0 = np.moveaxis(flat_idx, axis, 0)
        lo, hi = lab0[:-1].reshape(-1), lab0[1:].reshape(-1)
        sel = (lo != hi) & (lo != 0) & (hi != 0)
        if owned is not None:
            sel &= np.moveaxis(owned, axis, 0)[:-1].reshape(-1)
        if not sel.any():
            continue
        us.append(np.minimum(lo[sel], hi[sel]))
        vs.append(np.maximum(lo[sel], hi[sel]))
        ilos.append(idx0[:-1].reshape(-1)[sel])
        ihis.append(idx0[1:].reshape(-1)[sel])
    if not us:
        z = np.zeros(0, dtype=np.int64)
        return np.zeros(0, dtype=labels.dtype), np.zeros(0, dtype=labels.dtype), z, z
    return (
        np.concatenate(us), np.concatenate(vs),
        np.concatenate(ilos), np.concatenate(ihis),
    )


def filter_edge_features(
    labels: np.ndarray,
    responses: Sequence[np.ndarray],
    owner_shape=None,
    return_samples: bool = False,
):
    """Edge features over a bank of filter responses (the reference's
    filter-accumulation path, block_edge_features.py:151-238 via
    ndist.accumulateInput): 9 statistics [mean, var, min, q10, q25, q50,
    q75, q90, max] per response channel plus ONE trailing count column.

    ``responses`` are label-shaped float arrays (one per filter × sigma ×
    channel, the caller's flattening of multichannel filters).  Returns
    ``(edges [m,2], feats [m, 9*G+1])`` and, with ``return_samples``, the
    group-major flat sample array ``[G * total_count]`` (each group's
    samples edge-major sorted — the exact-merge partials consumed by
    ``merge_edge_features_multi``)."""
    G = len(responses)
    u0, v0, ilo, ihi = face_sample_indices(labels, owner_shape)
    u = np.concatenate([u0, u0])
    v = np.concatenate([v0, v0])
    edges = None
    feat_groups, sample_groups = [], []
    count = None
    for resp in responses:
        if resp.shape != labels.shape:
            raise ValueError(
                f"response shape {resp.shape} != labels shape {labels.shape}"
            )
        flat = resp.reshape(-1).astype(np.float64)
        s = np.concatenate([flat[ilo], flat[ihi]])
        e, f, samp = _edge_group_features(
            u, v, s, labels.dtype, 0, return_samples=True
        )
        if edges is None:
            edges = e
            count = f[:, 9]
        feat_groups.append(f[:, :9])
        if return_samples:
            sample_groups.append(samp)
    if edges is None or edges.shape[0] == 0:
        feats = np.zeros((0, 9 * G + 1))
        if return_samples:
            return np.zeros((0, 2), dtype=labels.dtype), feats, np.zeros(0)
        return np.zeros((0, 2), dtype=labels.dtype), feats
    feats = np.concatenate(feat_groups + [count[:, None]], axis=1)
    if return_samples:
        return edges, feats, np.concatenate(sample_groups)
    return edges, feats


def affinity_edge_features(
    labels: np.ndarray,
    affs: np.ndarray,
    offsets: Sequence[Sequence[int]],
    hist_bins: int = 0,
    owner_shape=None,
    return_samples: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Edge features from an affinity map [C, *spatial] with per-channel offsets
    (reference extractBlockFeaturesFromAffinityMaps).  Samples the affinity
    value at the source voxel of each offset-crossing label pair.

    With ``owner_shape`` a pair is accumulated iff its *min-corner* voxel
    (elementwise min of the two endpoints) lies in the inner block — a global
    rule assigning every pair to exactly one block regardless of offset sign,
    so a cross-face pair of a negative offset is owned by the lower block
    (which sees it through the +1 upper halo) instead of being dropped.
    Cross-block pairs reaching further than the 1-voxel halo remain
    per-block-invisible, as in the reference's blockwise accumulation."""
    offsets = np.asarray(offsets, dtype=np.int64)
    owned = _owner_mask(labels.shape, owner_shape)
    us, vs, samples = [], [], []
    for c, off in enumerate(offsets):
        src = tuple(
            slice(max(-o, 0), s - max(o, 0)) for o, s in zip(off, labels.shape)
        )
        dst = tuple(
            slice(max(o, 0), s - max(-o, 0)) for o, s in zip(off, labels.shape)
        )
        lo, hi = labels[src].reshape(-1), labels[dst].reshape(-1)
        val = affs[c][src].reshape(-1).astype(np.float64)
        sel = (lo != hi) & (lo != 0) & (hi != 0)
        if owned is not None:
            # min-corner of (src, dst): slice [0, s - |o|) along every axis —
            # aligned elementwise with the src/dst iteration space
            anchor = tuple(
                slice(0, s - abs(o)) for o, s in zip(off, labels.shape)
            )
            sel &= owned[anchor].reshape(-1)
        if sel.any():
            us.append(np.minimum(lo[sel], hi[sel]))
            vs.append(np.maximum(lo[sel], hi[sel]))
            samples.append(val[sel])
    if not us:
        empty = (
            np.zeros((0, 2), dtype=labels.dtype),
            np.zeros((0, N_FEATURES)),
        )
        if hist_bins:
            empty = empty + (np.zeros((0, hist_bins), dtype=np.uint32),)
        if return_samples:
            empty = empty + (np.zeros(0, dtype=np.float64),)
        return empty
    u = np.concatenate(us)
    v = np.concatenate(vs)
    s = np.concatenate(samples)
    return _edge_group_features(
        u, v, s, labels.dtype, hist_bins, return_samples
    )


def _histogram_quantiles(hist: np.ndarray, cum: np.ndarray, counts, q: float):
    """Per-row quantile from bin counts over [0, 1], linearly interpolated
    within the selected bin (matches the lower-index sample quantile up to one
    bin width).  ``cum`` is the precomputed row cumsum (shared by all five
    quantile calls)."""
    n_bins = hist.shape[1]
    target = q * (counts - 1)
    # first bin whose cumulative count exceeds the target rank
    idx = (cum <= target[:, None]).sum(axis=1)
    idx = np.minimum(idx, n_bins - 1)
    rows = np.arange(hist.shape[0])
    below = np.where(idx > 0, cum[rows, np.maximum(idx - 1, 0)], 0.0)
    in_bin = np.maximum(hist[rows, idx], 1.0)
    frac = np.clip((target - below + 0.5) / in_bin, 0.0, 1.0)
    return (idx + frac) / n_bins


def merge_edge_features(
    edge_ids_list: Sequence[np.ndarray],
    feats_list: Sequence[np.ndarray],
    n_edges: int,
    hists_list: Optional[Sequence[Optional[np.ndarray]]] = None,
) -> np.ndarray:
    """Merge per-block partial features into the global [n_edges, 10] matrix.

    count/mean/var/min/max merge exactly (parallel-variance formula).
    Quantiles merge exactly up to one histogram-bin width when every partial
    comes with a histogram sketch in ``hists_list`` AND the observed value
    range stays inside [0, 1] (the sketch's bin domain); otherwise — legacy
    partials without sketches, or out-of-range float data — the merge
    degrades to count-weighted quantile averaging for all edges rather than
    producing collapsed quantiles.
    """
    use_hist = (
        hists_list is not None
        and len(hists_list) == len(feats_list)
        and all(h is not None for h in hists_list)
        and any(h.shape[0] for h in hists_list)
    )
    hist_bins = (
        next(h.shape[1] for h in hists_list if h.shape[0]) if use_hist else 0
    )

    out = np.zeros((n_edges, N_FEATURES))
    count = np.zeros(n_edges)
    mean = np.zeros(n_edges)
    m2 = np.zeros(n_edges)
    mins = np.full(n_edges, np.inf)
    maxs = np.full(n_edges, -np.inf)
    qsum = np.zeros((n_edges, len(QUANTILES)))
    hist = np.zeros((n_edges, hist_bins), dtype=np.int64) if use_hist else None

    for i, (ids, feats) in enumerate(zip(edge_ids_list, feats_list)):
        if ids.size == 0:
            continue
        c = feats[:, 9]
        m = feats[:, 0]
        v = feats[:, 1]
        tot = count[ids] + c
        delta = m - mean[ids]
        m2[ids] += v * c + delta**2 * count[ids] * c / np.maximum(tot, 1)
        mean[ids] += delta * c / np.maximum(tot, 1)
        count[ids] = tot
        mins[ids] = np.minimum(mins[ids], feats[:, 2])
        maxs[ids] = np.maximum(maxs[ids], feats[:, 8])
        # accumulate both: the hist/fallback choice is made after the observed
        # value range is known
        qsum[ids] += feats[:, 3:8] * c[:, None]
        if use_hist:
            hist[ids] += hists_list[i].astype(np.int64)

    nonzero = count > 0
    if use_hist and nonzero.any():
        lo = mins[nonzero].min()
        hi = maxs[nonzero].max()
        if lo < -1e-9 or hi > 1.0 + 1e-9:
            use_hist = False  # samples escape the sketch's [0, 1] bin domain

    out[:, 0] = mean
    out[:, 1] = np.where(nonzero, m2 / np.maximum(count, 1), 0.0)
    out[:, 2] = np.where(nonzero, mins, 0.0)
    if use_hist:
        cum = np.cumsum(hist, axis=1)
        for qi, q in enumerate(QUANTILES):
            out[:, 3 + qi] = np.where(
                nonzero, _histogram_quantiles(hist, cum, count, q), 0.0
            )
        # histogram bin centers can't leave [min, max]; clamp to the exact ends
        out[:, 3:8] = np.clip(
            out[:, 3:8], out[:, 2:3], np.where(nonzero, maxs, 0.0)[:, None]
        )
    else:
        out[:, 3:8] = qsum / np.maximum(count, 1)[:, None]
    out[:, 8] = np.where(nonzero, maxs, 0.0)
    out[:, 9] = count
    return out


def _exact_quantiles_all_groups(
    out, ids_list, counts_list, samples_list, n_groups
):
    """Exact per-edge quantiles for every feature group from the raw sample
    partials: globally sort (edge, value) pairs pooled over all blocks and
    index the quantile positions — identical (by construction) to a
    single-shot whole-volume recompute, the reference's exact
    ``ndist.mergeFeatureBlocks`` semantics (merge_edge_features.py:141).

    The edge-id expansion and the per-edge spans are group-invariant
    (lexsort's primary key is the edge id), so they are computed once; only
    the value sort repeats per group."""
    eids, val_groups = [], []
    for ids, counts, flat in zip(ids_list, counts_list, samples_list):
        if ids.size == 0:
            continue
        total = int(counts.sum())
        eids.append(np.repeat(ids, counts.astype(np.int64)))
        val_groups.append(flat.reshape(n_groups, total))
    if not eids:
        return
    eids = np.concatenate(eids)
    vals_all = np.concatenate(val_groups, axis=1)
    # spans from the eids-sorted view: identical for every group, since any
    # lexsort((vals_g, eids)) orders groups by edge id first
    sorted_eids = np.sort(eids)
    first = np.concatenate([[True], sorted_eids[1:] != sorted_eids[:-1]])
    starts = np.nonzero(first)[0]
    counts = np.diff(np.append(starts, eids.size)).astype(np.int64)
    rows = sorted_eids[starts]
    qpos = [
        starts + np.minimum((q * (counts - 1)).astype(np.int64), counts - 1)
        for q in QUANTILES
    ]
    for g in range(n_groups):
        svals = vals_all[g][np.lexsort((vals_all[g], eids))]
        for qi in range(len(QUANTILES)):
            out[rows, 9 * g + 3 + qi] = svals[qpos[qi]]


def merge_edge_features_multi(
    edge_ids_list: Sequence[np.ndarray],
    feats_list: Sequence[np.ndarray],
    n_edges: int,
    samples_list: Optional[Sequence[np.ndarray]] = None,
) -> np.ndarray:
    """Merge per-block partials of the G-group feature layout
    ``[9 stats × G groups, count]`` (``filter_edge_features``; G=1 reproduces
    the default 10-column layout).

    count/mean/var/min/max merge exactly per group (parallel-variance
    formula).  Quantiles merge EXACTLY when every partial ships its raw
    sorted samples in ``samples_list`` (``quantile_mode: "exact"``) —
    matching a single-shot recompute bit-for-bit; without samples they
    degrade to count-weighted averaging."""
    n_cols = next(
        (f.shape[1] for f in feats_list if f.ndim == 2 and f.shape[0]), None
    )
    if n_cols is None:
        return np.zeros((n_edges, N_FEATURES))
    n_groups = (n_cols - 1) // 9
    if n_cols != 9 * n_groups + 1:
        raise ValueError(f"feature width {n_cols} is not 9*G+1")

    out = np.zeros((n_edges, n_cols))
    count = np.zeros(n_edges)
    mean = np.zeros((n_edges, n_groups))
    m2 = np.zeros((n_edges, n_groups))
    mins = np.full((n_edges, n_groups), np.inf)
    maxs = np.full((n_edges, n_groups), -np.inf)
    qsum = np.zeros((n_edges, n_groups, len(QUANTILES)))
    counts_list = []
    for ids, feats in zip(edge_ids_list, feats_list):
        if ids.size == 0:
            counts_list.append(np.zeros(0))
            continue
        c = feats[:, -1]
        counts_list.append(c)
        tot = count[ids] + c
        safe = np.maximum(tot, 1)
        for g in range(n_groups):
            base = 9 * g
            m = feats[:, base + 0]
            v = feats[:, base + 1]
            delta = m - mean[ids, g]
            m2[ids, g] += v * c + delta**2 * count[ids] * c / safe
            mean[ids, g] += delta * c / safe
            mins[ids, g] = np.minimum(mins[ids, g], feats[:, base + 2])
            maxs[ids, g] = np.maximum(maxs[ids, g], feats[:, base + 8])
            qsum[ids, g] += feats[:, base + 3 : base + 8] * c[:, None]
        count[ids] = tot

    nonzero = count > 0
    use_exact = (
        samples_list is not None
        and len(samples_list) == len(feats_list)
        and all(s is not None for s in samples_list)
    )
    for g in range(n_groups):
        base = 9 * g
        out[:, base + 0] = mean[:, g]
        out[:, base + 1] = np.where(nonzero, m2[:, g] / np.maximum(count, 1), 0.0)
        out[:, base + 2] = np.where(nonzero, mins[:, g], 0.0)
        if not use_exact:
            out[:, base + 3 : base + 8] = (
                qsum[:, g] / np.maximum(count, 1)[:, None]
            )
        out[:, base + 8] = np.where(nonzero, maxs[:, g], 0.0)
    if use_exact:
        _exact_quantiles_all_groups(
            out, edge_ids_list, counts_list, samples_list, n_groups
        )
    out[:, -1] = count
    return out


# ---------------------------------------------------------------------------
# device kernel: RAG extraction + feature accumulation as one XLA program
# ---------------------------------------------------------------------------


def _boundary_edge_features_device_impl(
    labels, values, max_edges, hist_bins, owner_shape=None, packed=False,
    max_samples=None,
):
    """One fused XLA program: face-pair extraction → 3-key lexicographic sort
    (u, v, sample) → segment reductions (count/mean/var/min/max), in-segment
    rank gathers for the five sample quantiles, and the per-edge histogram
    sketch.  Fixed shapes throughout: outputs are padded to ``max_edges``
    (ragged edge counts are the host's problem — SURVEY.md §7 #4).

    ``packed=True`` (static; caller must guarantee every label id ≤ 32766 —
    the host wrappers enforce ``uniq.size < 32767`` — so the largest packed
    key 32766*65536+65535 stays strictly below the int32-max sentinel) packs
    the (u, v) pair into ONE int32 sort key ``u*65536 + v``: the dominant
    sort drops
    from 3 streams (12 B/element) to 2 (8 B), and the edge-endpoint
    reduction collapses to a single segment-min.  The packing is
    order-preserving (same lexicographic (u, v) order, same sentinel-last
    layout), so results are bit-identical to the unpacked path.

    The device-side answer to ndist.extractBlockFeaturesFromBoundaryMaps
    (reference block_edge_features.py:127-148) — no int64 keys needed, so it
    runs under the default x64-disabled jax config.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    ndim = labels.ndim
    owned = None
    if owner_shape is not None:
        # face ownership (see _owner_mask): lower voxel inside the inner block
        owned = jnp.ones(labels.shape, dtype=bool)
        for d, lim in enumerate(owner_shape):
            ax_idx = lax.broadcasted_iota(jnp.int32, labels.shape, d)
            owned &= ax_idx < lim
    us, vs, ss = [], [], []
    for axis in range(ndim):
        lab0 = jnp.moveaxis(labels, axis, 0)
        val0 = jnp.moveaxis(values, axis, 0)
        lo = lab0[:-1].reshape(-1)
        hi = lab0[1:].reshape(-1)
        vlo = val0[:-1].reshape(-1)
        vhi = val0[1:].reshape(-1)
        sel = (lo != hi) & (lo != 0) & (hi != 0)
        if owned is not None:
            sel &= jnp.moveaxis(owned, axis, 0)[:-1].reshape(-1)
        a = jnp.minimum(lo, hi)
        b = jnp.maximum(lo, hi)
        # invalid pairs get the sentinel key (int32 max) and sort to the end
        big = jnp.int32(np.iinfo(np.int32).max)
        a = jnp.where(sel, a, big)
        b = jnp.where(sel, b, big)
        us += [a, a]
        vs += [b, b]
        ss += [vlo, vhi]
    u = jnp.concatenate(us)
    v = jnp.concatenate(vs)
    s = jnp.concatenate(ss).astype(jnp.float32)

    big = jnp.int32(np.iinfo(np.int32).max)
    n_true = (u != big).sum()
    if max_samples is not None:
        u, v, s = compact_valid_rows(u, v, s, max_samples, big)
    if packed:
        # one int32 key, lexicographic order preserved; the sentinel pair
        # (big, big) maps to the int32 max so invalid rows still sort last
        p = pack_uv(u, v, big)
        p, s = lax.sort((p, s), num_keys=2)
        valid = p != big
        first = jnp.concatenate([valid[:1], p[1:] != p[:-1]]) & valid
        # endpoints are recovered from edge_p after the segment reduction;
        # no per-sample unpack is needed
    else:
        u, v, s = lax.sort((u, v, s), num_keys=3)
        valid = u != big
        first = jnp.concatenate(
            [valid[:1], (u[1:] != u[:-1]) | (v[1:] != v[:-1])]
        ) & valid
    n_samples = n_true  # pre-compaction truth: caller detects dropped rows
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1  # -1 before first edge
    seg = jnp.where(valid, seg, max_edges)  # invalid → overflow bucket
    n_edges = first.sum()

    ones = valid.astype(jnp.float32)
    count = jax.ops.segment_sum(ones, seg, num_segments=max_edges + 1)
    ssum = jax.ops.segment_sum(s * ones, seg, num_segments=max_edges + 1)
    ssum2 = jax.ops.segment_sum(s * s * ones, seg, num_segments=max_edges + 1)
    smin = jax.ops.segment_min(
        jnp.where(valid, s, jnp.inf), seg, num_segments=max_edges + 1
    )
    smax = jax.ops.segment_max(
        jnp.where(valid, s, -jnp.inf), seg, num_segments=max_edges + 1
    )
    idx = jnp.arange(s.shape[0], dtype=jnp.int32)
    starts = jax.ops.segment_min(
        jnp.where(valid, idx, jnp.int32(np.iinfo(np.int32).max)),
        seg,
        num_segments=max_edges + 1,
    )

    count_e = count[:max_edges]
    safe_count = jnp.maximum(count_e, 1.0)
    mean = ssum[:max_edges] / safe_count
    var = jnp.maximum(ssum2[:max_edges] / safe_count - mean**2, 0.0)
    present = count_e > 0
    starts_e = jnp.where(present, starts[:max_edges], 0)

    # quantiles: values are sorted within each segment (3rd sort key)
    qcols = []
    for q in QUANTILES:
        pos = starts_e + jnp.minimum(
            (q * (count_e - 1)).astype(jnp.int32),
            jnp.maximum(count_e - 1, 0).astype(jnp.int32),
        )
        qcols.append(jnp.where(present, s[pos], 0.0))

    feats = jnp.stack(
        [
            jnp.where(present, mean, 0.0),
            jnp.where(present, var, 0.0),
            jnp.where(present, smin[:max_edges], 0.0),
            *qcols,
            jnp.where(present, smax[:max_edges], 0.0),
            count_e,
        ],
        axis=1,
    )

    # per-edge histogram sketch over [0, 1]
    bins = jnp.clip((s * hist_bins).astype(jnp.int32), 0, hist_bins - 1)
    flat = jnp.where(valid, seg * hist_bins + bins, max_edges * hist_bins)
    hist = jax.ops.segment_sum(
        valid.astype(jnp.uint32), flat,
        num_segments=max_edges * hist_bins + 1,
    )[: max_edges * hist_bins].reshape(max_edges, hist_bins)

    if packed:
        # p is constant within a segment: one reduction, then unpack
        edge_p = jax.ops.segment_min(
            jnp.where(valid, p, big), seg, num_segments=max_edges + 1
        )[:max_edges]
        edge_u, edge_v = unpack_uv(edge_p, big)
    else:
        edge_u = jax.ops.segment_min(
            jnp.where(valid, u, big), seg, num_segments=max_edges + 1
        )[:max_edges]
        edge_v = jax.ops.segment_min(
            jnp.where(valid, v, big), seg, num_segments=max_edges + 1
        )[:max_edges]
    return edge_u, edge_v, feats, hist, n_edges, n_samples


@lru_cache(maxsize=32)
def _jitted_device_features(max_edges: int, hist_bins: int, owner_shape,
                            packed: bool = False, max_samples=None):
    """One cached jitted kernel per static configuration — a fresh jax.jit
    per call would re-trace and re-compile for every block."""
    import jax

    fn = partial(
        _boundary_edge_features_device_impl,
        max_edges=max_edges,
        hist_bins=hist_bins,
        owner_shape=owner_shape,
        packed=packed,
        max_samples=max_samples,
    )
    return jax.jit(fn)


def sample_capacity(n_valid: int) -> int:
    """Static compaction capacity for a measured valid-sample count: 10%
    headroom rounded up to a quarter-octave bucket (2^k * {1, 1.25, 1.5,
    1.75}), so nearby block statistics share one compiled kernel without a
    full power-of-two overshoot (a straight pow2 can nearly double the
    dominant sort for nothing)."""
    need = max(int(n_valid * 1.1), 1024)
    base = 1 << (need.bit_length() - 1)
    for frac in (4, 5, 6, 7):
        cap = base * frac // 4
        if cap >= need:
            return cap
    return base * 2


def _face_mask(lo, hi):
    """THE face predicate of every RAG accumulator (device, sharded, host
    counts): an inter-label face with both sides foreground.  One
    definition — the host-side cap sizing must bound exactly what the
    kernels generate (each face contributes 2 sample rows)."""
    return (lo != hi) & (lo != 0) & (hi != 0)


def count_boundary_samples(labels: np.ndarray) -> int:
    """Host-side exact count of the kernel's valid face rows (2 samples per
    inter-label face, zero labels excluded) — cheap numpy comparisons, used
    to pick ``max_samples`` before dispatch."""
    n = 0
    for axis in range(labels.ndim):
        lo = np.moveaxis(labels, axis, 0)[:-1]
        hi = np.moveaxis(labels, axis, 0)[1:]
        n += 2 * int(_face_mask(lo, hi).sum())
    return n


def plane_face_counts(slab: np.ndarray, prev_last=None):
    """Per-z-plane valid-sample counts of one 3d slab, for streaming cap
    sizing (a caller that never holds the whole volume accumulates these
    slab by slab): returns ``(c_in, c_z, boundary, last_plane)`` where
    ``c_in[z]`` counts the in-plane (y/x-axis) samples of plane ``z``,
    ``c_z[z]`` the samples of the pair (z, z+1) WITHIN the slab
    (``c_z[-1]`` is always 0 — the pair into the next slab cannot be
    counted yet), and ``boundary`` the samples of the pair between
    ``prev_last`` (the previous slab's last plane, from the previous
    call's 4th element) and this slab's first plane — the caller adds it
    at the previous slab's last index."""
    c_in = np.zeros(slab.shape[0], np.int64)
    for ax in (1, 2):
        lo = np.moveaxis(slab, ax, 1)[:, :-1]
        hi = np.moveaxis(slab, ax, 1)[:, 1:]
        c_in += 2 * _face_mask(lo, hi).sum(axis=(1, 2))
    c_z = np.zeros(slab.shape[0], np.int64)
    c_z[:-1] = 2 * _face_mask(slab[:-1], slab[1:]).sum(axis=(1, 2))
    boundary = (
        2 * int(_face_mask(prev_last, slab[0]).sum())
        if prev_last is not None else 0
    )
    return c_in, c_z, boundary, slab[-1]


def boundary_edge_features_device(
    labels,
    values,
    max_edges: int = 16384,
    hist_bins: int = HIST_BINS,
    owner_shape=None,
    packed: bool = False,
    max_samples=None,
):
    """Jitted device RAG accumulator; see ``_boundary_edge_features_device_impl``.

    ``labels`` must be int32 (compact per-block ids — the host wrapper
    ``boundary_edge_features_tpu`` handles uint64 global labels).
    ``packed`` is static and only valid when every label id < 32768 — the
    host wrapper decides it from the compact id count.  ``max_samples``
    (static) turns on pre-sort compaction of valid face rows; the caller
    must check the returned ``n_samples`` against it (the host wrappers
    size it from ``count_boundary_samples`` so it cannot overflow).
    Compaction that cannot shrink the sort (cap >= the raw face-row
    count — small or boundary-dense blocks) is skipped: it would pay the
    cumsum/scatter pass, and possibly EXPAND the arrays, for nothing.
    """
    if max_samples is not None:
        shape = labels.shape
        raw_rows = 2 * sum(
            (shape[ax] - 1) * int(np.prod(shape)) // max(shape[ax], 1)
            for ax in range(len(shape))
        )
        if int(max_samples) >= raw_rows:
            max_samples = None
    fn = _jitted_device_features(
        int(max_edges),
        int(hist_bins),
        None if owner_shape is None else tuple(owner_shape),
        bool(packed),
        None if max_samples is None else int(max_samples),
    )
    return fn(labels, values)


def boundary_edge_features_tpu(
    labels: np.ndarray,
    boundary_map: np.ndarray,
    hist_bins: int = 0,
    owner_shape=None,
    max_edges: int = 16384,
):
    """Drop-in device-backed replacement for ``boundary_edge_features``:
    compacts uint64 labels to int32 on the host (SURVEY.md §7 #3: labels are
    uint64 with block offsets; the device program works on dense ids), runs
    the fused kernel, and crops the padded outputs.

    Moment statistics accumulate in float32 on device (TPUs have no native
    f64) — parity with the numpy path is to ~1e-5 relative, not bitwise.
    """
    import jax.numpy as jnp

    uniq, inv = np.unique(labels, return_inverse=True)
    compact = inv.reshape(labels.shape).astype(np.int32)
    # keep 0 → 0 so the kernel's background skip applies
    if uniq.size == 0 or uniq[0] != 0:
        compact = compact + 1
        # dtype-preserving prepend: a bare [0] would promote uint64 → float64
        uniq = np.concatenate([np.zeros(1, dtype=uniq.dtype), uniq])
    # pre-sort compaction sized from the exact host count (quarter-octave
    # bucketing bounds the compile-cache key count)
    cap = sample_capacity(count_boundary_samples(compact))
    eu, ev, feats, hist, n_edges, n_samples = boundary_edge_features_device(
        jnp.asarray(compact), jnp.asarray(boundary_map, jnp.float32),
        max_edges=max_edges, hist_bins=hist_bins or HIST_BINS,
        owner_shape=owner_shape,
        # single-key packed sort whenever the compact id space fits
        packed=uniq.size <= PACK_MAX_ID,
        max_samples=cap,
    )
    n = int(n_edges)
    if n > max_edges:
        raise ValueError(
            f"block has {n} edges > max_edges={max_edges}; raise max_edges"
        )
    if int(n_samples) > cap:
        # cannot happen while count_boundary_samples covers every kernel
        # selection path (the owner mask only removes rows) — but a silent
        # sample drop would corrupt features, so the invariant is enforced
        raise AssertionError(
            f"kernel saw {int(n_samples)} boundary samples > capacity {cap}"
        )
    edges = uniq[np.stack([np.asarray(eu[:n]), np.asarray(ev[:n])], axis=1)]
    feats = np.asarray(feats[:n], dtype=np.float64)
    if hist_bins:
        return edges, feats, np.asarray(hist[:n], dtype=np.uint32)
    return edges, feats
