"""Affinity-map kernels: label→affinity synthesis, embedding distances,
morphological dilation/erosion, gradients.

Replaces the reference's affogato C++ calls (reference
affinities/insert_affinities.py:16 ``compute_affinities``,
affinities/embedding_distances.py ``compute_embedding_distances``) with
shift-and-compare XLA programs: an affinity channel for offset ``o`` is a
comparison between the volume and itself rolled by ``o`` — elementwise work
that XLA fuses into one pass over HBM per channel.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _offset_valid(shape: Sequence[int], offset: Sequence[int]) -> jnp.ndarray:
    """Mask of voxels whose ``v + offset`` neighbor stays inside ``shape``."""
    ndim = len(shape)
    valid = jnp.ones(shape, dtype=bool)
    for ax, o in enumerate(offset):
        if o == 0:
            continue
        idx = jnp.arange(shape[ax])
        ok = (idx < shape[ax] - o) if o > 0 else (idx >= -o)
        bshape = [1] * ndim
        bshape[ax] = shape[ax]
        valid = valid & ok.reshape(bshape)
    return valid


def _shifted_pairs(x: jnp.ndarray, offset: Sequence[int]):
    """(x[v], x[v + offset], valid) with out-of-bounds marked invalid."""
    shifted = jnp.roll(x, shift=[-o for o in offset], axis=tuple(range(x.ndim)))
    return x, shifted, _offset_valid(x.shape, offset)


@partial(jax.jit, static_argnames=("offsets",))
def _compute_affinities(labels: jnp.ndarray, offsets) -> Tuple[jnp.ndarray, jnp.ndarray]:
    affs, masks = [], []
    for off in offsets:
        a, b, valid = _shifted_pairs(labels, off)
        affs.append(jnp.where(valid, (a == b).astype(jnp.float32), 0.0))
        masks.append(valid)
    return jnp.stack(affs), jnp.stack(masks)


def compute_affinities(labels, offsets) -> Tuple[np.ndarray, np.ndarray]:
    """Affinities of a label volume: channel c is 1 where the labels at ``v``
    and ``v + offsets[c]`` agree (affogato convention: 1 = attractive), plus a
    validity mask (0 where the offset leaves the volume).

    Labels are compacted to int32 on host first — jnp.asarray would truncate
    uint64 ids to 32 bits (no x64) and merge objects colliding mod 2**32."""
    offsets = tuple(tuple(int(o) for o in off) for off in offsets)
    labels = np.asarray(labels)
    if labels.dtype.itemsize > 4:
        _, inv = np.unique(labels, return_inverse=True)
        labels = inv.reshape(labels.shape).astype(np.int32)
    affs, mask = _compute_affinities(jnp.asarray(labels), offsets)
    return np.asarray(affs), np.asarray(mask)


@partial(jax.jit, static_argnames=("offsets", "norm"))
def _embedding_distances(emb: jnp.ndarray, offsets, norm: str) -> jnp.ndarray:
    """emb: [C, *spatial] → [len(offsets), *spatial]."""
    out = []
    for off in offsets:
        shifted = jnp.roll(
            emb, shift=[-o for o in off], axis=tuple(range(1, emb.ndim))
        )
        if norm == "l2":
            d = jnp.sqrt(jnp.sum((emb - shifted) ** 2, axis=0) + 1e-12)
        elif norm == "cosine":
            num = jnp.sum(emb * shifted, axis=0)
            den = jnp.linalg.norm(emb, axis=0) * jnp.linalg.norm(shifted, axis=0)
            d = 1.0 - num / jnp.maximum(den, 1e-12)
        else:
            raise ValueError(f"unknown norm {norm!r}")
        out.append(jnp.where(_offset_valid(emb.shape[1:], off), d, 0.0))
    return jnp.stack(out)


def embedding_distances(emb, offsets, norm: str = "l2") -> np.ndarray:
    """Per-offset distances between embedding vectors (reference
    embedding_distances.py via affogato ``compute_embedding_distances``)."""
    offsets = tuple(tuple(int(o) for o in off) for off in offsets)
    return np.asarray(_embedding_distances(jnp.asarray(emb, jnp.float32),
                                           offsets, norm))


def _neighbor_max(x: jnp.ndarray, axes: Sequence[int], fill: float = 0.0):
    """Max over the cross neighborhood; ``fill`` is the out-of-volume value."""
    out = x
    for ax in axes:
        for shift in (1, -1):
            rolled = jnp.roll(x, shift, axis=ax)
            # freshly rolled-in border values must not wrap around
            idx = jnp.arange(x.shape[ax])
            ok = (idx > 0) if shift == 1 else (idx < x.shape[ax] - 1)
            shape = [1] * x.ndim
            shape[ax] = x.shape[ax]
            rolled = jnp.where(ok.reshape(shape), rolled, fill)
            out = jnp.maximum(out, rolled)
    return out


@partial(jax.jit, static_argnames=("iterations", "in_2d"))
def binary_dilation(x: jnp.ndarray, iterations: int, in_2d: bool = False):
    """Cross-structuring-element dilation iterated (scipy binary_dilation
    equivalent; ``in_2d`` restricts to the trailing two axes)."""
    mask = x.astype(jnp.float32)
    axes = list(range(mask.ndim))[-2:] if in_2d else list(range(mask.ndim))

    def body(_, m):
        return _neighbor_max(m, axes)

    return jax.lax.fori_loop(0, iterations, body, mask) > 0


@partial(jax.jit, static_argnames=("iterations",))
def binary_erosion(x: jnp.ndarray, iterations: int):
    """Cross-structuring-element erosion iterated (dilation of the
    complement; out-of-volume counts as background, scipy's border_value=0)."""
    inv = (~x.astype(bool)).astype(jnp.float32)

    def body(_, m):
        return _neighbor_max(m, list(range(x.ndim)), fill=1.0)

    return jax.lax.fori_loop(0, iterations, body, inv) <= 0


@jax.jit
def gradient_mean(x: jnp.ndarray) -> jnp.ndarray:
    """Mean over per-axis central-difference gradients (np.gradient average,
    reference gradients.py:131-140)."""
    grads = jnp.gradient(x)
    return jnp.mean(jnp.stack(grads), axis=0)
