"""Multicut solvers and cost transforms.

Replaces elf.segmentation.multicut / nifty solvers (reference
multicut/solve_subproblems.py:184, costs/probs_to_costs.py:212-215).

The solver is host-side (sequential combinatorial; C++ via
``cluster_tools_tpu.native`` with a pure-python fallback); the cost transform is
vectorized and can run on device.
"""

from __future__ import annotations

import heapq
from typing import Dict, Optional, Tuple

import numpy as np

from .. import native


def transform_probabilities_to_costs(
    probs: np.ndarray,
    beta: float = 0.5,
    edge_sizes: Optional[np.ndarray] = None,
    weighting_exponent: float = 1.0,
) -> np.ndarray:
    """Log-odds edge costs with optional edge-size weighting
    (reference probs_to_costs.py:212-215 via elf)."""
    p = np.clip(probs.astype(np.float64), 0.001, 0.999)
    costs = np.log((1.0 - p) / p) + np.log((1.0 - beta) / beta)
    if edge_sizes is not None:
        w = (edge_sizes / edge_sizes.max()) ** weighting_exponent
        costs = costs * w
    return costs


NODE_LABEL_MODES = ("ignore", "isolate", "ignore_transition")


def apply_node_label_costs(
    costs: np.ndarray,
    endpoint_labels: np.ndarray,
    mode: str,
    max_repulsive: float,
    max_attractive: float,
) -> np.ndarray:
    """Force edge costs from per-endpoint node labels (reference
    costs/probs_to_costs.py:116-152).

    ``endpoint_labels`` is ``[m, 2]``: the node label of each edge endpoint.
    A node "has the label" when its value is > 0.

    - ``ignore``: any edge touching a labeled node → ``max_repulsive``
      (excise labeled nodes from the partition).
    - ``isolate``: both endpoints labeled → ``max_attractive``; exactly one
      labeled → ``max_repulsive`` (labeled nodes form their own segment).
    - ``ignore_transition``: endpoints with *different* label values →
      ``max_repulsive`` (semantic boundaries must stay cut).
    """
    if mode not in NODE_LABEL_MODES:
        raise ValueError(f"invalid node-label mode {mode!r}, pick from {NODE_LABEL_MODES}")
    out = np.asarray(costs, dtype=np.float64).copy()
    lab = np.asarray(endpoint_labels)
    if lab.ndim != 2 or lab.shape[1] != 2 or lab.shape[0] != out.shape[0]:
        raise ValueError(
            f"endpoint_labels must be [{out.shape[0]}, 2], got {lab.shape}"
        )
    has = lab > 0
    if mode == "ignore":
        out[has.any(axis=1)] = max_repulsive
    elif mode == "isolate":
        n_labeled = has.sum(axis=1)
        out[n_labeled == 2] = max_attractive
        out[n_labeled == 1] = max_repulsive
    else:  # ignore_transition
        out[lab[:, 0] != lab[:, 1]] = max_repulsive
    return out


def _gaec_python(n_nodes: int, uv: np.ndarray, costs: np.ndarray,
                 stop_priority: float = 0.0, mean_mode: bool = False,
                 counts: Optional[np.ndarray] = None) -> np.ndarray:
    """Pure-python greedy edge contraction (fallback).

    ``mean_mode=False``: parallel edges sum, priority = value (GAEC).
    ``mean_mode=True``: parallel edges combine by count-weighted mean,
    priority = -mean (threshold clustering; pass stop_priority=-threshold).
    """
    if counts is None:
        counts = np.ones(len(costs))

    def combine(a, b):
        if mean_mode:
            return ((a[0] * a[1] + b[0] * b[1]) / (a[1] + b[1]), a[1] + b[1])
        return (a[0] + b[0], a[1] + b[1])

    def prio(val):
        return -val[0] if mean_mode else val[0]

    adj: list = [dict() for _ in range(n_nodes)]
    for (u, v), c, cnt in zip(uv, costs, counts):
        u, v = int(u), int(v)
        if u == v:
            continue
        val = (float(c), float(cnt))
        if v in adj[u]:
            val = combine(adj[u][v], val)
        adj[u][v] = val
        adj[v][u] = val

    parent = np.arange(n_nodes, dtype=np.int64)

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    stamp: Dict[Tuple[int, int], int] = {}
    counter = 0
    heap = []
    for u in range(n_nodes):
        for v, val in adj[u].items():
            if v > u:
                stamp[(u, v)] = 0
                heapq.heappush(heap, (-prio(val), u, v, 0))

    while heap:
        negp, u, v, st = heapq.heappop(heap)
        ru, rv = find(u), find(v)
        if ru == rv:
            continue
        key = (min(ru, rv), max(ru, rv))
        if stamp.get(key) != st:
            continue
        if -negp <= stop_priority:
            break
        # contract the smaller adjacency into the larger
        if len(adj[ru]) < len(adj[rv]):
            ru, rv = rv, ru
        parent[rv] = ru
        adj[ru].pop(rv, None)
        adj[rv].pop(ru, None)
        for w, val in adj[rv].items():
            adj[w].pop(rv, None)
            if w in adj[ru]:
                val = combine(adj[ru][w], val)
            adj[ru][w] = val
            adj[w][ru] = val
            counter += 1
            k2 = (min(ru, w), max(ru, w))
            stamp[k2] = counter
            heapq.heappush(heap, (-prio(val), ru, w, counter))
        adj[rv].clear()

    return np.array([find(i) for i in range(n_nodes)], dtype=np.int64)


def solve_multicut(
    n_nodes: int, uv: np.ndarray, costs: np.ndarray, use_native: bool = True
) -> np.ndarray:
    """GAEC multicut: returns a consecutive node labeling (0..k-1).

    Positive cost = attractive (merge), negative = repulsive — the convention of
    the log-odds transform above.
    """
    if uv.shape[0] == 0:
        return np.arange(n_nodes, dtype=np.int64)
    if use_native and native.available():
        roots = native.gaec_multicut(n_nodes, uv, costs)
    else:
        roots = _gaec_python(n_nodes, uv, costs)
    _, labels = np.unique(roots, return_inverse=True)
    return labels.astype(np.int64)


def agglomerative_clustering(
    n_nodes: int,
    uv: np.ndarray,
    weights: np.ndarray,
    threshold: float,
    edge_sizes: Optional[np.ndarray] = None,
    use_native: bool = True,
) -> np.ndarray:
    """Merge edges with (size-weighted mean) weight < threshold, cheapest
    boundary first — mala clustering semantics (reference
    agglomerate.py:190-198).  Returns a consecutive labeling."""
    if uv.shape[0] == 0:
        return np.arange(n_nodes, dtype=np.int64)
    if use_native and native.available():
        roots = native.agglomerative_clustering(
            n_nodes, uv, weights, threshold, sizes=edge_sizes
        )
    else:
        roots = _gaec_python(
            n_nodes, uv, weights.astype(np.float64),
            stop_priority=-threshold, mean_mode=True, counts=edge_sizes,
        )
    _, labels = np.unique(roots, return_inverse=True)
    return labels.astype(np.int64)


def contract_edges(
    new_u: np.ndarray, new_v: np.ndarray, values: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Contract an edge list under a node relabeling: drops edges that became
    internal (u == v), canonicalizes pair order, and sums ``values`` over
    duplicate pairs (the reduce step of the hierarchical solve, reference
    reduce_problem.py:205-218 via nt.EdgeMapping).

    Returns ``(edges [k,2] sorted lexicographically, summed values [k])``.
    """
    live = new_u != new_v
    nu = np.asarray(new_u[live], dtype=np.int64).copy()
    nv = np.asarray(new_v[live], dtype=np.int64).copy()
    swap = nu > nv
    nu[swap], nv[swap] = nv[swap], nu[swap]
    if nu.size == 0:
        return np.zeros((0, 2), dtype=np.int64), np.zeros(0)
    base = int(max(nu.max(), nv.max())) + 2
    keys = nu * base + nv
    uniq_keys, inv = np.unique(keys, return_inverse=True)
    summed = np.zeros(uniq_keys.size)
    np.add.at(summed, inv, values[live])
    edges = np.stack([uniq_keys // base, uniq_keys % base], axis=1)
    return edges.astype(np.int64), summed


def multicut_energy(uv: np.ndarray, costs: np.ndarray, labels: np.ndarray) -> float:
    """Energy of a node labeling: sum of costs of *cut* edges (lower = better
    when repulsive edges are cut; used by tests as a sanity oracle)."""
    cut = labels[uv[:, 0]] != labels[uv[:, 1]]
    return float(costs[cut].sum())
