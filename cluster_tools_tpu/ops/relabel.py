"""Consecutive relabeling and label-table application.

Replaces vigra.relabelConsecutive (18 call sites in the reference) and
nifty.tools.take/takeDict (reference write.py:157-181) with sort/searchsorted
programs on device plus host fallbacks for uint64 global label spaces.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("max_labels", "keep_zero"))
def relabel_consecutive(
    labels: jnp.ndarray, max_labels: int, keep_zero: bool = True
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Map non-negative labels to consecutive ids preserving order.

    ``max_labels`` is the static bound on distinct labels (labels must be
    < iinfo(dtype).max, which is used as the pad sentinel).  With ``keep_zero``
    label 0 stays 0 and the others become 1..n; otherwise ranks are 0..n-1.
    Returns ``(relabeled, n_labels)`` where n excludes zero when ``keep_zero``.

    Overflow contract: if the input holds more than ``max_labels`` distinct
    values, the surplus labels alias together (a jitted kernel cannot raise on
    data).  Callers MUST treat ``n_labels == max_labels`` (or == max_labels - 1
    with ``keep_zero``) as saturation and re-run with a larger bound.
    """
    flat = labels.reshape(-1)
    # sentinel must be an array of the label dtype: a Python-int iinfo.max would
    # overflow jnp.unique's default-int fill_value conversion for wide dtypes
    sentinel = jnp.asarray(jnp.iinfo(flat.dtype).max, flat.dtype)
    uniq = jnp.unique(flat, size=max_labels, fill_value=sentinel)
    idx = jnp.searchsorted(uniq, flat).astype(flat.dtype)
    n_uniq = (uniq < sentinel).sum().astype(jnp.int32)
    if keep_zero:
        # labels are >= 0, so a present 0 has rank 0 and nonzero ranks are already
        # 1-based; if absent, shift ranks up by one
        has_zero = jnp.any(uniq == 0)
        new = jnp.where(flat == 0, 0, idx + (1 - has_zero.astype(flat.dtype)))
        n = n_uniq - has_zero.astype(jnp.int32)
        return new.reshape(labels.shape), n
    return idx.reshape(labels.shape), n_uniq


def relabel_consecutive_np(
    labels: np.ndarray, keep_zero: bool = True
) -> Tuple[np.ndarray, int]:
    """Host relabeling for global (uint64) label volumes."""
    uniq, inv = np.unique(labels, return_inverse=True)
    inv = inv.reshape(labels.shape)
    if keep_zero and uniq.size and uniq[0] == 0:
        return inv.astype(labels.dtype), int(uniq.size - 1)
    return (inv + 1).astype(labels.dtype) if keep_zero else inv.astype(labels.dtype), int(
        uniq.size
    )


def apply_mapping_np(labels: np.ndarray, mapping: np.ndarray) -> np.ndarray:
    """labels → mapping[labels] with a dense mapping array (nifty.tools.take)."""
    return mapping[labels]


def apply_assignment_table_np(
    labels: np.ndarray, table: np.ndarray, default_zero: bool = True
) -> np.ndarray:
    """Apply a 2-column (old_id, new_id) assignment table
    (reference write.py:157-181 'node label assignment' modes)."""
    if table.shape[0] == 0:
        out = np.zeros_like(labels) if default_zero else labels.copy()
        return out
    old, new = table[:, 0], table[:, 1]
    order = np.argsort(old)
    old, new = old[order], new[order]
    idx = np.searchsorted(old, labels.reshape(-1))
    idx = np.clip(idx, 0, old.size - 1)
    found = old[idx] == labels.reshape(-1)
    out = np.where(found, new[idx], 0 if default_zero else labels.reshape(-1))
    return out.reshape(labels.shape).astype(labels.dtype)


@partial(jax.jit, static_argnames=())
def apply_mapping(labels: jnp.ndarray, mapping: jnp.ndarray) -> jnp.ndarray:
    """Device gather: labels → mapping[labels]."""
    return mapping[labels.reshape(-1)].reshape(labels.shape)
