"""JAX/XLA kernel library — the TPU replacement for the reference's native layer.

Every kernel here substitutes a C++ dependency of the reference (vigra / nifty /
fastfilters / affogato — see SURVEY.md §2.10 for the full checklist) with a
jit-compilable, statically-shaped XLA program:

  * filters   — separable gaussian / min / max convolutions (vigra+fastfilters)
  * dt        — Euclidean distance transform (vigra.filters.distanceTransform)
  * cc        — connected components (skimage.morphology.label / vigra labelVolume)
  * watershed — seeds + seeded minimax-flood watershed (vigra watershedsNew)
  * segment   — segment reductions, contingency tables (nifty accumulators)
  * relabel   — consecutive relabeling (vigra.relabelConsecutive)

All kernels take/return plain arrays, are free of data-dependent Python control
flow (lax.while_loop / scan inside), and are written to batch with vmap.
"""

from . import cc, dt, filters, relabel, segment, watershed

__all__ = ["cc", "dt", "filters", "relabel", "segment", "watershed"]
