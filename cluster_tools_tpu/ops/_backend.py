"""Shared backend-mode switches for the log-depth sweep kernels.

The flood (ops/watershed.py), connected-components (ops/cc.py), and EDT line
scans (ops/dt.py) all choose between log-depth formulations
(``lax.associative_scan`` / ``lax.cummax`` — win on dispatch/latency-bound
TPUs) and sequential carry chains (O(n) work — win on work-bound XLA-CPU).
Further opt-in kernel switches route whole pipelines to Pallas
(flood/cc/dtws) or to the device MWS formulation.  One registry keeps every
switch on the same contract:

  * default: by env var (``CTT_<KIND>_MODE``), else a backend-tagged pin
    file (``tools/chip_modes.json``, written by tools/chip_session.py from
    on-chip measurements; applied only when the running backend matches the
    one the pins were measured on), else the kind's default rule;
  * the env pin remains the explicit way to deploy a mode and always
    overrides the pin file;
  * ``force_<kind>_mode(mode)`` scopes an override for tests and
    benchmarks, owning both the restore and the jit-cache invalidation
    (traces bake the mode in — all switches are read at TRACE time, so
    already-compiled shapes keep their path until the caches clear).
"""

from __future__ import annotations

import os
from contextlib import contextmanager

# kind -> forced mode (None = fall back to env var / default rule)
_FORCED: dict = {}

_ENV = {
    "sweep": "CTT_SWEEP_MODE",
    "flood": "CTT_FLOOD_MODE",
    "cc": "CTT_CC_MODE",
    "dtws": "CTT_DTWS_MODE",
    "mws": "CTT_MWS_MODE",
}


# measured-pin file: {"backend": "<jax backend>", "modes": {ENVVAR: mode}}
_PINS_CACHE: dict = {}


def _file_pins() -> dict:
    """Mode pins from tools/chip_modes.json, keyed by env-var name.

    Loaded once per backend: pins measured on one backend (e.g. pallas
    kernels validated on TPU) must not leak into runs on another (the CPU
    test mesh), so a backend-tagged file only applies when
    jax.default_backend() matches its tag."""
    import jax

    backend = jax.default_backend()
    if backend in _PINS_CACHE:
        return _PINS_CACHE[backend]
    path = os.environ.get("CTT_MODES_FILE")
    if path is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        path = os.path.join(root, "tools", "chip_modes.json")
    pins: dict = {}
    try:
        import json

        with open(path) as f:
            data = json.load(f)
        if (isinstance(data, dict) and isinstance(data.get("modes"), dict)
                and data.get("backend") == backend):
            pins = dict(data["modes"])
    except (OSError, ValueError):
        pins = {}
    if pins:
        # implicit mode changes must be traceable: a process whose backend
        # happens to match the committed pin file inherits these silently
        import logging

        logging.getLogger(__name__).debug(
            "loaded %s pin(s) for backend %r from %s: %s",
            len(pins), backend, path, pins,
        )
    _PINS_CACHE[backend] = pins
    return pins


def pinned_value(env_name: str):
    """Resolve a measured pin by its env-var name: the env var wins, else
    the backend-tagged pin file entry, else None.  The one precedence
    implementation for every CTT_* value that is not a mode switch
    (e.g. CTT_DEVICE_BATCH in runtime/executor.py)."""
    env = os.environ.get(env_name)
    if env is not None:
        return env
    return _file_pins().get(env_name)


def _mode(kind: str):
    forced = _FORCED.get(kind)
    if forced is not None:
        return forced
    return pinned_value(_ENV[kind])


@contextmanager
def _force(kind: str, mode):
    """Scoped mode override: set, clear jit caches, restore + clear on exit
    even on error — the single implementation behind every force_*_mode."""
    import jax

    prev = _FORCED.get(kind)
    _FORCED[kind] = mode
    jax.clear_caches()
    try:
        yield
    finally:
        _FORCED[kind] = prev
        jax.clear_caches()


def use_assoc() -> bool:
    """Sweep formulation: associative-scan (TPU) vs sequential carry (CPU);
    CTT_SWEEP_MODE=assoc|seq pins it."""
    mode = _mode("sweep")
    if mode in ("assoc", "seq"):
        return mode == "assoc"
    import jax

    return jax.default_backend() != "cpu"


def use_pallas_flood() -> bool:
    """Whether the per-slice flood uses the Pallas kernel
    (ops/pallas_flood.py, CTT_FLOOD_MODE=pallas)."""
    return _mode("flood") == "pallas"


def use_pallas_cc() -> bool:
    """Whether volume CC uses the per-slice Pallas kernel + z-merge
    (ops/pallas_cc.py, CTT_CC_MODE=pallas)."""
    return _mode("cc") == "pallas"


def use_slices_cc() -> bool:
    """Whether volume CC uses the XLA per-slice sweeps + z-merge structure
    (CTT_CC_MODE=slices) instead of whole-volume 3d propagation."""
    return _mode("cc") == "slices"


def use_coarse_cc() -> bool:
    """Whether CC uses the coarse-to-fine tiled kernel (ops/cc.py ctt-cc:
    tile-local fixpoints + compact boundary union-find) instead of the flat
    whole-volume fixpoint.  ``CTT_CC_MODE=coarse|flat`` pins it; the default
    follows the sweep-mode economics (the bench records both paths): on
    TPU the tile-bounded round count + vmapped VMEM-friendly tiles win, on
    the work-bound CPU mesh the seq-sweep flat kernel already converges in
    a handful of rounds and the O(volume·log boundary) relabel gather of
    the merge table costs more than the saved rounds (bench.py
    ``cc_flat_vs_baseline`` / ``cc_coarse_vs_baseline``).  Both paths are
    bit-exact on every input (tests/test_cc_coarse.py)."""
    mode = _mode("cc")
    if mode in ("coarse", "flat"):
        return mode == "coarse"
    import jax

    return jax.default_backend() != "cpu"


def use_pallas_dtws() -> bool:
    """Whether the per-slice DT-watershed uses the fused Pallas kernel
    (ops/pallas_dtws.py, CTT_DTWS_MODE=pallas)."""
    return _mode("dtws") == "pallas"


def use_mws_device() -> bool:
    """Whether graph-domain MWS solves route to the parallel-greedy device
    kernel (ops/mws_device.py, CTT_MWS_MODE=device) instead of host C++."""
    return _mode("mws") == "device"


def force_sweep_mode(mode):
    """Scoped sweep-mode override ('assoc' | 'seq')."""
    return _force("sweep", mode)


def force_flood_mode(mode):
    """Scoped flood-mode override ('pallas' | 'xla')."""
    return _force("flood", mode)


def force_cc_mode(mode):
    """Scoped CC-mode override ('coarse' | 'flat' | 'pallas' | 'slices')."""
    return _force("cc", mode)


def force_dtws_mode(mode):
    """Scoped DT-watershed-mode override ('pallas' | 'xla')."""
    return _force("dtws", mode)


def force_mws_mode(mode):
    """Scoped MWS-mode override ('device' | 'host')."""
    return _force("mws", mode)
