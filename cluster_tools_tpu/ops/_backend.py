"""Shared backend-mode switch for the log-depth sweep kernels.

The flood (ops/watershed.py), connected-components (ops/cc.py), and EDT line
scans (ops/dt.py) all choose between log-depth formulations
(``lax.associative_scan`` / ``lax.cummax`` — win on dispatch/latency-bound
TPUs) and sequential carry chains (O(n) work — win on work-bound XLA-CPU).
One switch keeps every kernel on the same path:

  * default: by backend (assoc off-cpu, seq on cpu);
  * ``CTT_SWEEP_MODE=assoc|seq`` pins the choice for production runs (the
    supported way to deploy whichever mode bench/tpu_validate measured best);
  * ``force_sweep_mode(mode)`` scopes an override for tests and benchmarks,
    owning both the restore and the jit-cache invalidation.
"""

from __future__ import annotations

import os
from contextlib import contextmanager

# None = pick by env/backend; force_sweep_mode() overrides within a scope
FORCE_SWEEP_MODE = None


def use_assoc() -> bool:
    if FORCE_SWEEP_MODE is not None:
        return FORCE_SWEEP_MODE == "assoc"
    env = os.environ.get("CTT_SWEEP_MODE")
    if env in ("assoc", "seq"):
        return env == "assoc"
    import jax

    return jax.default_backend() != "cpu"


@contextmanager
def force_sweep_mode(mode):
    """Scoped sweep-mode override: sets the switch, clears jit caches (traces
    bake the mode in), and restores + clears on exit even on error."""
    global FORCE_SWEEP_MODE
    import jax

    prev = FORCE_SWEEP_MODE
    FORCE_SWEEP_MODE = mode
    jax.clear_caches()
    try:
        yield
    finally:
        FORCE_SWEEP_MODE = prev
        jax.clear_caches()


# None = read CTT_FLOOD_MODE; force_flood_mode() overrides within a scope
FORCE_FLOOD_MODE = None


def use_pallas_flood() -> bool:
    """Whether the per-slice flood should use the Pallas kernel
    (ops/pallas_flood.py).  Like ``use_assoc`` this is read at TRACE time —
    already-compiled shapes keep their path; pin the mode before first use
    (CTT_FLOOD_MODE=pallas) or flip it under ``force_flood_mode``, which owns
    the jit-cache invalidation."""
    if FORCE_FLOOD_MODE is not None:
        return FORCE_FLOOD_MODE == "pallas"
    return os.environ.get("CTT_FLOOD_MODE") == "pallas"


@contextmanager
def force_flood_mode(mode):
    """Scoped flood-mode override ('pallas' | 'xla'): sets the switch, clears
    jit caches (traces bake the path in), restores + clears on exit."""
    global FORCE_FLOOD_MODE
    import jax

    prev = FORCE_FLOOD_MODE
    FORCE_FLOOD_MODE = mode
    jax.clear_caches()
    try:
        yield
    finally:
        FORCE_FLOOD_MODE = prev
        jax.clear_caches()


# None = read CTT_CC_MODE; force_cc_mode() overrides within a scope
FORCE_CC_MODE = None


def use_pallas_cc() -> bool:
    """Whether volume CC should use the per-slice Pallas kernel + z-merge
    (ops/pallas_cc.py).  Read at TRACE time, like ``use_pallas_flood``."""
    if FORCE_CC_MODE is not None:
        return FORCE_CC_MODE == "pallas"
    return os.environ.get("CTT_CC_MODE") == "pallas"


@contextmanager
def force_cc_mode(mode):
    """Scoped CC-mode override ('pallas' | 'xla'): sets the switch, clears
    jit caches (traces bake the path in), restores + clears on exit."""
    global FORCE_CC_MODE
    import jax

    prev = FORCE_CC_MODE
    FORCE_CC_MODE = mode
    jax.clear_caches()
    try:
        yield
    finally:
        FORCE_CC_MODE = prev
        jax.clear_caches()


# None = read CTT_DTWS_MODE; force_dtws_mode() overrides within a scope
FORCE_DTWS_MODE = None


def use_pallas_dtws() -> bool:
    """Whether the per-slice DT-watershed should use the fused Pallas kernel
    (ops/pallas_dtws.py).  Read at TRACE time, like the other mode switches."""
    if FORCE_DTWS_MODE is not None:
        return FORCE_DTWS_MODE == "pallas"
    return os.environ.get("CTT_DTWS_MODE") == "pallas"


@contextmanager
def force_dtws_mode(mode):
    """Scoped DT-watershed-mode override ('pallas' | 'xla')."""
    global FORCE_DTWS_MODE
    import jax

    prev = FORCE_DTWS_MODE
    FORCE_DTWS_MODE = mode
    jax.clear_caches()
    try:
        yield
    finally:
        FORCE_DTWS_MODE = prev
        jax.clear_caches()
