"""Shared backend-mode switch for the log-depth sweep kernels.

The flood (ops/watershed.py) and connected-components (ops/cc.py) sweeps both
choose between ``lax.associative_scan`` (log-depth, full-array work — wins on
dispatch/latency-bound TPUs) and sequential carry chains (O(n) work — wins on
work-bound XLA-CPU).  One switch keeps the two kernels on the same path;
tools/tpu_validate.py measures both on real hardware.
"""

from __future__ import annotations

# None = pick by backend; tests/benchmarks override to "assoc" / "seq"
FORCE_SWEEP_MODE = None


def use_assoc() -> bool:
    if FORCE_SWEEP_MODE is not None:
        return FORCE_SWEEP_MODE == "assoc"
    import jax

    return jax.default_backend() != "cpu"
